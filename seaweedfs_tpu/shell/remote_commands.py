"""remote.* shell commands (weed/shell/command_remote_*.go).

remote.configure    — save named remote storage credentials
remote.mount        — map a filer directory to a remote bucket/path
remote.mount.buckets— mount every bucket of a remote
remote.meta.sync    — re-pull the remote listing into filer metadata
remote.cache        — pull object content into local chunks
remote.uncache      — drop local chunks, keep remote metadata
remote.unmount      — remove the mapping (and its imported metadata)
"""

from __future__ import annotations

import json

from ..remote_storage.client import RemoteConf, RemoteLocation, make_client
from ..remote_storage.mounts import (REMOTE_CONF_PATH, RemoteMounts,
                                     read_remote_conf, remote_key_for,
                                     sync_metadata, write_remote_conf)
from ..utils.httpd import HttpError, http_bytes, http_json
from .commands import CommandEnv, command
from .fs_commands import _filer, _listing


def _loc_parse(s: str) -> RemoteLocation:
    """conf_name/bucket/path/in/bucket"""
    parts = s.strip("/").split("/", 2)
    if not parts or not parts[0]:
        raise ValueError("remote location must be <conf>/<bucket>[/path]")
    return RemoteLocation(parts[0], parts[1] if len(parts) > 1 else "",
                          "/" + parts[2] if len(parts) > 2 else "/")


@command("remote.configure")
def cmd_remote_configure(env: CommandEnv, flags: dict) -> str:
    """remote.configure [-name n -type local|s3|azure|gcs|hdfs [-root /dir]
    [-endpoint host:port] [-accessKey k -secretKey s] | -delete -name n]
    # create/update/delete named remote storage configurations"""
    confs = read_remote_conf(_filer(env))
    name = flags.get("name", "")
    if not name:
        return json.dumps({n: c.to_dict() for n, c in confs.items()},
                          indent=2)
    env.confirm_is_locked()
    if "delete" in flags:
        if confs.pop(name, None) is None:
            return f"no remote configuration {name!r}"
        write_remote_conf(_filer(env), confs)
        return f"deleted remote configuration {name}"
    conf = RemoteConf(name=name, type=flags.get("type", "local"),
                      root=flags.get("root", ""),
                      endpoint=flags.get("endpoint", ""),
                      access_key=flags.get("accessKey", ""),
                      secret_key=flags.get("secretKey", ""))
    make_client(conf)  # validate type/SDK availability before saving
    confs[name] = conf
    write_remote_conf(_filer(env), confs)
    return f"configured remote {name} ({conf.type})"


@command("remote.mount")
def cmd_remote_mount(env: CommandEnv, flags: dict) -> str:
    """remote.mount -dir /buckets/b -remote conf/bucket[/path]
    # map a filer directory to remote storage and import its metadata"""
    env.confirm_is_locked()
    dir_path = flags["dir"]
    loc = _loc_parse(flags["remote"])
    confs = read_remote_conf(_filer(env))
    conf = confs.get(loc.conf_name)
    if conf is None:
        raise RuntimeError(f"unknown remote configuration {loc.conf_name!r};"
                           " run remote.configure first")
    client = make_client(conf)
    http_json("POST", f"http://{_filer(env)}/api/mkdir",
              {"path": dir_path}, timeout=30.0)
    mounts = RemoteMounts.read(_filer(env))
    mounts.mounts[dir_path] = loc
    mounts.write(_filer(env))
    n = sync_metadata(_filer(env), dir_path, loc, client)
    return f"mounted {flags['remote']} on {dir_path} ({n} entries)"


@command("remote.mount.buckets")
def cmd_remote_mount_buckets(env: CommandEnv, flags: dict) -> str:
    """remote.mount.buckets -remote conf [-bucketPattern *]
    # mount every bucket of a remote under /buckets/<name>"""
    env.confirm_is_locked()
    import fnmatch

    conf_name = flags["remote"].strip("/")
    confs = read_remote_conf(_filer(env))
    conf = confs.get(conf_name)
    if conf is None:
        raise RuntimeError(f"unknown remote configuration {conf_name!r}")
    client = make_client(conf)
    pattern = flags.get("bucketPattern", "*")
    out = []
    for bucket in client.list_buckets():
        if not fnmatch.fnmatch(bucket, pattern):
            continue
        out.append(cmd_remote_mount(env, {
            "dir": f"/buckets/{bucket}",
            "remote": f"{conf_name}/{bucket}"}))
    return "\n".join(out) or "no buckets matched"


@command("remote.meta.sync")
def cmd_remote_meta_sync(env: CommandEnv, flags: dict) -> str:
    """remote.meta.sync -dir /buckets/b  # re-pull the remote listing"""
    env.confirm_is_locked()
    dir_path = flags["dir"]
    mounts = RemoteMounts.read(_filer(env))
    loc = mounts.mounts.get(dir_path)
    if loc is None:
        raise RuntimeError(f"{dir_path} is not a remote mount")
    conf = read_remote_conf(_filer(env))[loc.conf_name]
    n = sync_metadata(_filer(env), dir_path, loc, make_client(conf))
    return f"synced {n} entries into {dir_path}"


def _walk_files(env: CommandEnv, path: str):
    for e in _listing(env, path):
        if e["IsDirectory"]:
            yield from _walk_files(env, e["FullPath"])
        else:
            yield e


@command("remote.cache")
def cmd_remote_cache(env: CommandEnv, flags: dict) -> str:
    """remote.cache -dir /buckets/b [-include *.pdf]
    # pull remote object content into local chunks"""
    env.confirm_is_locked()
    import fnmatch

    dir_path = flags["dir"]
    include = flags.get("include", "*")
    cached = 0
    for e in _walk_files(env, dir_path):
        name = e["FullPath"].rsplit("/", 1)[-1]
        if not fnmatch.fnmatch(name, include):
            continue
        if not e.get("Remote") or e.get("chunks"):
            continue
        # a plain GET triggers CacheRemoteObjectToLocalCluster
        status, body, _ = http_bytes(
            "GET", f"http://{_filer(env)}{e['FullPath']}", timeout=60.0)
        if status == 200:
            cached += 1
    return f"cached {cached} objects under {dir_path}"


@command("remote.uncache")
def cmd_remote_uncache(env: CommandEnv, flags: dict) -> str:
    """remote.uncache -dir /buckets/b [-include *.bin]
    # drop local chunk copies, keep remote metadata"""
    env.confirm_is_locked()
    import fnmatch

    dir_path = flags["dir"]
    include = flags.get("include", "*")
    n = 0
    for e in _walk_files(env, dir_path):
        name = e["FullPath"].rsplit("/", 1)[-1]
        if not fnmatch.fnmatch(name, include):
            continue
        if not e.get("Remote") or not e.get("chunks"):
            continue
        r = http_json("POST", f"http://{_filer(env)}/api/remote/uncache",
                      {"path": e["FullPath"]}, timeout=30.0)
        n += 1 if r.get("uncached") else 0
    return f"uncached {n} objects under {dir_path}"


@command("remote.unmount")
def cmd_remote_unmount(env: CommandEnv, flags: dict) -> str:
    """remote.unmount -dir /buckets/b
    # remove the mapping and the imported metadata tree"""
    env.confirm_is_locked()
    dir_path = flags["dir"]
    mounts = RemoteMounts.read(_filer(env))
    if dir_path not in mounts.mounts:
        raise RuntimeError(f"{dir_path} is not a remote mount")
    del mounts.mounts[dir_path]
    mounts.write(_filer(env))
    status, body, _ = http_bytes(
        "DELETE", f"http://{_filer(env)}{dir_path}?recursive=true",
            timeout=60.0)
    if status not in (200, 204, 404):
        raise HttpError(status, body.decode(errors="replace"))
    return f"unmounted {dir_path}"
