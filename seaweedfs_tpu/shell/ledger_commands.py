"""Resource-ledger shell commands (observability/ledger.py).

    cluster.top [-by route|client|server] [-top 20] [-json]

The cluster's `top(1)`: who is consuming which serving resource,
right now.  Reads the master's merged resource ledger
(GET /cluster/ledger) — decayed per-route-class / per-client-key
CPU, byte and queue-wait rates shipped by every server — and ranks
the chosen axis by CPU share.  The triage loop this exists for: a
loop_stall or queue-wait alert fires -> `cluster.top` names the route
(or client prefix) carrying the CPU -> the row's exemplar trace id
opens the request in trace.get -> the per-server profiler windows on
/cluster/ledger say WHICH stacks are rising.
"""

from __future__ import annotations

import json

from .commands import CommandEnv, command

_AXES = ("route", "client", "server")


def _ms(rate_s: float) -> str:
    """Seconds-per-second rate as ms/s (CPU and queue-wait columns)."""
    return f"{rate_s * 1000.0:.1f}"


def _kb(rate_b: float) -> str:
    return f"{rate_b / 1024.0:.1f}"


@command("cluster.top")
def cmd_cluster_top(env: CommandEnv, flags: dict) -> str:
    """cluster.top [-by route|client|server] [-top 20] [-json]
    # rank the cluster's serving cost by CPU share: per-route-class
    # (default), per-client /24 prefix, or per-server — merged from
    # every server's per-request resource ledger, with queue-wait,
    # byte and cache rates, loop-lag p99 and recent loop stalls"""
    by = str(flags.get("by") or "route")
    if by not in _AXES:
        raise ValueError(f"bad -by {by!r}: pick one of {'|'.join(_AXES)}")
    try:
        top = max(1, int(flags.get("top") or 20))
    except ValueError as e:
        raise ValueError(f"bad -top: {e}")
    doc = env.master_get(f"/cluster/ledger?top={top}")
    if flags.get("json") == "true":
        return json.dumps(doc, indent=2)
    totals = doc.get("totals") or {}
    lines: list[str] = []
    if by == "server":
        lines.append(f"{'server':<22} {'cpu%':>6} {'cpu ms/s':>9} "
                     f"{'req/s':>8} {'qwait ms/s':>11} "
                     f"{'loop p99 ms':>12} {'stalls':>6}")
        for row in (doc.get("servers") or [])[:top]:
            lines.append(
                f"{row['server']:<22} {row.get('cpu_share', 0.0):>6.1%} "
                f"{_ms(row.get('cpu_rate', 0.0)):>9} "
                f"{row.get('req_rate', 0.0):>8.2f} "
                f"{_ms(row.get('queue_wait_rate', 0.0)):>11} "
                f"{row.get('loop_lag_p99_ms', 0.0):>12.2f} "
                f"{row.get('stalls', 0):>6}")
    else:
        key = by
        rows = doc.get("routes" if by == "route" else "clients") or []
        lines.append(f"{key:<26} {'cpu%':>6} {'cpu ms/s':>9} "
                     f"{'req/s':>8} {'qwait ms/s':>11} {'in KB/s':>8} "
                     f"{'out KB/s':>9} {'hit/s':>7}  trace")
        for row in rows[:top]:
            lines.append(
                f"{row[key]:<26} {row.get('cpu_share', 0.0):>6.1%} "
                f"{_ms(row.get('cpu_rate', 0.0)):>9} "
                f"{row.get('req_rate', 0.0):>8.2f} "
                f"{_ms(row.get('queue_wait_rate', 0.0)):>11} "
                f"{_kb(row.get('bytes_in_rate', 0.0)):>8} "
                f"{_kb(row.get('bytes_out_rate', 0.0)):>9} "
                f"{row.get('cache_hit_rate', 0.0):>7.2f}  "
                f"{row.get('trace') or '-'}")
    if len(lines) == 1:
        lines.append("  (no ledger snapshots yet — servers ship "
                     "every ~1s; is -ledger.off set?)")
    lines.append(f"total: cpu {_ms(totals.get('cpu_rate', 0.0))} ms/s "
                 f"across {totals.get('req_rate', 0.0):g} req/s; "
                 f"{len(doc.get('peers') or {})} peers")
    stalls = doc.get("stalls") or []
    for ev in stalls[-3:]:
        d = ev.get("details") or {}
        lines.append(f"  loop_stall: server={ev.get('server')} "
                     f"route={d.get('route')} "
                     f"lag_ms={d.get('lag_ms')} "
                     f"trace={ev.get('trace') or '-'}")
    return "\n".join(lines)
