"""Observability shell commands: trace analysis + cluster telemetry.

    trace.analyze -server host:port       # analyze a live server's ring
    trace.analyze -file trace.json        # analyze a saved trace offline
    cluster.health                        # per-volume-server health rollup

trace.analyze turns a span ring into the attribution report
(observability/analysis.py): stage occupancy, the critical-path stage,
gap classification, and the clean-vs-degraded verdict — the answer the
next perf PR needs, without eyeballing raw span dumps.  -file accepts
either a Tracer.to_dict() document or the Chrome trace JSON written by
`bench.py --trace-out` / GET /debug/traces.
"""

from __future__ import annotations

import json
import time

from ..utils.httpd import http_bytes
from .commands import CommandEnv, command


@command("trace.analyze")
def cmd_trace_analyze(env: CommandEnv, flags: dict) -> str:
    """trace.analyze [-server host:port] [-file trace.json] [-json]
    # critical-path attribution report for a server's span ring (or a
    # saved trace file); -json emits the raw report document"""
    from ..observability.analysis import analyze, render_report

    path = flags.get("file") or ""
    server = flags.get("server") or ""
    if path:
        with open(path) as f:
            doc = json.load(f)
        report = analyze(doc)
    elif server:
        status, body, _ = http_bytes(
            "GET", f"http://{server}/debug/traces/analyze", timeout=60.0)
        if status != 200:
            raise RuntimeError(
                f"{server}/debug/traces/analyze: status {status}: "
                f"{body[:200].decode(errors='replace')}")
        report = json.loads(body)
    else:
        raise ValueError(
            "trace.analyze needs -server host:port or -file trace.json")
    if flags.get("json") == "true":
        return json.dumps(report, indent=2)
    return render_report(report).rstrip("\n")


@command("trace.fetch")
def cmd_trace_fetch(env: CommandEnv, flags: dict) -> str:
    """trace.fetch <trace_id> | -trace <trace_id> [-json]
    [-chrome [-out file.json]] | -list
    # fetch one stitched cluster trace from the master's collector and
    # render the cross-server analysis (per-hop occupancy, network-vs-
    # server split, bounding hop, degraded verdict); -chrome saves the
    # Chrome trace-event view instead; -list shows recent trace ids.
    # A bare `trace.fetch` defaults to the PREVIOUS command's trace id
    # (env.prev_trace_id — the repl prints it after each command), so
    # the "what did that command do across the cluster?" follow-up
    # needs no copy-paste."""
    if flags.get("list") == "true" or flags.get("") == "list":
        doc = env.master_get("/cluster/traces")
        lines = []
        for t in doc.get("traces", []):
            lines.append(f"{t['trace_id']}  root={t.get('root')} "
                         f"spans={t['span_count']} wall={t['wall_s']}s "
                         f"servers={','.join(t['servers'])}")
        return "\n".join(lines) or "no traces collected"
    trace_id = (flags.get("trace") or flags.get("")
                or getattr(env, "prev_trace_id", "") or "")
    if not trace_id:
        raise ValueError("trace.fetch needs a trace id — run a command "
                         "first, pass one, or -list recent ones")
    if flags.get("chrome") == "true":
        doc = env.master_get(f"/cluster/traces/{trace_id}?format=chrome")
        out = flags.get("out") or f"trace_{trace_id[:8]}.json"
        with open(out, "w") as f:
            json.dump(doc, f)
        return f"wrote {out} ({len(doc.get('traceEvents', []))} events)"
    doc = env.master_get(f"/cluster/traces/{trace_id}")
    if flags.get("json") == "true":
        return json.dumps(doc, indent=2)
    from ..observability.analysis import render_cluster_report

    return render_cluster_report(doc["analysis"]).rstrip("\n")


@command("cluster.health")
def cmd_cluster_health(env: CommandEnv, flags: dict) -> str:
    """cluster.health [-json]  # master's per-volume-server telemetry
    rollup: reachability/staleness + pipeline health counters"""
    doc = env.master_get("/cluster/health")
    if flags.get("json") == "true":
        return json.dumps(doc, indent=2)
    lines = [f"peers: {doc['peer_count']}  "
             f"degraded: {doc['degraded']}  "
             f"stale: {', '.join(doc['stale_peers']) or 'none'}"]
    # one-line alerting rollup (best-effort: an old master without the
    # engine must not break the health view)
    try:
        al = env.master_get("/cluster/alerts")
        firing = [a["name"] for a in al.get("alerts", [])
                  if a["state"] == "firing"]
        lines.append(f"alerts: {al.get('firing', 0)} firing"
                     + (f" ({', '.join(firing)})" if firing else ""))
    except Exception:
        pass
    # one-line capacity hint when a probe result is parked on the
    # master (weed shell capacity.probe / the bench capacity section);
    # best-effort — 404 just means nobody probed yet
    try:
        cap = env.master_get("/cluster/capacity")
        slo = cap.get("slo") or {}
        parts = [f"{route}~{res['capacity_rps']:g}rps"
                 for route, res in sorted((cap.get("routes") or {}).items())
                 if isinstance(res, dict) and res.get("capacity_rps")]
        if parts:
            age = int(time.time() - float(cap.get("posted_at")
                                          or cap.get("probed_at") or 0))
            lines.append(
                f"capacity: {' '.join(parts)} "
                f"(SLO p99<{slo.get('max_p99_ms', '?')}ms, "
                f"probed {age}s ago)")
    except Exception:
        pass
    t = doc["totals"]
    lines.append(f"totals: worker_restarts={t['worker_restarts']} "
                 f"engine_fallbacks={t['engine_fallbacks']} "
                 f"degraded_binds={t['degraded_binds']} "
                 f"scrub_unrepairable={t.get('scrub_unrepairable', 0)}")
    for url, p in sorted(doc["peers"].items()):
        ph = p["pipeline_health"]
        state = "up" if p["up"] else f"DOWN ({p.get('error', '')})"
        if p["stale"]:
            state += " STALE"
        line = (f"  {url}: {state} age={p.get('age_s')}s "
                f"restarts={ph['worker_restarts']} "
                f"fallbacks={ph['engine_fallbacks']} "
                f"degraded_binds={ph['degraded_binds']}")
        scrub = p.get("scrub")
        if scrub:
            verdicts = ",".join(f"{k}={v}" for k, v
                                in sorted(scrub["verdicts"].items())) \
                or "none"
            line += (f" scrub[running={scrub['running']} "
                     f"passes={scrub['passes']} {verdicts}]")
        lines.append(line)
    return "\n".join(lines)
