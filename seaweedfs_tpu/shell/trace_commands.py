"""Observability shell commands: trace analysis + cluster telemetry.

    trace.analyze -server host:port       # analyze a live server's ring
    trace.analyze -file trace.json        # analyze a saved trace offline
    cluster.health                        # per-volume-server health rollup
    cluster.raft                          # per-master raft quorum view

trace.analyze turns a span ring into the attribution report
(observability/analysis.py): stage occupancy, the critical-path stage,
gap classification, and the clean-vs-degraded verdict — the answer the
next perf PR needs, without eyeballing raw span dumps.  -file accepts
either a Tracer.to_dict() document or the Chrome trace JSON written by
`bench.py --trace-out` / GET /debug/traces.
"""

from __future__ import annotations

import json
import time

from ..utils.httpd import http_bytes, http_json
from .commands import CommandEnv, command


@command("trace.analyze")
def cmd_trace_analyze(env: CommandEnv, flags: dict) -> str:
    """trace.analyze [-server host:port] [-file trace.json] [-json]
    # critical-path attribution report for a server's span ring (or a
    # saved trace file); -json emits the raw report document"""
    from ..observability.analysis import analyze, render_report

    path = flags.get("file") or ""
    server = flags.get("server") or ""
    if path:
        with open(path) as f:
            doc = json.load(f)
        report = analyze(doc)
    elif server:
        status, body, _ = http_bytes(
            "GET", f"http://{server}/debug/traces/analyze", timeout=60.0)
        if status != 200:
            raise RuntimeError(
                f"{server}/debug/traces/analyze: status {status}: "
                f"{body[:200].decode(errors='replace')}")
        report = json.loads(body)
    else:
        raise ValueError(
            "trace.analyze needs -server host:port or -file trace.json")
    if flags.get("json") == "true":
        return json.dumps(report, indent=2)
    return render_report(report).rstrip("\n")


@command("trace.fetch")
def cmd_trace_fetch(env: CommandEnv, flags: dict) -> str:
    """trace.fetch <trace_id> | -trace <trace_id> [-json]
    [-chrome [-out file.json]] | -list
    # fetch one stitched cluster trace from the master's collector and
    # render the cross-server analysis (per-hop occupancy, network-vs-
    # server split, bounding hop, degraded verdict); -chrome saves the
    # Chrome trace-event view instead; -list shows recent trace ids.
    # A bare `trace.fetch` defaults to the PREVIOUS command's trace id
    # (env.prev_trace_id — the repl prints it after each command), so
    # the "what did that command do across the cluster?" follow-up
    # needs no copy-paste."""
    if flags.get("list") == "true" or flags.get("") == "list":
        doc = env.master_get("/cluster/traces")
        lines = []
        for t in doc.get("traces", []):
            lines.append(f"{t['trace_id']}  root={t.get('root')} "
                         f"spans={t['span_count']} wall={t['wall_s']}s "
                         f"servers={','.join(t['servers'])}")
        return "\n".join(lines) or "no traces collected"
    trace_id = (flags.get("trace") or flags.get("")
                or getattr(env, "prev_trace_id", "") or "")
    if not trace_id:
        raise ValueError("trace.fetch needs a trace id — run a command "
                         "first, pass one, or -list recent ones")
    if flags.get("chrome") == "true":
        doc = env.master_get(f"/cluster/traces/{trace_id}?format=chrome")
        out = flags.get("out") or f"trace_{trace_id[:8]}.json"
        with open(out, "w") as f:
            json.dump(doc, f)
        return f"wrote {out} ({len(doc.get('traceEvents', []))} events)"
    doc = env.master_get(f"/cluster/traces/{trace_id}")
    if flags.get("json") == "true":
        return json.dumps(doc, indent=2)
    from ..observability.analysis import render_cluster_report

    return render_cluster_report(doc["analysis"]).rstrip("\n")


def _raft_views(env: CommandEnv) -> dict[str, dict]:
    """/cluster/status from every reachable master: the configured
    candidates (env.master_url may be a comma-separated HA list) plus
    every peer any of them names.  Unreachable masters stay in the map
    with an `error` key — a failover drill wants to SEE the dead one."""
    queue = [u for u in env.master_url.split(",") if u]
    views: dict[str, dict] = {}
    while queue:
        url = queue.pop(0)
        if url in views:
            continue
        try:
            views[url] = http_json(
                "GET", f"http://{url}/cluster/status", timeout=5.0)
        except Exception as e:  # dead master: report, keep walking
            views[url] = {"error": str(e) or e.__class__.__name__}
            continue
        for p in views[url].get("Peers", []):
            if p not in views and p not in queue:
                queue.append(p)
    return views


def _quorum_line(views: dict[str, dict]) -> str:
    """`masters: 3 (leader host:port, term 7)` — the one-line quorum
    summary cluster.health and cluster.raft share."""
    up = {u: v for u, v in views.items() if "error" not in v}
    leaders = sorted({v.get("Leader") or "" for v in up.values()} - {""})
    term = max((int(v.get("Term") or 0) for v in up.values()), default=0)
    leader = leaders[0] if len(leaders) == 1 else \
        (f"DISPUTED {'/'.join(leaders)}" if leaders else "none")
    line = f"masters: {len(views)} (leader {leader}, term {term})"
    down = sorted(u for u, v in views.items() if "error" in v)
    if down:
        line += f"  down: {', '.join(down)}"
    return line


@command("cluster.raft")
def cmd_cluster_raft(env: CommandEnv, flags: dict) -> str:
    """cluster.raft [-json]  # per-master raft view: role, term, known
    leader, commit/applied indexes, log span, snapshot transfers —
    the quorum's replication progress at a glance"""
    views = _raft_views(env)
    if flags.get("json") == "true":
        return json.dumps({"masters": views}, indent=2)
    lines = [_quorum_line(views)]
    for url, v in sorted(views.items()):
        if "error" in v:
            lines.append(f"  {url}: unreachable ({v['error']})")
            continue
        lines.append(
            f"  {url}: {v.get('Role', '?')} term={v.get('Term', 0)} "
            f"commit={v.get('CommitIndex', 0)} "
            f"applied={v.get('LastApplied', 0)} "
            f"log[{v.get('LogFirstIndex', 1)}..{v.get('LastIndex', 0)}] "
            f"snap@{v.get('SnapshotIndex', 0)} "
            f"installed={v.get('SnapshotsInstalled', 0)} "
            f"sent={v.get('SnapshotsSent', 0)}")
    return "\n".join(lines)


@command("cluster.health")
def cmd_cluster_health(env: CommandEnv, flags: dict) -> str:
    """cluster.health [-json]  # master's per-volume-server telemetry
    rollup: reachability/staleness + pipeline health counters"""
    doc = env.master_get("/cluster/health")
    if flags.get("json") == "true":
        return json.dumps(doc, indent=2)
    lines = [f"peers: {doc['peer_count']}  "
             f"degraded: {doc['degraded']}  "
             f"stale: {', '.join(doc['stale_peers']) or 'none'}"]
    # one-line master-quorum rollup (best-effort: a single-master
    # deployment has no peers and still renders `masters: 1`)
    try:
        lines.append(_quorum_line(_raft_views(env)))
    except Exception:
        pass
    # one-line alerting rollup (best-effort: an old master without the
    # engine must not break the health view)
    try:
        al = env.master_get("/cluster/alerts")
        firing = [a["name"] for a in al.get("alerts", [])
                  if a["state"] == "firing"]
        lines.append(f"alerts: {al.get('firing', 0)} firing"
                     + (f" ({', '.join(firing)})" if firing else ""))
    except Exception:
        pass
    # one-line capacity hint when a probe result is parked on the
    # master (weed shell capacity.probe / the bench capacity section);
    # best-effort — 404 just means nobody probed yet
    try:
        cap = env.master_get("/cluster/capacity")
        slo = cap.get("slo") or {}
        parts = [f"{route}~{res['capacity_rps']:g}rps"
                 for route, res in sorted((cap.get("routes") or {}).items())
                 if isinstance(res, dict) and res.get("capacity_rps")]
        if parts:
            age = int(time.time() - float(cap.get("posted_at")
                                          or cap.get("probed_at") or 0))
            lines.append(
                f"capacity: {' '.join(parts)} "
                f"(SLO p99<{slo.get('max_p99_ms', '?')}ms, "
                f"probed {age}s ago)")
    except Exception:
        pass
    # one-line resource-ledger hint (best-effort): worst loop-lag p99
    # across the peers plus the route currently carrying the most CPU
    # — `cluster.top` is the drill-down
    try:
        led = env.master_get("/cluster/ledger?top=1")
        worst = max((s.get("loop_lag_p99_ms", 0.0)
                     for s in led.get("servers") or []), default=0.0)
        stalls = sum(s.get("stalls", 0)
                     for s in led.get("servers") or [])
        routes = led.get("routes") or []
        if routes:
            r = routes[0]
            lines.append(
                f"ledger: loop_lag_p99={worst:g}ms stalls={stalls} "
                f"top_route={r['route']} "
                f"({r.get('cpu_share', 0.0):.0%} cpu, "
                f"{r.get('req_rate', 0.0):g} req/s) — cluster.top")
    except Exception:
        pass
    t = doc["totals"]
    lines.append(f"totals: worker_restarts={t['worker_restarts']} "
                 f"engine_fallbacks={t['engine_fallbacks']} "
                 f"degraded_binds={t['degraded_binds']} "
                 f"scrub_unrepairable={t.get('scrub_unrepairable', 0)}")
    for url, p in sorted(doc["peers"].items()):
        ph = p["pipeline_health"]
        state = "up" if p["up"] else f"DOWN ({p.get('error', '')})"
        if p["stale"]:
            state += " STALE"
        line = (f"  {url}: {state} age={p.get('age_s')}s "
                f"restarts={ph['worker_restarts']} "
                f"fallbacks={ph['engine_fallbacks']} "
                f"degraded_binds={ph['degraded_binds']}")
        scrub = p.get("scrub")
        if scrub:
            verdicts = ",".join(f"{k}={v}" for k, v
                                in sorted(scrub["verdicts"].items())) \
                or "none"
            line += (f" scrub[running={scrub['running']} "
                     f"passes={scrub['passes']} {verdicts}]")
        lines.append(line)
    return "\n".join(lines)
