"""Workload recording, replay & capacity shell commands.

    workload.record [-sample 1.0] [-size 8192] [-seed N]  # start, cluster-wide
    workload.stop                                         # stop everywhere
    workload.export [-out recording.json] [-route r]      # save the recording
    workload.replay [-file recording.json] [-speed 2] [-duration s] [-json]
    capacity.probe [-routes http_read,native_read] [-p99 5] [-step 2]

workload.record fans POST /debug/reqlog/start to the master and every
heartbeat-registered volume server (the recorder is per-process; the
shippers stream sampled records to the master's /cluster/workload
journal continuously).  workload.export saves the master's recording
document; workload.replay fits it into a ScenarioSpec
(scenarios/replay.spec_from_recording) and drives it with the scenario
engine — alerting live, open-loop paced at recorded (or -speed scaled)
rate — then prints the verdict AND the replay-fidelity checks.

capacity.probe runs the SLO capacity search (scenarios/capacity.py)
against the connected cluster and posts the result to the master
(POST /cluster/capacity), where cluster.health picks it up as a
one-line hint.  The probe WRITES load objects and drives the cluster
to its knee — hold the admin lock.
"""

from __future__ import annotations

import json
import time

from ..utils.httpd import http_json
from .commands import CommandEnv, command


def _all_servers(env: CommandEnv) -> list[str]:
    """Every server whose recorder a cluster-wide record/stop must
    reach: the master, every heartbeat-registered volume server, and
    the connected filer (filers are not in /dir/status topology — a
    fan-out built from it alone would silently omit the whole filer
    workload from the recording; further filers need -server)."""
    targets = [env.master_url]
    topo = env.topology()
    for dc in topo.get("DataCenters", []):
        for rack in dc.get("Racks", []):
            for n in rack.get("DataNodes", []):
                targets.append(n["Url"])
    if env.filer_url:
        targets.append(env.filer_url)
    return targets


@command("workload.record")
def cmd_workload_record(env: CommandEnv, flags: dict) -> str:
    """workload.record [-sample 1.0] [-size 8192] [-seed N]
    [-include_ops] [-server host:port]
    # start the workload flight recorder on the master, every
    # registered volume server, and the connected filer (or one
    # -server); sampled, redacted access records stream to the
    # master's /cluster/workload journal"""
    body: dict = {"reset": True}
    try:
        if flags.get("sample"):
            body["sample"] = float(flags["sample"])
        if flags.get("size"):
            body["size"] = int(flags["size"])
        if flags.get("seed"):
            body["seed"] = int(flags["seed"])
    except ValueError as e:
        raise ValueError(f"bad -sample/-size/-seed: {e}")
    if flags.get("include_ops") == "true":
        body["include_ops"] = True
    targets = [flags["server"]] if flags.get("server") \
        else _all_servers(env)
    lines = []
    for url in targets:
        try:
            st = http_json("POST", f"http://{url}/debug/reqlog/start",
                           body, timeout=15.0)
            lines.append(f"{url}: recording sample={st['sample']:g} "
                         f"capacity={st['capacity']}")
        except Exception as e:
            lines.append(f"{url}: start failed: "
                         f"{type(e).__name__}: {e}")
    return "\n".join(lines)


@command("workload.stop")
def cmd_workload_stop(env: CommandEnv, flags: dict) -> str:
    """workload.stop [-server host:port]
    # stop recording (rings keep their records for export)"""
    targets = [flags["server"]] if flags.get("server") \
        else _all_servers(env)
    lines = []
    for url in targets:
        try:
            st = http_json("POST", f"http://{url}/debug/reqlog/stop",
                           {}, timeout=15.0)
            lines.append(f"{url}: stopped "
                         f"(recorded={st['recorded']} "
                         f"dropped={st['dropped']})")
        except Exception as e:
            lines.append(f"{url}: stop failed: "
                         f"{type(e).__name__}: {e}")
    return "\n".join(lines)


@command("workload.export")
def cmd_workload_export(env: CommandEnv, flags: dict) -> str:
    """workload.export [-out recording.json] [-route r] [-since ts]
    # save the master's merged workload recording (the replayable
    # document); prints the per-route summary"""
    params = []
    if flags.get("route"):
        params.append(f"route={flags['route']}")
    if flags.get("since"):
        params.append(f"since={flags['since']}")
    qs = ("?" + "&".join(params)) if params else ""
    doc = env.master_get(f"/cluster/workload/export{qs}")
    out = flags.get("out") or f"recording_{int(time.time())}.json"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    s = doc.get("summary") or {}
    lines = [f"wrote {out}: {s.get('records', 0)} records over "
             f"{s.get('window_s', 0)}s (dropped={doc.get('dropped', 0)})"]
    for route, row in sorted((s.get("routes") or {}).items()):
        lines.append(f"  {route:<14} ops={row['ops']} "
                     f"errors={row['errors']} "
                     f"in={row['bytes_in']} out={row['bytes_out']}")
    return "\n".join(lines)


@command("workload.profile")
def cmd_workload_profile(env: CommandEnv, flags: dict) -> str:
    """workload.profile [-file recording.json] [-route r] [-json]
    # fit the recorded workload's measured shape LIVE (the
    # recording_profile document spec_from_recording fits from):
    # mix fractions, observed rps, size buckets, and the Zipf skew —
    # cross-checked against the heat plane's own live fit
    # (/cluster/heat) when heat snapshots are flowing"""
    from ..scenarios.replay import recording_profile

    if flags.get("file"):
        with open(flags["file"], encoding="utf-8") as f:
            recording = json.load(f)
    else:
        qs = f"?route={flags['route']}" if flags.get("route") else ""
        recording = env.master_get(f"/cluster/workload/export{qs}")
    profile = recording_profile(recording)
    heat_zipf = None
    try:
        heat = env.master_get("/cluster/heat?top=1")
        z = heat.get("zipf") or {}
        if z.get("distinct", 0) >= 3:
            heat_zipf = z
    except Exception:
        pass  # heat plane off or no snapshots yet: profile still prints
    if flags.get("json") == "true":
        doc = dict(profile)
        if heat_zipf is not None:
            doc["heat_zipf"] = heat_zipf
        return json.dumps(doc, indent=2)
    lines = [
        f"records={profile['records']} over {profile['window_s']}s "
        f"(observed_rps={profile['observed_rps']:g})",
        f"mix: read={profile['read_fraction']:g} "
        f"churn={profile['churn_fraction']:g} "
        f"submit={profile['submit_fraction']:g}",
        f"popularity: zipf_s={profile['zipf_s']:g} over "
        f"{profile['distinct_keys']} distinct keys",
        "sizes: " + ", ".join(f"{b}B x{w:g}"
                              for b, w in profile["sizes"]),
        f"deadline_p50_s={profile['deadline_p50_s']:g}",
    ]
    if heat_zipf is not None:
        lines.append(f"heat plane agrees: live zipf_s="
                     f"{heat_zipf.get('s', 0.0):g} over "
                     f"{heat_zipf.get('distinct', 0)} needles "
                     f"(/cluster/heat)")
    for key, count in profile["top_keys"][:5]:
        lines.append(f"  top key {key}: {count} reads")
    return "\n".join(lines)


@command("workload.replay")
def cmd_workload_replay(env: CommandEnv, flags: dict) -> str:
    """workload.replay [-file recording.json] [-speed 1.0]
    [-duration s] [-clients 8] [-against host:port] [-json]
    # fit a recording (a -file, or the master's current journal) into
    # a ScenarioSpec and replay it with the scenario engine — fresh
    # in-process cluster, alerting live, open-loop paced.  Prints the
    # scenario verdict and the machine-checked replay-fidelity list.
    # -against drives the recorded workload at a LIVE cluster's master
    # instead of spawning one (writes load objects; hold the admin
    # lock) — how a recorded workload proves a refactor on real
    # before/after servers"""
    from ..scenarios import run_against, run_scenario
    from ..scenarios.replay import replay_fidelity, spec_from_recording

    if flags.get("file"):
        with open(flags["file"], encoding="utf-8") as f:
            recording = json.load(f)
    else:
        recording = env.master_get("/cluster/workload/export")
    try:
        speed = float(flags.get("speed") or 1.0)
        duration = float(flags["duration"]) if flags.get("duration") \
            else None
        clients = int(flags.get("clients") or 8)
    except ValueError as e:
        raise ValueError(f"bad -speed/-duration/-clients: {e}")
    spec = spec_from_recording(recording, speed=speed,
                               duration_s=duration, clients=clients)
    against = (flags.get("against") or "").strip()
    if against:
        # replaying INTO a live cluster mutates it (hot-set preload +
        # recorded write mix): same admin-lock bar as capacity.probe
        env.confirm_is_locked()
        result = run_against(spec, against)
    else:
        result = run_scenario(spec)
    fidelity = replay_fidelity(recording, spec, result=result)
    result["fidelity"] = fidelity
    if flags.get("json") == "true":
        return json.dumps(result, indent=2)
    where = f" against {against}" if against else ""
    lines = [f"replayed {spec.name}{where}: "
             f"verdict={result['verdict']} "
             f"({result['total_ops']} ops over {result['wall_s']}s, "
             f"target_rps={spec.target_rps:g})"]
    for c in result.get("checks", []) + fidelity:
        mark = "ok " if c["ok"] else "FAIL"
        lines.append(f"  {mark} {c['check']}: value={c['value']} "
                     f"bound={c['bound']}")
    return "\n".join(lines)


@command("capacity.probe")
def cmd_capacity_probe(env: CommandEnv, flags: dict) -> str:
    """capacity.probe [-routes http_read,native_read,http_write]
    [-p99 5.0] [-errors 0.001] [-start 100] [-max 50000] [-step 2.0]
    [-json]
    # binary-search the max sustainable rps per route class under the
    # SLO against the LIVE cluster (writes load objects; drives the
    # cluster to its knee — hold the admin lock), then post the result
    # to the master so cluster.health can hint at it"""
    from ..scenarios.capacity import (CapacitySLO, probe_cluster,
                                      render_capacity)

    env.confirm_is_locked()
    routes = tuple(s.strip() for s in
                   (flags.get("routes")
                    or "http_read,native_read,http_write").split(",")
                   if s.strip())
    try:
        slo = CapacitySLO(
            max_p99_ms=float(flags.get("p99") or 5.0),
            max_error_ratio=float(flags.get("errors") or 0.001))
        start = float(flags.get("start") or 100.0)
        max_rps = float(flags.get("max") or 50000.0)
        step_s = float(flags.get("step") or 2.0)
    except ValueError as e:
        raise ValueError(f"bad probe knobs: {e}")
    doc = probe_cluster(env.master_url, routes=routes, slo=slo,
                        start_rps=start, max_rps=max_rps, step_s=step_s)
    for res in doc["routes"].values():
        res.pop("samples", None)
    try:
        env.master_post("/cluster/capacity", doc)
        posted = "posted to master /cluster/capacity"
    except Exception as e:
        posted = f"post to master failed: {type(e).__name__}: {e}"
    if flags.get("json") == "true":
        return json.dumps(doc, indent=2)
    return render_capacity(doc) + f"\n{posted}"
