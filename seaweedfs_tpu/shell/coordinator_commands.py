"""Rebuild/rebalance coordinator shell commands.

    coordinator.status [-json]   # queue, budget, recent actions
    coordinator.pause            # hold autonomous plans (survives locks)
    coordinator.resume

The shell's admin `lock` already pauses the coordinator implicitly (no
dueling migrations); pause/resume is the explicit operator hold that
outlives a lock session.  Output is stable line-per-record text like
alerts.list, so scripts can grep it; -json emits the raw document.
"""

from __future__ import annotations

import json
import time

from .commands import CommandEnv, command


def _fmt_ts(ts: float) -> str:
    return time.strftime("%H:%M:%S", time.localtime(ts)) if ts else "-"


def _render_status(doc: dict) -> str:
    state = "paused" if doc.get("paused") else (
        "running" if doc.get("enabled") else "disabled")
    reason = doc.get("pause_reason") or ""
    head = (f"coordinator: {state}"
            + (f" ({reason})" if reason else "")
            + f"  cycles={doc.get('cycles', 0)}"
            f" last={_fmt_ts(doc.get('last_cycle_at', 0))}"
            f" under_replicated={doc.get('under_replicated', 0)}")
    lines = [head]
    rep = doc.get("repairs") or {}
    budget = doc.get("move_budget") or {}
    lines.append(f"  repairs: done={rep.get('done', 0)} "
                 f"failed={rep.get('failed', 0)}  "
                 f"moves={doc.get('moves', 0)} "
                 f"(budget {budget.get('tokens', 0)}/"
                 f"{budget.get('burst', 0)} tokens, "
                 f"{budget.get('rate_per_s', 0)}/s)")
    if doc.get("last_error"):
        lines.append(f"  last_error: {doc['last_error']}")
    for q in doc.get("queue", []):
        lines.append(
            f"  queued volume {q.get('vid')}: clean={q.get('clean')}"
            f" deficit={q.get('deficit')}"
            + (" CRITICAL" if q.get("critical") else "")
            + (f" alert={q['alert']}" if q.get("alert") else "")
            + (f" [trace {q['cause_trace']}]"
               if q.get("cause_trace") else ""))
    for a in list(doc.get("recent", []))[:10]:
        extra = {k: v for k, v in a.items()
                 if k not in ("at", "action") and v not in ("", [], None)}
        detail = " ".join(f"{k}={v}" for k, v in sorted(extra.items()))
        lines.append(f"  {_fmt_ts(a.get('at', 0))} {a.get('action'):<14}"
                     f" {detail}")
    return "\n".join(lines)


@command("coordinator.status")
def cmd_coordinator_status(env: CommandEnv, flags: dict) -> str:
    """coordinator.status [-json]
    # the autonomous EC rebuild/rebalance coordinator's state: repair
    # queue (clean-shard deficit, causing alert + trace id), repair and
    # move totals, token-bucket budget, recent actions"""
    doc = env.master_get("/cluster/coordinator")
    if flags.get("json") == "true":
        return json.dumps(doc, indent=2)
    return _render_status(doc)


@command("coordinator.pause")
def cmd_coordinator_pause(env: CommandEnv, flags: dict) -> str:
    """coordinator.pause
    # hold all autonomous repair/rebalance plans until resume (the
    # admin lock pauses implicitly; this survives unlock)"""
    doc = env.master_post("/cluster/coordinator/pause", {})
    return _render_status(doc)


@command("coordinator.resume")
def cmd_coordinator_resume(env: CommandEnv, flags: dict) -> str:
    """coordinator.resume
    # lift a coordinator.pause hold and wake the planner"""
    doc = env.master_post("/cluster/coordinator/resume", {})
    return _render_status(doc)
