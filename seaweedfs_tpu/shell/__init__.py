"""Admin shell package — importing registers all commands."""

from . import alert_commands as alert_commands  # noqa: F401
from . import autoscale_commands as autoscale_commands  # noqa: F401
from . import commands as commands  # noqa: F401
from . import coordinator_commands as coordinator_commands  # noqa: F401
from . import ec_commands as ec_commands  # noqa: F401
from . import fs_commands as fs_commands  # noqa: F401
from . import heat_commands as heat_commands  # noqa: F401
from . import ledger_commands as ledger_commands  # noqa: F401
from . import remote_commands as remote_commands  # noqa: F401
from . import s3_commands as s3_commands  # noqa: F401
from . import trace_commands as trace_commands  # noqa: F401
from . import volume_commands as volume_commands  # noqa: F401
from . import workload_commands as workload_commands  # noqa: F401
from .commands import COMMANDS, CommandEnv, repl, run_command  # noqa: F401
