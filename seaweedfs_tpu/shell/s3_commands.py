"""s3.* shell commands (weed/shell/command_s3_*.go).

Buckets are filer directories under /buckets; identities live in the
in-FS config file /etc/seaweedfs/identity.json that the S3 gateways
hot-reload (command_s3_configure.go edits the same stored config in the
reference).  All commands talk to the filer over its HTTP API.
"""

from __future__ import annotations

import json
import time
import urllib.parse

from ..gateway.s3_auth import IDENTITY_PATH
from ..utils.httpd import HttpError, http_bytes, http_json
from .commands import CommandEnv, command
from .fs_commands import _filer, _listing

BUCKETS_PATH = "/buckets"
UPLOADS_PATH = "/buckets/.uploads"


def _read_identities(env: CommandEnv) -> dict:
    status, body, _ = http_bytes(
        "GET", f"http://{_filer(env)}{IDENTITY_PATH}")
    if status != 200:
        return {"identities": []}
    return json.loads(body)


def _write_identities(env: CommandEnv, config: dict) -> None:
    status, body, _ = http_bytes(
        "PUT", f"http://{_filer(env)}{IDENTITY_PATH}",
        json.dumps(config, indent=2).encode(),
        headers={"Content-Type": "application/json"})
    if status not in (200, 201):
        raise HttpError(status, body.decode(errors="replace"))


@command("s3.bucket.list")
def cmd_s3_bucket_list(env: CommandEnv, flags: dict) -> str:
    """s3.bucket.list  # list all buckets"""
    try:
        entries = _listing(env, BUCKETS_PATH)
    except HttpError:
        return ""
    return "\n".join(e["FullPath"].rsplit("/", 1)[-1] for e in entries
                     if e["IsDirectory"]
                     and not e["FullPath"].rsplit("/", 1)[-1].startswith("."))


@command("s3.bucket.create")
def cmd_s3_bucket_create(env: CommandEnv, flags: dict) -> str:
    """s3.bucket.create -name <bucket>"""
    name = flags.get("name") or flags.get("")
    if not name:
        raise ValueError("usage: s3.bucket.create -name <bucket>")
    env.confirm_is_locked()
    http_json("POST", f"http://{_filer(env)}/api/mkdir",
              {"path": f"{BUCKETS_PATH}/{name}"})
    return f"created bucket {name}"


@command("s3.bucket.delete")
def cmd_s3_bucket_delete(env: CommandEnv, flags: dict) -> str:
    """s3.bucket.delete -name <bucket>  # removes the bucket and its objects"""
    name = flags.get("name") or flags.get("")
    if not name:
        raise ValueError("usage: s3.bucket.delete -name <bucket>")
    env.confirm_is_locked()
    status, body, _ = http_bytes(
        "DELETE", f"http://{_filer(env)}{BUCKETS_PATH}/{name}?recursive=true")
    if status not in (204, 200):
        raise HttpError(status, body.decode(errors="replace"))
    return f"deleted bucket {name}"


@command("s3.clean.uploads")
def cmd_s3_clean_uploads(env: CommandEnv, flags: dict) -> str:
    """s3.clean.uploads -timeAgo 24h  # abort stale multipart uploads"""
    age = _parse_duration(flags.get("timeAgo", "24h"))
    cutoff = time.time() - age
    try:
        uploads = _listing(env, UPLOADS_PATH)
    except (HttpError, NotADirectoryError):
        return "no stale uploads"
    doomed = [u for u in uploads if u.get("Mtime", 0) < cutoff]
    for u in doomed:
        path = u["FullPath"]
        http_bytes("DELETE", f"http://{_filer(env)}{path}?recursive=true")
    return f"removed {len(doomed)} stale multipart uploads"


@command("s3.configure")
def cmd_s3_configure(env: CommandEnv, flags: dict) -> str:
    """s3.configure -user <name> [-access_key k -secret_key s]
    [-actions Read,Write:bucket] [-delete] [-apply]
    # edit the S3 identity table; without -apply, prints the result"""
    config = _read_identities(env)
    identities = config.setdefault("identities", [])
    user = flags.get("user", "")
    if user:
        ident = next((i for i in identities if i.get("name") == user), None)
        if "delete" in flags:
            if ident is not None:
                identities.remove(ident)
        else:
            if ident is None:
                ident = {"name": user, "credentials": [], "actions": []}
                identities.append(ident)
            if flags.get("access_key") and flags.get("secret_key"):
                creds = [c for c in ident["credentials"]
                         if c["accessKey"] != flags["access_key"]]
                creds.append({"accessKey": flags["access_key"],
                              "secretKey": flags["secret_key"]})
                ident["credentials"] = creds
            if flags.get("actions"):
                ident["actions"] = flags["actions"].split(",")
    if "apply" in flags:
        env.confirm_is_locked()
        _write_identities(env, config)
        return f"applied: {len(identities)} identities"
    return json.dumps(config, indent=2)


def _parse_duration(s: str) -> float:
    units = {"s": 1, "m": 60, "h": 3600, "d": 86400}
    if s and s[-1] in units:
        return float(s[:-1]) * units[s[-1]]
    return float(s)
