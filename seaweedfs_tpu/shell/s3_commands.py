"""s3.* shell commands (weed/shell/command_s3_*.go).

Buckets are filer directories under /buckets; identities live in the
in-FS config file /etc/seaweedfs/identity.json that the S3 gateways
hot-reload (command_s3_configure.go edits the same stored config in the
reference).  All commands talk to the filer over its HTTP API.
"""

from __future__ import annotations

import json
import time
import urllib.parse

from ..gateway.s3_auth import IDENTITY_PATH
from ..utils.httpd import HttpError, http_bytes, http_json
from .commands import CommandEnv, command
from .fs_commands import _filer, _listing

BUCKETS_PATH = "/buckets"
UPLOADS_PATH = "/buckets/.uploads"


def _read_json_conf(env: CommandEnv, path: str, default):
    from ..utils.jsonconf import read_json_conf

    return read_json_conf(_filer(env), path, default)


def _write_json_conf(env: CommandEnv, path: str, config) -> None:
    from ..utils.jsonconf import write_json_conf

    write_json_conf(_filer(env), path, config)


def _read_identities(env: CommandEnv) -> dict:
    return _read_json_conf(env, IDENTITY_PATH, {"identities": []})


def _write_identities(env: CommandEnv, config: dict) -> None:
    _write_json_conf(env, IDENTITY_PATH, config)


@command("s3.bucket.list")
def cmd_s3_bucket_list(env: CommandEnv, flags: dict) -> str:
    """s3.bucket.list  # list all buckets"""
    try:
        entries = _listing(env, BUCKETS_PATH)
    except HttpError:
        return ""
    return "\n".join(e["FullPath"].rsplit("/", 1)[-1] for e in entries
                     if e["IsDirectory"]
                     and not e["FullPath"].rsplit("/", 1)[-1].startswith("."))


@command("s3.bucket.create")
def cmd_s3_bucket_create(env: CommandEnv, flags: dict) -> str:
    """s3.bucket.create -name <bucket>"""
    name = flags.get("name") or flags.get("")
    if not name:
        raise ValueError("usage: s3.bucket.create -name <bucket>")
    env.confirm_is_locked()
    http_json("POST", f"http://{_filer(env)}/api/mkdir",
              {"path": f"{BUCKETS_PATH}/{name}"}, timeout=30.0)
    return f"created bucket {name}"


@command("s3.bucket.delete")
def cmd_s3_bucket_delete(env: CommandEnv, flags: dict) -> str:
    """s3.bucket.delete -name <bucket>  # removes the bucket and its objects"""
    name = flags.get("name") or flags.get("")
    if not name:
        raise ValueError("usage: s3.bucket.delete -name <bucket>")
    env.confirm_is_locked()
    status, body, _ = http_bytes(
        "DELETE", f"http://{_filer(env)}{BUCKETS_PATH}/{name}?recursive=true",
            timeout=60.0)
    if status not in (204, 200):
        raise HttpError(status, body.decode(errors="replace"))
    return f"deleted bucket {name}"


@command("s3.clean.uploads")
def cmd_s3_clean_uploads(env: CommandEnv, flags: dict) -> str:
    """s3.clean.uploads -timeAgo 24h  # abort stale multipart uploads"""
    age = _parse_duration(flags.get("timeAgo", "24h"))
    cutoff = time.time() - age
    try:
        uploads = _listing(env, UPLOADS_PATH)
    except (HttpError, NotADirectoryError):
        return "no stale uploads"
    doomed = [u for u in uploads if u.get("Mtime", 0) < cutoff]
    for u in doomed:
        path = u["FullPath"]
        http_bytes("DELETE", f"http://{_filer(env)}{path}?recursive=true",
            timeout=60.0)
    return f"removed {len(doomed)} stale multipart uploads"


@command("s3.configure")
def cmd_s3_configure(env: CommandEnv, flags: dict) -> str:
    """s3.configure -user <name> [-access_key k -secret_key s]
    [-actions Read,Write:bucket] [-delete] [-apply]
    # edit the S3 identity table; without -apply, prints the result"""
    config = _read_identities(env)
    identities = config.setdefault("identities", [])
    user = flags.get("user", "")
    if user:
        ident = next((i for i in identities if i.get("name") == user), None)
        if "delete" in flags:
            if ident is not None:
                identities.remove(ident)
        else:
            if ident is None:
                ident = {"name": user, "credentials": [], "actions": []}
                identities.append(ident)
            if flags.get("access_key") and flags.get("secret_key"):
                creds = [c for c in ident["credentials"]
                         if c["accessKey"] != flags["access_key"]]
                creds.append({"accessKey": flags["access_key"],
                              "secretKey": flags["secret_key"]})
                ident["credentials"] = creds
            if flags.get("actions"):
                ident["actions"] = flags["actions"].split(",")
    if "apply" in flags:
        env.confirm_is_locked()
        _write_identities(env, config)
        return f"applied: {len(identities)} identities"
    return json.dumps(config, indent=2)


def _parse_duration(s: str) -> float:
    units = {"s": 1, "m": 60, "h": 3600, "d": 86400}
    if s and s[-1] in units:
        return float(s[:-1]) * units[s[-1]]
    return float(s)


QUOTA_PATH = "/etc/seaweedfs/bucket_quotas.json"


def _read_quota_conf(env: CommandEnv) -> dict:
    d = _read_json_conf(env, QUOTA_PATH, {})
    # layout: {"quotas": {bucket: bytes}, "marked": [bucket...]} —
    # "marked" records which read-only rules WE set, so quota.check
    # never lifts an operator's manual rule
    if "quotas" not in d:
        d = {"quotas": d, "marked": []}
    return d


def _write_quota_conf(env: CommandEnv, conf: dict) -> None:
    _write_json_conf(env, QUOTA_PATH, conf)


def _bucket_size(env: CommandEnv, name: str) -> int:
    def walk(p: str) -> int:
        size = 0
        for e in _listing(env, p):
            size += walk(e["FullPath"]) if e["IsDirectory"] \
                else e["FileSize"]
        return size

    return walk(f"{BUCKETS_PATH}/{name}")


@command("s3.bucket.quota")
def cmd_s3_bucket_quota(env: CommandEnv, flags: dict) -> str:
    """s3.bucket.quota -name <bucket> [-sizeMB <n> | -remove]
    # set/show/remove a bucket size quota (command_s3_bucket_quota.go)"""
    name = flags.get("name") or flags.get("")
    qc = _read_quota_conf(env)
    quotas = qc["quotas"]
    if not name:
        return json.dumps(quotas, indent=2) or "{}"
    if "remove" in flags:
        env.confirm_is_locked()
        quotas.pop(name, None)
        out = [f"removed quota of bucket {name}"]
        if name in qc.get("marked", []):
            # lift the read-only mark we set, or the bucket stays frozen
            # with no quota to ever clear it
            from ..filer.filer_conf import FILER_CONF_PATH, FilerConf

            status, body, _ = http_bytes(
                "GET", f"http://{_filer(env)}{FILER_CONF_PATH}", timeout=60.0)
            conf = FilerConf.from_bytes(body if status == 200 else b"")
            prefix = f"{BUCKETS_PATH}/{name}"
            rule = conf.rules.get(prefix)
            if rule is not None and rule.read_only:
                conf.delete_rule(prefix)
                _write_json_conf(env, FILER_CONF_PATH,
                                 json.loads(conf.to_bytes()))
            qc["marked"] = [m for m in qc["marked"] if m != name]
            out.append(f"lifted read-only on {prefix}")
        _write_quota_conf(env, qc)
        return "\n".join(out)
    if "sizeMB" in flags:
        env.confirm_is_locked()
        quotas[name] = int(flags["sizeMB"]) * 1024 * 1024
        _write_quota_conf(env, qc)
        return f"bucket {name} quota = {flags['sizeMB']}MB"
    return f"bucket {name} quota = {quotas.get(name, 'none')}"


@command("s3.bucket.quota.enforce")
@command("s3.bucket.quota.check")
def cmd_s3_bucket_quota_check(env: CommandEnv, flags: dict) -> str:
    """s3.bucket.quota.check [-apply]
    # compare bucket sizes against quotas; with -apply, mark exceeded
    buckets read-only via a filer.conf rule (and lift the mark when back
    under quota) — the s3 gateway then rejects writes (command_s3_bucket_
    quota_check.go marks the bucket entry; same effect here)"""
    from ..filer.filer_conf import FILER_CONF_PATH, FilerConf, PathConf

    qc = _read_quota_conf(env)
    quotas = qc["quotas"]
    if not quotas:
        return "no bucket quotas configured"
    status, body, _ = http_bytes(
        "GET", f"http://{_filer(env)}{FILER_CONF_PATH}", timeout=60.0)
    conf = FilerConf.from_bytes(body if status == 200 else b"")
    lines, changed = [], False
    marked_by_us = set(qc.get("marked", []))
    for name, limit in sorted(quotas.items()):
        prefix = f"{BUCKETS_PATH}/{name}"
        try:
            used = _bucket_size(env, name)
        except (HttpError, NotADirectoryError):
            # bucket gone but quota entry remains: skip, keep enforcing
            # the others
            lines.append(f"bucket {name}: missing (stale quota entry)")
            continue
        over = used > limit
        marked = prefix in conf.rules and conf.rules[prefix].read_only
        lines.append(f"bucket {name}: used={used} quota={limit} "
                     f"{'OVER' if over else 'ok'}"
                     f"{' (read-only)' if marked else ''}")
        if "apply" in flags and over and not marked:
            env.confirm_is_locked()
            rule = conf.rules.get(prefix) or PathConf(location_prefix=prefix)
            rule.read_only = True
            conf.set_rule(rule)
            marked_by_us.add(name)
            lines.append(f"  -> marked {prefix} read-only")
            changed = True
        elif "apply" in flags and not over and marked:
            # only lift marks WE set — an operator's manual read-only
            # rule must survive quota checks
            if name not in marked_by_us:
                lines.append(f"  (read-only set manually; not lifting)")
                continue
            env.confirm_is_locked()
            rule = conf.rules[prefix]
            rule.read_only = False
            if rule.to_dict() == PathConf(
                    location_prefix=prefix).to_dict():
                conf.delete_rule(prefix)  # nothing else set: drop it
            marked_by_us.discard(name)
            lines.append(f"  -> lifted read-only on {prefix}")
            changed = True
    if changed:
        qc["marked"] = sorted(marked_by_us)
        _write_quota_conf(env, qc)
        status, body, _ = http_bytes(
            "PUT", f"http://{_filer(env)}{FILER_CONF_PATH}",
            conf.to_bytes(), timeout=60.0)
        if status not in (200, 201):
            raise HttpError(status, body.decode(errors="replace"))
    return "\n".join(lines)
