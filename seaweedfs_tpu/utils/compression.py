"""Gzip compression helpers for the upload/read path.

Equivalent of weed/util/compression.go: MaybeGzipData's 10/9 win rule,
gzip magic sniffing, and the IsCompressableFileType ext/mime table that
decides whether an upload is worth compressing.
"""

from __future__ import annotations

import gzip
import io
import zlib

_COMPRESS_EXT = {
    ".svg", ".bmp", ".wav", ".pdf", ".txt", ".html", ".htm", ".css",
    ".js", ".json", ".php", ".java", ".go", ".rb", ".c", ".cpp",
    ".h", ".hpp",
}
_NO_COMPRESS_EXT = {
    ".zip", ".rar", ".gz", ".bz2", ".xz", ".zst", ".br",
    ".png", ".jpg", ".jpeg",
}
_WAV_MIMES = {"wave", "wav", "x-wav", "x-pn-wav"}


def is_gzipped_content(data: bytes) -> bool:
    return len(data) >= 2 and data[0] == 31 and data[1] == 139


def gzip_data(data: bytes) -> bytes:
    buf = io.BytesIO()
    # fixed mtime=0 so identical input -> identical stored bytes
    with gzip.GzipFile(fileobj=buf, mode="wb", mtime=0) as f:
        f.write(data)
    return buf.getvalue()


def ungzip_data(data: bytes) -> bytes:
    return gzip.decompress(data)


def maybe_gzip_data(data: bytes) -> bytes:
    """Gzip unless already gzipped or the win is under 10% (MaybeGzipData,
    compression.go:16-28)."""
    if is_gzipped_content(data):
        return data
    try:
        gzipped = gzip_data(data)
    except OSError:  # pragma: no cover
        return data
    if len(gzipped) * 10 > len(data) * 9:
        return data
    return gzipped


def maybe_decompress_data(data: bytes) -> bytes:
    if is_gzipped_content(data):
        try:
            return ungzip_data(data)
        except (OSError, EOFError, zlib.error):
            # gzip raises BadGzipFile (an OSError) for bad headers but
            # EOFError for truncation and zlib.error for corrupt deflate
            # bodies — all three mean "not really gzip, serve raw"
            return data
    return data


def is_compressable_file_type(ext: str, mtype: str) -> tuple[bool, bool]:
    """(should_be_compressed, i_am_sure) — IsCompressableFileType,
    compression.go:102-155."""
    ext = ext.lower()
    if mtype.startswith("text/"):
        return True, True
    if ext in (".svg", ".bmp", ".wav"):
        return True, True
    if mtype.startswith("image/"):
        return False, True
    if ext in _NO_COMPRESS_EXT:
        return False, True
    if ext in _COMPRESS_EXT:
        return True, True
    if mtype.startswith("application/"):
        if mtype.endswith("zstd") or mtype.endswith("vnd.rar"):
            return False, True
        if mtype.endswith("xml") or mtype.endswith("script"):
            return True, True
    if mtype.startswith("audio/"):
        if mtype[len("audio/"):] in _WAV_MIMES:
            return True, True
    return False, False
