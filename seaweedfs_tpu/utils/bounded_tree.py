"""Bounded tree of visited directories.

Equivalent of weed/util/bounded_tree/: the mount meta cache remembers
which directories have been fully listed; the node count is bounded and
least-recently-visited subtrees are forgotten first (they just re-list
on next access).
"""

from __future__ import annotations

import threading
from collections import OrderedDict


class BoundedTree:
    def __init__(self, limit: int = 100_000):
        self.limit = limit
        self._lock = threading.Lock()
        self._visited: OrderedDict[str, None] = OrderedDict()

    def mark_visited(self, path: str) -> None:
        with self._lock:
            self._visited.pop(path, None)
            self._visited[path] = None
            while len(self._visited) > self.limit:
                self._visited.popitem(last=False)

    def has_visited(self, path: str) -> bool:
        with self._lock:
            if path in self._visited:
                self._visited.move_to_end(path)
                return True
            return False

    def ensure_invalidated(self, path: str) -> None:
        """Drop a subtree: the path and everything below it."""
        with self._lock:
            doomed = [p for p in self._visited
                      if p == path or p.startswith(path.rstrip("/") + "/")]
            for p in doomed:
                del self._visited[p]
