"""Graceful-shutdown hooks + profiling setup.

Equivalent of weed/util/grace/signal_handling.go:17-39 (ordered shutdown
callbacks on SIGINT/SIGTERM) and pprof.go:11 (CPU/mem profiles behind
flags — here cProfile/tracemalloc).
"""

from __future__ import annotations

import atexit
import signal
import threading
from typing import Callable

_hooks: list[Callable[[], None]] = []
_lock = threading.Lock()
_installed = False


def on_interrupt(hook: Callable[[], None]) -> None:
    """Register a shutdown hook; hooks run LIFO like the reference's list."""
    global _installed
    with _lock:
        _hooks.append(hook)
        if not _installed:
            _installed = True
            try:
                signal.signal(signal.SIGTERM, _run_hooks_and_exit)
                signal.signal(signal.SIGINT, _run_hooks_and_exit)
            except ValueError:
                pass  # not the main thread (tests) — atexit still covers us
            atexit.register(_run_hooks)


def _run_hooks(*_args) -> None:
    with _lock:
        hooks, _hooks[:] = _hooks[::-1], []
    for h in hooks:
        try:
            h()
        except Exception:
            pass


def _run_hooks_and_exit(signum, _frame) -> None:
    _run_hooks()
    raise SystemExit(128 + signum)


_profiler = None


def setup_profiling(cpu_profile: str = "", mem_profile: str = "") -> None:
    """grace/pprof.go: start CPU profiling now, dump at exit."""
    global _profiler
    if cpu_profile:
        import cProfile

        _profiler = cProfile.Profile()
        _profiler.enable()

        def dump_cpu():
            _profiler.disable()
            _profiler.dump_stats(cpu_profile)

        on_interrupt(dump_cpu)
    if mem_profile:
        import tracemalloc

        tracemalloc.start()

        def dump_mem():
            snap = tracemalloc.take_snapshot()
            snap.dump(mem_profile)

        on_interrupt(dump_mem)
