"""Shared framed-TCP RPC plumbing for the raw-TCP planes.

One frame shape serves every TCP front end (volume needle IO, master
assign):

  request:  op(1) | key_len(u16) | key utf8 | body_len(u32) | body
  response: status(1, 0=ok)      | payload_len(u32) | payload

FramedServer runs an accept loop with a thread per connection and calls
`handler(op, key, body) -> payload`; any exception becomes a status-1
frame with the message, and the connection survives.  FramedClient keeps
one TCP_NODELAY connection per (thread, address) with a single retry on
stale reuse.
"""

from __future__ import annotations

import socket
import struct
import threading
import time as _time
from typing import Callable, Optional

from ..observability import context as _trace_context
from ..observability import get_tracer as _get_tracer
from ..observability import reqlog as _reqlog
from . import deadline as _deadline

# the process-global workload recorder (observability/reqlog.py): the
# framed ingress reads ONE attribute per frame while recording is off
_RECORDER = _reqlog.get_recorder()

TCP_PORT_OFFSET = 20000
U16 = struct.Struct(">H")
U32 = struct.Struct(">I")


def tcp_port_for(http_port: int) -> int:
    """http port + 20000, wrapping DOWN when that leaves the valid range
    (test servers sit on high ephemeral ports)."""
    p = http_port + TCP_PORT_OFFSET
    return p if p <= 65535 else http_port - TCP_PORT_OFFSET


def tcp_address(http_url: str) -> str:
    """host:port -> host:tcp_port_for(port), the address convention."""
    host, _, port = http_url.partition(":")
    return f"{host}:{tcp_port_for(int(port))}"


def pack_fid_frames(items, with_data: bool) -> bytes:
    """Encode the shared batch record stream: ``u16 fid_len | fid``
    (+ ``u32 data_len | data`` when with_data) repeated.  One encoder
    for every producer — the HTTP /batch/write body, the framed 'B'/'P'
    ops, and both client builders."""
    out = []
    for item in items:
        fid = item[0] if with_data else item
        f = fid.encode()
        out.append(U16.pack(len(f)) + f)
        if with_data:
            data = item[1]
            out.append(U32.pack(len(data)))
            out.append(data)
    return b"".join(out)


def unpack_fid_frames(body: bytes, with_data: bool) -> list:
    """Decode pack_fid_frames; raises ValueError on ANY truncation so
    a torn batch is rejected whole before a single record is acted on.
    Returns [fid] or [(fid, data)]."""
    out: list = []
    i = 0
    n = len(body)
    while i < n:
        if i + 2 > n:
            raise ValueError("truncated batch frame")
        flen = U16.unpack_from(body, i)[0]
        i += 2
        if i + flen > n:
            raise ValueError("truncated batch frame")
        fid = body[i:i + flen].decode(errors="replace")
        i += flen
        if not with_data:
            out.append(fid)
            continue
        if i + 4 > n:
            raise ValueError("truncated batch frame")
        dlen = U32.unpack_from(body, i)[0]
        i += 4
        if i + dlen > n:
            raise ValueError("truncated batch frame")
        out.append((fid, body[i:i + dlen]))
        i += dlen
    return out


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        piece = sock.recv(n - len(buf))
        if not piece:
            raise ConnectionError("peer closed")
        buf += piece
    return bytes(buf)


def serve_frame(handler: Callable[[bytes, str, bytes], bytes],
                name: str, op: bytes, key: str, body: bytes,
                peer: str = "", send=None, ledger=None,
                queue_wait_s: float = 0.0) -> bytes:
    """Serve ONE framed op through the native plane's ingress
    chokepoint — trace mint, deadline-slot hygiene, and the workload
    recorder all happen here, so the thread-per-connection server and
    the reactor dataplane share exactly one copy of the contract.
    Returns the complete response frame (status + length + payload);
    exceptions become a status-1 frame and never escape.

    `send` (the threaded path passes conn.sendall) transmits the frame
    INSIDE the recording window, keeping the recorded duration's
    the-send-is-the-work semantics for synchronous transports; the
    reactor passes None and enqueues the returned frame (its writeback
    is asynchronous, so transmission time is not attributable to one
    op).

    `ledger` (observability/ledger.py RequestLedger, or None) settles
    this op's thread-CPU / bytes / queue-wait into the native plane's
    cost tables; `queue_wait_s` is the reactor's parse-to-worker
    handoff wait (the threaded path runs inline and passes 0)."""
    t_frame0 = _time.perf_counter() if _RECORDER.enabled else 0.0
    # resource-ledger entry stamp: ON the executing thread (worker or
    # per-conn thread), same per-thread-CPU-clock rule as dispatch
    ltok = ledger.begin() if ledger is not None else None
    # trace ingress for the headerless native plane: frames have no
    # Traceparent slot, so every framed op is its own head-based
    # sampling decision (rate-gated), minted fresh — the cross-server
    # propagation story stays an HTTP-plane concern
    tracer = _get_tracer()
    prev_ctx = sampled = None
    traced = False
    if tracer.enabled:
        sampled, prev_ctx = _trace_context.begin_request(None)
        traced = True
    # deadline hygiene for the headerless plane: frames carry no
    # X-Weed-Deadline slot, so each op runs budget-free — but the slot
    # must be CLEARED (and restored), or a pooled thread would leak a
    # previous request's budget into this frame
    _ddl, _prev_ddl = _deadline.begin_request(None)
    frame_status, out_len = 200, 0
    try:
        try:
            # gate on the sampled decision: the hot framed path must
            # not build span names for unsampled ops
            if sampled is not None:
                with tracer.span(f"tcp.{name}",
                                 op=op.decode("latin-1"), key=key):
                    payload = handler(op, key, body)
            else:
                payload = handler(op, key, body)
            out_len = len(payload)
            frame = b"\x00" + U32.pack(len(payload)) + payload
        except Exception as e:  # noqa: BLE001 - conn must survive
            frame_status = 500
            msg = f"{type(e).__name__}: {e}".encode()[:65536]
            out_len = len(msg)
            frame = b"\x01" + U32.pack(len(msg)) + msg
        if send is not None:
            send(frame)
    finally:
        _deadline.end_request(_prev_ddl)
        if traced:
            _trace_context.end_request(prev_ctx)
        if _RECORDER.enabled and t_frame0:
            # workload flight recorder (observability/reqlog.py): the
            # native plane's half of the access record stream.  Frames
            # carry no query strings, so the key needs no redaction;
            # the route class comes from the op byte.
            try:
                _RECORDER.record(
                    _reqlog.NATIVE_ROUTES.get(
                        op, f"native_{op.decode('latin-1')}"),
                    "TCP", "/" + key, frame_status,
                    bytes_in=len(body), bytes_out=out_len,
                    duration_ms=(_time.perf_counter() - t_frame0) * 1e3,
                    peer=peer, handler=name)
            except Exception:
                pass  # recording never breaks the plane
        if ledger is not None:
            # resource ledger settle: the native plane's half of the
            # cost stream (route class from the op byte, client key
            # from the peer)
            try:
                ledger.settle_native(
                    ltok, op, frame_status, len(body), out_len, peer,
                    sampled.trace_id if sampled is not None else "",
                    queue_wait_s)
            except Exception:
                pass  # accounting never breaks the plane
    return frame


class FramedServer:
    def __init__(self, handler: Callable[[bytes, str, bytes], bytes],
                 host: str = "127.0.0.1", port: int = 0,
                 whitelist_ok: Optional[Callable[[str], bool]] = None,
                 name: str = "framed"):
        self.handler = handler
        self.host, self.port = host, port
        self._whitelist_ok = whitelist_ok
        self.name = name
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._reactor = None
        # optional resource ledger (observability/ledger.py): the
        # owning server installs its RequestLedger so framed ops settle
        # cost like HTTP dispatches do — both the threaded per-conn
        # loop and the reactor (via listener.owner) read it from here
        self.ledger = None

    @property
    def alive(self) -> bool:
        return self._sock is not None

    def start(self) -> "FramedServer":
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)  # weedlint: disable=W502 lifecycle handoff: written on the start() thread before the accept thread exists
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._sock.bind((self.host, self.port))
        except OSError:
            # conventional port taken (ephemeral-port test clusters can
            # collide): the HTTP plane still serves everything
            self._sock.close()
            self._sock = None  # weedlint: disable=W502 lifecycle handoff: bind failed, no accept thread was ever started
            return self
        self._sock.listen(64)
        from . import eventloop

        if eventloop.reactor_enabled():
            # the shared dataplane owns accept + readiness; frames
            # dispatch onto its bounded pool through serve_frame (the
            # same ingress chokepoint the threaded path runs)
            self._reactor = eventloop.get_reactor()  # weedlint: disable=W502 lifecycle handoff: written on the start() thread before any conn exists
            self._reactor.add_framed_listener(self._sock, self.handler,
                                              self.name, self)
            return self
        threading.Thread(target=self._accept_loop, daemon=True,
                         name=f"{self.name}:{self.port}").start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._reactor is not None:
            self._reactor.remove_listener(self)
            self._reactor = None  # weedlint: disable=W502 lifecycle teardown: runs after remove_listener drained the loop side
            self._sock = None  # weedlint: disable=W502 lifecycle teardown: the reactor closed the listener socket
            return
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return  # listener closed
            if self._whitelist_ok is not None and \
                    not self._whitelist_ok(addr[0]):
                conn.close()
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name=f"{self.name}-conn:{addr[1]}").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            peer = ""
            try:
                peer = conn.getpeername()[0]
            except OSError:
                pass
            while not self._stop.is_set():
                try:
                    op = recv_exact(conn, 1)
                except ConnectionError:
                    return
                key_len = U16.unpack(recv_exact(conn, 2))[0]
                key = recv_exact(conn, key_len).decode()
                body_len = U32.unpack(recv_exact(conn, 4))[0]
                body = recv_exact(conn, body_len) if body_len else b""
                try:
                    serve_frame(self.handler, self.name, op, key, body,
                                peer, send=conn.sendall,
                                ledger=self.ledger)
                except OSError:
                    return  # peer went away mid-send: drop the conn
        finally:
            conn.close()


class FramedClient(threading.local):
    """Per-thread persistent framed-TCP connections, one per server."""

    def __init__(self):
        self._conns: dict[str, socket.socket] = {}

    def _conn(self, addr: str,
              timeout: float = 30.0) -> socket.socket:
        sock = self._conns.get(addr)
        if sock is None:
            host, _, port = addr.partition(":")
            # the CONNECT timeout is the caller's clamped budget too: a
            # SYN-blackholed peer must not pin a budgeted caller for a
            # fixed 30s when its deadline allows 2
            sock = socket.create_connection((host, int(port)),
                                            timeout=timeout)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns[addr] = sock
        return sock

    def _drop(self, addr: str) -> None:
        sock = self._conns.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def request(self, addr: str, op: bytes, key: str,
                body: bytes = b"") -> bytes:
        """One framed op; retries once on a stale pooled connection.
        The egress is deadline-aware (the per-op socket timeout is
        clamped to the caller's remaining budget) and rides the same
        peer-scoped net.* fault points as the pooled HTTP client."""
        from . import faultinject as fi

        if fi._points:
            fi.hit_peer("net.partition", addr)
            fi.hit_peer("net.drop", addr)
            _net_delay = fi.peer_delay("net.delay", addr)
            if _net_delay:
                _deadline.sleep_within(_net_delay)
        op_timeout = _deadline.clamp(30.0)
        key_b = key.encode()
        frame = (op + U16.pack(len(key_b)) + key_b
                 + U32.pack(len(body)) + body)
        for attempt in (0, 1):
            reused = addr in self._conns
            sock = self._conn(addr, op_timeout)
            try:
                sock.settimeout(op_timeout)
                sock.sendall(frame)
                status = recv_exact(sock, 1)
                n = U32.unpack(recv_exact(sock, 4))[0]
                payload = recv_exact(sock, n) if n else b""
            except (ConnectionError, OSError):
                self._drop(addr)
                ddl = _deadline.current()
                if ddl is not None and ddl.expired():
                    # the budget was the binding constraint, not the
                    # wire: surface it as such (callers answer 504)
                    raise _deadline.DeadlineExceeded(
                        f"deadline exceeded awaiting {addr}") from None
                if not reused:
                    raise
                continue
            if status != b"\x00":
                raise OSError(payload.decode(errors="replace"))
            return payload
