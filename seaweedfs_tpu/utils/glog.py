"""Leveled logging in the style of the reference's vendored glog.

Equivalent of weed/glog: `V(level)` gates verbose logs on the process-wide
verbosity (set by the -v flag, weed/weed.go:46 wires MaxSize etc.);
Infof/Warningf/Errorf always emit. Output goes through the stdlib logging
root so tests can capture it and services can add file rotation handlers.
"""

from __future__ import annotations

import logging
import sys
import threading

_logger = logging.getLogger("weed")
_verbosity = 0
_lock = threading.Lock()


def init(verbosity: int = 0, to_stderr: bool = True) -> None:
    global _verbosity
    _verbosity = verbosity
    if to_stderr and not _logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(levelname).1s%(asctime)s %(threadName)s %(message)s",
            datefmt="%m%d %H:%M:%S"))
        _logger.addHandler(h)
        _logger.setLevel(logging.DEBUG)


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


class _V:
    """glog.V(n).Infof(...) — emits only when n <= verbosity."""

    def __init__(self, enabled: bool):
        self.enabled = enabled

    def infof(self, fmt: str, *args) -> None:
        if self.enabled:
            _logger.info(fmt % args if args else fmt)


def V(level: int) -> _V:  # noqa: N802 — matches glog.V
    return _V(level <= _verbosity)


def infof(fmt: str, *args) -> None:
    _logger.info(fmt % args if args else fmt)


def warningf(fmt: str, *args) -> None:
    _logger.warning(fmt % args if args else fmt)


def errorf(fmt: str, *args) -> None:
    _logger.error(fmt % args if args else fmt)


def fatalf(fmt: str, *args) -> None:
    _logger.critical(fmt % args if args else fmt)
    raise SystemExit(255)
