"""Leveled logging in the style of the reference's vendored glog.

Equivalent of weed/glog: `V(level)` gates verbose logs on the process-wide
verbosity (set by the -v flag, weed/weed.go:46 wires MaxSize etc.);
Infof/Warningf/Errorf always emit. Output goes through the stdlib logging
root so tests can capture it and services can add file rotation handlers.

Log lines emitted while the calling thread holds a SAMPLED
distributed-trace decision (observability/context.py) are prefixed with
`[trace <id>]`, so a grep of stderr joins the stitched cluster trace the
master collected for the same operation.  Off the sampled path the cost
is one thread-local read per emitted record — and records are only
formatted when actually logged.
"""

from __future__ import annotations

import logging
import sys
import threading

_logger = logging.getLogger("weed")
_verbosity = 0
_lock = threading.Lock()

# lazily bound observability.context.current_sampled (None = not yet
# tried, False = import failed — stripped-down deployments keep logging)
_current_sampled = None


def _trace_prefix_filter(record: logging.LogRecord) -> bool:
    """Handler filter: stamp `record.trace` with `[trace <id>] ` when
    the emitting thread's trace-context decision is sampled."""
    global _current_sampled
    if _current_sampled is None:
        try:
            from ..observability.context import current_sampled
            _current_sampled = current_sampled
        except Exception:
            _current_sampled = False
    ctx = _current_sampled() if _current_sampled else None
    record.trace = f"[trace {ctx.trace_id}] " if ctx is not None else ""
    return True


def init(verbosity: int = 0, to_stderr: bool = True,
         level: int = logging.DEBUG) -> None:
    """`level` gates the stdlib logger (a service embedding this can run
    at WARNING without touching verbosity, which only gates V(n))."""
    global _verbosity
    _verbosity = verbosity
    if to_stderr and not _logger.handlers:
        h = logging.StreamHandler(sys.stderr)
        h.setFormatter(logging.Formatter(
            "%(levelname).1s%(asctime)s %(threadName)s %(trace)s%(message)s",
            datefmt="%m%d %H:%M:%S"))
        h.addFilter(_trace_prefix_filter)
        _logger.addHandler(h)
    _logger.setLevel(level)


def set_verbosity(v: int) -> None:
    global _verbosity
    _verbosity = v


class _V:
    """glog.V(n).Infof(...) — emits only when n <= verbosity.  Carries
    the full warning/error surface: `V(n).warningf(...)` call sites must
    gate on verbosity exactly like infof, not crash."""

    def __init__(self, enabled: bool):
        self.enabled = enabled

    def infof(self, fmt: str, *args) -> None:
        if self.enabled:
            _logger.info(fmt % args if args else fmt)

    def warningf(self, fmt: str, *args) -> None:
        if self.enabled:
            _logger.warning(fmt % args if args else fmt)

    def errorf(self, fmt: str, *args) -> None:
        if self.enabled:
            _logger.error(fmt % args if args else fmt)


def V(level: int) -> _V:  # noqa: N802 — matches glog.V
    return _V(level <= _verbosity)


def infof(fmt: str, *args) -> None:
    _logger.info(fmt % args if args else fmt)


def warningf(fmt: str, *args) -> None:
    _logger.warning(fmt % args if args else fmt)


def errorf(fmt: str, *args) -> None:
    _logger.error(fmt % args if args else fmt)


def fatalf(fmt: str, *args) -> None:
    _logger.critical(fmt % args if args else fmt)
    raise SystemExit(255)
