"""TOML configuration loader with env-var overrides.

Equivalent of weed/util/config.go:20-47 (viper): look for <name>.toml in
".", "~/.seaweedfs", "/etc/seaweedfs" (first hit wins), then let
WEED_<SECTION>_<KEY> environment variables override file values — the same
convention the reference's docker compose files rely on.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional

try:  # stdlib since 3.11
    import tomllib
except ModuleNotFoundError:  # pragma: no cover - version-dependent
    try:
        import tomli as tomllib  # type: ignore[no-redef]
    except ModuleNotFoundError:
        tomllib = None  # fall back to the minimal parser below

SEARCH_DIRS = [".", os.path.expanduser("~/.seaweedfs"), "/etc/seaweedfs"]


class Configuration:
    def __init__(self, data: Optional[dict] = None, source: str = ""):
        self.data = data or {}
        self.source = source

    def get(self, dotted_key: str, default: Any = None) -> Any:
        """viper-style lookup: 'jwt.signing.key' walks nested tables, and a
        WEED_JWT_SIGNING_KEY env var overrides whatever the file says."""
        env = "WEED_" + dotted_key.upper().replace(".", "_").replace("-", "_")
        if env in os.environ:
            return _coerce(os.environ[env], default)
        node: Any = self.data
        for part in dotted_key.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_string(self, key: str, default: str = "") -> str:
        return str(self.get(key, default))

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        return v if isinstance(v, bool) else str(v).lower() in ("1", "true", "yes")

    def get_int(self, key: str, default: int = 0) -> int:
        try:
            return int(self.get(key, default))
        except (TypeError, ValueError):
            return default


def _coerce(value: str, default: Any) -> Any:
    if isinstance(default, bool):
        return value.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        try:
            return int(value)
        except ValueError:
            return default
    return value


_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\"}


def _unescape(s: str) -> str:
    """Single left-to-right scan (chained str.replace misorders \\\\n)."""
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append(_ESCAPES.get(s[i + 1], "\\" + s[i + 1]))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _split_dotted(key: str) -> list[str]:
    """Split a [table] header on dots OUTSIDE quotes: [sink.local] nests,
    but ["sink.local"] is ONE flat key (what load_sink consumes)."""
    parts: list[str] = []
    cur: list[str] = []
    quote = ""
    for ch in key:
        if quote:
            if ch == quote:
                quote = ""
            else:
                cur.append(ch)
        elif ch in ('"', "'"):
            quote = ch
        elif ch == ".":
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur).strip())
    return parts


def _parse_toml_minimal(text: str) -> dict:
    """Fallback TOML-subset parser for interpreters without tomllib
    (stdlib only appeared in 3.11): [a.b] tables, string/int/float/bool
    scalars and flat arrays — exactly the shapes the reference's
    security.toml / notification.toml files use."""
    root: dict = {}
    node = root

    def value(tok: str) -> Any:
        tok = tok.strip()
        if tok.startswith("[") and tok.endswith("]"):
            inner = tok[1:-1].strip()
            return [value(t) for t in
                    re.findall(r'"[^"]*"|\'[^\']*\'|[^,\s]+', inner)] \
                if inner else []
        if tok.startswith('"') and tok.endswith('"'):
            return _unescape(tok[1:-1])  # basic string: honor escapes
        if tok.startswith("'") and tok.endswith("'"):
            return tok[1:-1]  # literal string: no escapes in TOML
        if tok in ("true", "false"):
            return tok == "true"
        try:
            return int(tok)
        except ValueError:
            try:
                return float(tok)
            except ValueError:
                return tok

    def strip_comment(line: str) -> str:
        # cut at the first '#' OUTSIDE quotes — a '#' inside a quoted
        # value (e.g. a signing secret) is data, not a comment.  Inside
        # basic (double-quoted) strings a backslash escapes the next
        # char, so \" must not read as the closing quote.
        quote = ""
        i, n = 0, len(line)
        while i < n:
            ch = line[i]
            if quote:
                if ch == "\\" and quote == '"':
                    i += 2
                    continue
                if ch == quote:
                    quote = ""
            elif ch in ('"', "'"):
                quote = ch
            elif ch == "#":
                return line[:i]
            i += 1
        return line

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = strip_comment(raw).strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            node = root
            for part in _split_dotted(line[1:-1].strip()):
                node = node.setdefault(part, {})
            continue
        if "=" in line:
            k, _, v = line.partition("=")
            v = v.strip()
            if v == "[" or (v.startswith("[") and not v.endswith("]")):
                # multi-line arrays are beyond this subset: refuse loudly
                # rather than feed a silently-truncated config downstream
                raise ValueError(
                    f"minimal TOML parser: multi-line array at line "
                    f"{lineno} unsupported (install tomli or use a "
                    f"single-line array)")
            node[k.strip().strip('"').strip("'")] = value(v)
            continue
        # neither table header nor key=value: refusing keeps the
        # fallback honest where stdlib tomllib would parse or raise
        raise ValueError(
            f"minimal TOML parser: unsupported syntax at line "
            f"{lineno}: {line[:60]!r}")
    return root


def load_toml(path: str) -> dict:
    """Parse one TOML file with whatever this interpreter has: stdlib
    tomllib (3.11+), tomli, or the minimal fallback parser."""
    if tomllib is not None:
        with open(path, "rb") as f:
            return tomllib.load(f)
    with open(path, encoding="utf-8") as f:
        return _parse_toml_minimal(f.read())


def load_configuration(name: str, required: bool = False,
                       search_dirs: Optional[list[str]] = None) -> Configuration:
    """util/config.go LoadConfiguration: <name>.toml from the search path."""
    for d in (search_dirs if search_dirs is not None else SEARCH_DIRS):
        path = os.path.join(d, f"{name}.toml")
        if os.path.isfile(path):
            return Configuration(load_toml(path), source=path)
    if required:
        raise FileNotFoundError(
            f"{name}.toml not found in {search_dirs or SEARCH_DIRS}")
    return Configuration()
