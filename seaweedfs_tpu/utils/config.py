"""TOML configuration loader with env-var overrides.

Equivalent of weed/util/config.go:20-47 (viper): look for <name>.toml in
".", "~/.seaweedfs", "/etc/seaweedfs" (first hit wins), then let
WEED_<SECTION>_<KEY> environment variables override file values — the same
convention the reference's docker compose files rely on.
"""

from __future__ import annotations

import os
import tomllib
from typing import Any, Optional

SEARCH_DIRS = [".", os.path.expanduser("~/.seaweedfs"), "/etc/seaweedfs"]


class Configuration:
    def __init__(self, data: Optional[dict] = None, source: str = ""):
        self.data = data or {}
        self.source = source

    def get(self, dotted_key: str, default: Any = None) -> Any:
        """viper-style lookup: 'jwt.signing.key' walks nested tables, and a
        WEED_JWT_SIGNING_KEY env var overrides whatever the file says."""
        env = "WEED_" + dotted_key.upper().replace(".", "_").replace("-", "_")
        if env in os.environ:
            return _coerce(os.environ[env], default)
        node: Any = self.data
        for part in dotted_key.split("."):
            if not isinstance(node, dict) or part not in node:
                return default
            node = node[part]
        return node

    def get_string(self, key: str, default: str = "") -> str:
        return str(self.get(key, default))

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key, default)
        return v if isinstance(v, bool) else str(v).lower() in ("1", "true", "yes")

    def get_int(self, key: str, default: int = 0) -> int:
        try:
            return int(self.get(key, default))
        except (TypeError, ValueError):
            return default


def _coerce(value: str, default: Any) -> Any:
    if isinstance(default, bool):
        return value.lower() in ("1", "true", "yes")
    if isinstance(default, int):
        try:
            return int(value)
        except ValueError:
            return default
    return value


def load_configuration(name: str, required: bool = False,
                       search_dirs: Optional[list[str]] = None) -> Configuration:
    """util/config.go LoadConfiguration: <name>.toml from the search path."""
    for d in (search_dirs if search_dirs is not None else SEARCH_DIRS):
        path = os.path.join(d, f"{name}.toml")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                return Configuration(tomllib.load(f), source=path)
    if required:
        raise FileNotFoundError(
            f"{name}.toml not found in {search_dirs or SEARCH_DIRS}")
    return Configuration()
