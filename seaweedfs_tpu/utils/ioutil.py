"""Shared low-level IO helpers."""

from __future__ import annotations

import os

import numpy as np


def pread_padded(f, length: int, offset: int) -> np.ndarray:
    """Read `length` bytes at `offset` from file object `f`, zero-padding past
    EOF (the EC tail-block rule, ec_encoder.go:172-176)."""
    buf = os.pread(f.fileno(), length, offset)
    arr = np.zeros(length, dtype=np.uint8)
    if buf:
        arr[: len(buf)] = np.frombuffer(buf, dtype=np.uint8)
    return arr


def preadv_into(f, views: list, offset: int) -> None:
    """Scatter one contiguous file span at `offset` directly into `views`
    (writable buffers, consumed in order) with vectored reads — no
    intermediate bytes object.  Zero-fills everything past EOF (the same
    EC tail rule as pread_padded).  Loops on short reads."""
    fd = f.fileno()
    filled = 0
    pending = [memoryview(v) for v in views]
    while pending:
        got = os.preadv(fd, pending, offset + filled)
        if got <= 0:
            break  # EOF
        filled += got
        while pending and got >= len(pending[0]):
            got -= len(pending[0])
            pending.pop(0)
        if pending and got:
            pending[0] = pending[0][got:]
    for v in pending:
        v[:] = bytes(len(v))
