"""Shared low-level IO helpers."""

from __future__ import annotations

import os

import numpy as np


def pread_padded(f, length: int, offset: int) -> np.ndarray:
    """Read `length` bytes at `offset` from file object `f`, zero-padding past
    EOF (the EC tail-block rule, ec_encoder.go:172-176)."""
    buf = os.pread(f.fileno(), length, offset)
    arr = np.zeros(length, dtype=np.uint8)
    if buf:
        arr[: len(buf)] = np.frombuffer(buf, dtype=np.uint8)
    return arr
