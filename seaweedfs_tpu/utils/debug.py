"""/debug/pprof analog + server status UI.

Equivalent of the reference's profiling/observability surface:
util/grace/pprof.go (-cpuprofile/-memprofile) and the per-server status
UIs (server/master_ui, volume_server_ui, filer_ui).  Python-native
counterparts:

  GET /debug/pprof/profile?seconds=N  — cProfile over a live window,
                                        cumulative-time text report
  GET /debug/pprof/goroutine          — all thread stacks (the goroutine
                                        dump analog)
  GET /debug/pprof/heap               — tracemalloc top allocations
                                        (first call enables tracing)
  GET /ui                             — minimal HTML status page built
                                        from the server's /status JSON

register_debug_routes(router, status_fn) wires all four onto any Router.
"""

from __future__ import annotations

import html
import json
import sys
import threading
import traceback
from typing import Callable, Optional

from .httpd import HttpError, Request, Response, Router, qfloat


def _profile_text(seconds: float, interval: float = 0.005) -> str:
    """Sampling profile across ALL threads (cProfile instruments only the
    calling thread, which here would just be sleeping): the shared
    observability.profiler sampler, rendered as the self/cumulative hit
    tables — a py-spy-style statistical profile of real server work
    under load."""
    from ..observability.profiler import SamplingProfiler

    prof = SamplingProfiler(hz=1.0 / interval)
    prof.run_for(seconds)
    return prof.report_text()


def _thread_dump() -> str:
    frames = sys._current_frames()
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = names.get(ident)
        label = f"{t.name} daemon={t.daemon}" if t else f"thread-{ident}"
        out.append(f"--- {label} ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def _heap_text(limit: int = 40) -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return ("tracemalloc just enabled — allocations made from now on "
                "will appear on the next call\n")
    snap = tracemalloc.take_snapshot()
    lines = [f"heap: {len(snap.traces)} traced blocks"]
    for stat in snap.statistics("lineno")[:limit]:
        lines.append(str(stat))
    return "\n".join(lines) + "\n"


def _human_bytes(n) -> str:
    try:
        n = int(n)
    except (TypeError, ValueError):
        return str(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f} {unit}".replace(".0 ", " ") if unit != "B" \
                else f"{n} B"
        n /= 1024
    return str(n)


_SIZE_HINTS = ("size", "bytes", "free_space")


def _cell(key: str, v) -> str:
    """One table cell: scalars inline (sizes humanized), short scalar
    lists joined, anything deeper as compact JSON."""
    if isinstance(v, bool) or v is None:
        return html.escape(str(v))
    if isinstance(v, (int, float)):
        if any(h in key.lower() for h in _SIZE_HINTS):
            return html.escape(_human_bytes(v))
        return html.escape(str(v))
    if isinstance(v, str):
        return html.escape(v)
    if isinstance(v, list) and all(
            isinstance(x, (str, int, float, bool)) for x in v):
        shown = ", ".join(str(x) for x in v[:24])
        if len(v) > 24:
            shown += f", … ({len(v)} total)"
        return html.escape(shown)
    return html.escape(json.dumps(v, default=str))


def _render_value(key: str, v, depth: int) -> str:
    """Recursive section renderer: dicts become key/value tables with
    nested subsections, lists of dicts become striped column tables —
    the reference's master/volume/filer UI table style
    (ref: weed/server/master_ui/master.html:1,
    weed/server/volume_server_ui/volume.html:1) without its static
    bootstrap assets (this page is fully self-contained)."""
    h = min(2 + depth, 5)
    title = f"<h{h}>{html.escape(key)}</h{h}>" if key else ""
    if isinstance(v, dict):
        scalars = {k: x for k, x in v.items()
                   if isinstance(x, (str, int, float, bool)) or x is None}
        nested = {k: x for k, x in v.items() if k not in scalars}
        rows = "".join(
            f"<tr><th>{html.escape(str(k))}</th><td>{_cell(str(k), x)}</td>"
            f"</tr>" for k, x in scalars.items())
        out = title
        if rows:
            out += f"<table class='kv'>{rows}</table>"
        for k, x in nested.items():
            out += _render_value(str(k), x, depth + 1)
        return out
    if isinstance(v, list) and v and all(isinstance(x, dict) for x in v):
        cols: list[str] = []
        for x in v:
            for k in x:
                if k not in cols:
                    cols.append(k)
        head = "".join(f"<th>{html.escape(str(c))}</th>" for c in cols)
        body = "".join(
            "<tr>" + "".join(
                f"<td>{_cell(str(c), x.get(c))}</td>" for c in cols)
            + "</tr>" for x in v)
        return (f"{title}<table class='grid'><thead><tr>{head}</tr>"
                f"</thead><tbody>{body}</tbody></table>")
    return f"{title}<p>{_cell(key, v)}</p>"


def _render_status_html(name: str, status: dict) -> str:
    """One dependency-free single-page dashboard rendering the role's
    /status document as real tables — topology, volumes, EC shards,
    native-plane gauges — in the spirit of the reference's server UIs."""
    body = _render_value("", status, 0)
    return f"""<!doctype html><html><head><title>{html.escape(name)}</title>
<meta http-equiv="refresh" content="15">
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2em; color: #1c2733; }}
 h1 {{ border-bottom: 2px solid #2a6f4e; padding-bottom: .3em; }}
 h2, h3, h4, h5 {{ margin: 1.2em 0 .4em; color: #2a6f4e; }}
 table {{ border-collapse: collapse; margin: .4em 0 1em; }}
 th, td {{ text-align: left; padding: 4px 12px; border-bottom: 1px solid #dfe5ea; }}
 table.kv th {{ color: #50606e; font-weight: 600; }}
 table.grid thead th {{ background: #f1f5f3; border-bottom: 2px solid #cfd9d3; }}
 table.grid tbody tr:nth-child(even) {{ background: #fafbfb; }}
 .links a {{ margin-right: 1em; color: #2a6f4e; }}
</style></head><body>
<h1>{html.escape(name)}</h1>
<div class="links">
 <a href="/status">status json</a>
 <a href="/metrics">metrics</a>
 <a href="/debug/pprof/goroutine">threads</a>
 <a href="/debug/pprof/heap">heap</a>
 <a href="/debug/traces">traces</a>
 <a href="/debug/traces/analyze?format=text">analyze</a>
 <a href="/debug/profile">profile</a>
 <a href="/debug/events">events</a>
 <a href="/debug/reqlog">reqlog</a>
 <a href="/debug/flightrecorder">flight recorder</a>
</div>
{body}
</body></html>"""


def register_debug_routes(router: Router,
                          status_fn: Optional[Callable[[], dict]] = None,
                          name: str = "") -> None:
    """Mount /debug/pprof/* (+ /ui when status_fn is given) on a Router."""

    @router.route("GET", "/debug/pprof/profile")
    def pprof_profile(req: Request) -> Response:
        seconds = min(qfloat(req.query, "seconds", 2.0), 60.0)
        return Response(raw=_profile_text(seconds).encode(),
                        headers={"Content-Type": "text/plain; charset=utf-8"})

    @router.route("GET", "/debug/pprof/goroutine")
    def pprof_goroutine(req: Request) -> Response:
        return Response(raw=_thread_dump().encode(),
                        headers={"Content-Type": "text/plain; charset=utf-8"})

    @router.route("GET", "/debug/pprof/heap")
    def pprof_heap(req: Request) -> Response:
        return Response(raw=_heap_text().encode(),
                        headers={"Content-Type": "text/plain; charset=utf-8"})

    @router.route("GET", "/debug/profile")
    def debug_profile(req: Request) -> Response:
        """Wall-clock sampling profile of every server thread, in
        collapsed-stack (flamegraph.pl) format.  ?seconds=N bounds the
        capture window (default 2, max 60), ?hz=H the sampling rate
        (default 100, max 250).  Paste the body into any flamegraph
        viewer to see where python time goes — the piece of the drain
        loop the span tracer cannot attribute."""
        from ..observability.profiler import profile_collapsed

        seconds = min(qfloat(req.query, "seconds", 2.0), 60.0)
        hz = min(qfloat(req.query, "hz", 100.0), 250.0)
        return Response(raw=profile_collapsed(seconds, hz=hz).encode(),
                        headers={"Content-Type": "text/plain; charset=utf-8"})

    @router.route("GET", "/debug/traces/analyze")
    def debug_traces_analyze(req: Request) -> Response:
        """Critical-path attribution report over the process-global span
        ring: stage occupancy, gap analysis, overlap-efficiency
        decomposition, and the clean-vs-degraded verdict (the pipeline
        restart/fallback counters ride in as `health`).  ?format=text
        renders the human view the shell's trace.analyze shows."""
        from ..observability import analyze, get_tracer, render_report
        from ..stats import ec_integrity_metrics, ec_pipeline_metrics

        # integrity counters ride along so a run that met (and healed)
        # shard rot reads DEGRADED even after the ring rotated the
        # corrupt_shard retry events out
        report = analyze(get_tracer(),
                         counters={**ec_pipeline_metrics().totals(),
                                   **ec_integrity_metrics().totals()})
        if req.query.get("format", "").lower() == "text":
            return Response(raw=render_report(report).encode(),
                            headers={"Content-Type":
                                     "text/plain; charset=utf-8"})
        return Response(report)

    @router.route("GET", "/debug/traces")
    def debug_traces(req: Request) -> Response:
        """Dump the process-global span ring as Chrome trace-event JSON
        (load in chrome://tracing or ui.perfetto.dev).  ?enable=1 turns
        the tracer on for live capture, ?disable=1 turns it off again,
        ?clear=1 empties the ring after dumping.  ?trace_id=<32-hex>
        returns only one distributed trace's spans and ?root=<span_id>
        only that span's subtree — a single request's tree without
        downloading the whole ring (filters never drain the ring: clear
        is ignored when a filter is active, because clearing spans the
        caller did not see would silently lose them)."""
        from ..observability import (disable_tracing, enable_tracing,
                                     get_tracer)

        def flag(name: str) -> bool:
            # allowlist: only explicit affirmatives act — ?clear=off or
            # ?enable=n must not drain the ring / flip the tracer
            return req.query.get(name, "").lower() in \
                ("1", "true", "yes", "on")

        if flag("enable"):
            enable_tracing()
        tracer = get_tracer()
        trace_id = req.query.get("trace_id", "")
        root_id = req.query.get("root", "")
        spans = None
        if trace_id or root_id:
            spans = tracer.snapshot()
            if trace_id:
                spans = [sp for sp in spans if sp.trace_id == trace_id]
            if root_id:
                by_id = {sp.span_id: sp for sp in spans}
                children: dict = {}
                for sp in spans:
                    if sp.parent_id:
                        children.setdefault(sp.parent_id, []).append(sp)
                keep, stack, seen = [], [root_id], set()
                while stack:
                    sid = stack.pop()
                    if sid in seen:
                        continue
                    seen.add(sid)
                    sp = by_id.get(sid)
                    if sp is not None:
                        keep.append(sp)
                    stack.extend(c.span_id
                                 for c in children.get(sid, []))
                spans = keep
        # loss accounting rides every dump: a truncated ring cannot
        # masquerade as a complete trace.  Read BEFORE to_chrome — a
        # clear=1 drain re-baselines tracer.dropped, and this dump must
        # report the drops of the capture it returns, not the zeroed
        # post-clear count
        spans_dropped = tracer.dropped
        # clear rides the same lock as the read: spans recorded while
        # this dump renders are never silently dropped
        doc = tracer.to_chrome(clear=flag("clear"), spans=spans)
        doc["spansDropped"] = spans_dropped
        if flag("disable"):
            disable_tracing()
        return Response(raw=json.dumps(doc).encode(),
                        headers={"Content-Type": "application/json"})

    @router.route("GET", "/debug/events")
    def debug_events(req: Request) -> Response:
        """This process's structured event journal
        (observability/events.py): the typed record of every degraded
        moment (worker restarts, engine fallbacks, shard corruption,
        scrub verdicts, degraded binds) with severity, timestamp, and
        the distributed-trace id active when it happened.  Filters:
        ?type=, ?severity= (exact), ?min_severity=, ?since_seq=,
        ?since=<unix ts>, ?limit=N."""
        from ..observability.events import get_journal

        j = get_journal()
        try:
            since_seq = int(req.query.get("since_seq") or 0)
            since_ts = float(req.query.get("since") or 0.0)
            limit = min(int(req.query.get("limit") or 256), 2048)
        except ValueError as e:
            # a typo'd query param is the CLIENT's mistake: 400, never
            # a 500 that burns the error-ratio SLO budget
            raise HttpError(400, f"bad query parameter: {e}")
        events = j.query(
            type_=req.query.get("type") or None,
            severity=req.query.get("severity") or None,
            min_severity=req.query.get("min_severity") or None,
            since_seq=since_seq, since_ts=since_ts, limit=limit)
        return Response({"events": events, "count": len(events),
                         "namespace": j.namespace,
                         "dropped": j.dropped})

    @router.route("GET", "/debug/reqlog")
    def debug_reqlog(req: Request) -> Response:
        """This process's workload flight recorder (observability/
        reqlog.py): the sampled, redacted access-record ring both
        ingress chokepoints feed.  Filters: ?route=, ?since=<unix ts>,
        ?limit=N.  The `config` block carries the live knobs and loss
        accounting."""
        from ..observability.reqlog import get_recorder

        rl = get_recorder()
        try:
            since_ts = float(req.query.get("since") or 0.0)
            # clamp BOTH ways: a negative limit would slice as [-0:]
            # downstream and return the whole ring, bypassing the cap
            limit = min(max(int(req.query.get("limit") or 512), 1),
                        8192)
        except ValueError as e:
            raise HttpError(400, f"bad query parameter: {e}")
        records = rl.query(route=req.query.get("route") or None,
                           since_ts=since_ts, limit=limit)
        return Response({"records": records, "count": len(records),
                         "config": rl.status()})

    @router.route("POST", "/debug/reqlog/start")
    def debug_reqlog_start(req: Request) -> Response:
        """Start (or re-knob) workload recording on this server.  Body
        knobs: sample (0..1], size (ring capacity), seed, include_ops,
        reset (default true: a fresh recording window).  What
        `weed shell workload.record` fans out cluster-wide."""
        from ..observability.reqlog import get_recorder

        try:
            b = req.json()
        except Exception:
            b = {}
        try:
            sample = float(b["sample"]) if "sample" in b else None
            size = int(b["size"]) if "size" in b else None
            seed = int(b["seed"]) if "seed" in b else None
        except (TypeError, ValueError):
            raise HttpError(400, "bad sample/size/seed")
        # out-of-range knobs answer 400 (the W601 convention), never a
        # 200 that silently starts a recorder recording nothing
        if sample is not None and not 0.0 < sample <= 1.0:
            raise HttpError(400, f"sample={sample:g} out of (0, 1]")
        if size is not None and size <= 0:
            raise HttpError(400, f"size={size} must be positive")
        rl = get_recorder()
        rl.start(sample=sample, capacity=size, seed=seed,
                 include_ops=(bool(b["include_ops"])
                              if "include_ops" in b else None),
                 reset=bool(b.get("reset", True)))
        return Response(rl.status())

    @router.route("POST", "/debug/reqlog/stop")
    def debug_reqlog_stop(req: Request) -> Response:
        """Stop recording; the ring keeps its records for export."""
        from ..observability.reqlog import get_recorder

        rl = get_recorder()
        rl.stop()
        return Response(rl.status())

    @router.route("POST", "/debug/flightrecorder/capture")
    def flightrecorder_capture(req: Request) -> Response:
        """Freeze this process's diagnostics into one spooled bundle
        (trace-ring dump + short sampling profile + /metrics exposition
        + recent events) — what the master's alert engine POSTs when a
        rule fires, and what `weed shell alerts.capture` drives by
        hand.  Body knobs: reason, alert, trace_id, profile_s."""
        from ..observability.flightrecorder import get_flightrecorder

        try:
            b = req.json()
        except Exception:
            b = {}
        try:
            profile_s = min(float(b.get("profile_s", 0.25)), 5.0)
        except (TypeError, ValueError):
            raise HttpError(400, "bad profile_s")
        meta = get_flightrecorder().capture(
            reason=str(b.get("reason") or "manual"),
            alert=(str(b.get("alert")) if b.get("alert") else None),
            trace_id=(str(b.get("trace_id"))
                      if b.get("trace_id") else None),
            profile_s=profile_s)
        return Response(meta, status=201)

    @router.route("GET", "/debug/flightrecorder")
    def flightrecorder_list(req: Request) -> Response:
        from ..observability.flightrecorder import get_flightrecorder

        fr = get_flightrecorder()
        return Response({"bundles": fr.list(),
                         "spool_dir": fr.spool_dir or "",
                         "total_bytes": fr.total_bytes(),
                         "captures": fr.captures,
                         "evicted": fr.evicted})

    @router.route("GET", r"/debug/flightrecorder/([A-Za-z0-9][A-Za-z0-9._-]*)")
    def flightrecorder_get(req: Request) -> Response:
        from ..observability.flightrecorder import get_flightrecorder

        doc = get_flightrecorder().get(req.match.group(1))
        if doc is None:
            raise HttpError(404,
                            f"no bundle {req.match.group(1)!r} spooled")
        return Response(doc)

    if status_fn is not None:
        @router.route("GET", "/ui")
        def status_ui(req: Request) -> Response:
            page = _render_status_html(name or router.name, status_fn())
            return Response(raw=page.encode(),
                            headers={"Content-Type":
                                     "text/html; charset=utf-8"})
