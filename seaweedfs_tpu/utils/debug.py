"""/debug/pprof analog + server status UI.

Equivalent of the reference's profiling/observability surface:
util/grace/pprof.go (-cpuprofile/-memprofile) and the per-server status
UIs (server/master_ui, volume_server_ui, filer_ui).  Python-native
counterparts:

  GET /debug/pprof/profile?seconds=N  — cProfile over a live window,
                                        cumulative-time text report
  GET /debug/pprof/goroutine          — all thread stacks (the goroutine
                                        dump analog)
  GET /debug/pprof/heap               — tracemalloc top allocations
                                        (first call enables tracing)
  GET /ui                             — minimal HTML status page built
                                        from the server's /status JSON

register_debug_routes(router, status_fn) wires all four onto any Router.
"""

from __future__ import annotations

import html
import json
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from .httpd import Request, Response, Router


def _profile_text(seconds: float, interval: float = 0.005) -> str:
    """Sampling profiler across ALL threads (cProfile instruments only the
    calling thread, which here would just be sleeping): sample
    sys._current_frames() every `interval` and aggregate self/cumulative
    hits per frame — a py-spy-style statistical profile of real server
    work under load."""
    self_hits: dict[tuple, int] = {}
    cum_hits: dict[tuple, int] = {}
    own = threading.get_ident()
    samples = 0
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        for ident, frame in sys._current_frames().items():
            if ident == own:
                continue
            leaf = True
            seen_in_stack = set()
            while frame is not None:
                key = (frame.f_code.co_filename, frame.f_lineno,
                       frame.f_code.co_name)
                if leaf:
                    self_hits[key] = self_hits.get(key, 0) + 1
                    leaf = False
                ckey = (frame.f_code.co_filename, frame.f_code.co_name)
                if ckey not in seen_in_stack:  # recursion counts once
                    cum_hits[ckey] = cum_hits.get(ckey, 0) + 1
                    seen_in_stack.add(ckey)
                frame = frame.f_back
        samples += 1
        time.sleep(interval)
    lines = [f"sampling profile: {samples} samples over {seconds}s "
             f"({interval * 1e3:.0f}ms interval), all threads",
             "", "-- self time (leaf frames) --"]
    for (fname, lineno, func), n in sorted(self_hits.items(),
                                           key=lambda kv: -kv[1])[:40]:
        lines.append(f"{n:>6} {100 * n / max(samples, 1):5.1f}% "
                     f"{func} ({fname}:{lineno})")
    lines += ["", "-- cumulative (anywhere on stack) --"]
    for (fname, func), n in sorted(cum_hits.items(),
                                   key=lambda kv: -kv[1])[:40]:
        lines.append(f"{n:>6} {100 * n / max(samples, 1):5.1f}% "
                     f"{func} ({fname})")
    return "\n".join(lines) + "\n"


def _thread_dump() -> str:
    frames = sys._current_frames()
    names = {t.ident: t for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        t = names.get(ident)
        label = f"{t.name} daemon={t.daemon}" if t else f"thread-{ident}"
        out.append(f"--- {label} ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
        out.append("")
    return "\n".join(out)


def _heap_text(limit: int = 40) -> str:
    import tracemalloc

    if not tracemalloc.is_tracing():
        tracemalloc.start()
        return ("tracemalloc just enabled — allocations made from now on "
                "will appear on the next call\n")
    snap = tracemalloc.take_snapshot()
    lines = [f"heap: {len(snap.traces)} traced blocks"]
    for stat in snap.statistics("lineno")[:limit]:
        lines.append(str(stat))
    return "\n".join(lines) + "\n"


def _render_status_html(name: str, status: dict) -> str:
    """One dependency-free HTML page: every scalar becomes a stat row,
    every list/dict a pretty-printed JSON block (the reference's server
    UI templates show the same /status content)."""
    rows, blocks = [], []
    for k, v in status.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            rows.append(f"<tr><th>{html.escape(str(k))}</th>"
                        f"<td>{html.escape(str(v))}</td></tr>")
        else:
            blocks.append(
                f"<h2>{html.escape(str(k))}</h2>"
                f"<pre>{html.escape(json.dumps(v, indent=2, default=str))}"
                f"</pre>")
    return f"""<!doctype html><html><head><title>{html.escape(name)}</title>
<style>
 body {{ font-family: sans-serif; margin: 2em; color: #222; }}
 table {{ border-collapse: collapse; }}
 th, td {{ text-align: left; padding: 4px 12px; border-bottom: 1px solid #ddd; }}
 pre {{ background: #f6f6f6; padding: 1em; overflow-x: auto; }}
 .links a {{ margin-right: 1em; }}
</style></head><body>
<h1>{html.escape(name)}</h1>
<div class="links">
 <a href="/status">status json</a>
 <a href="/metrics">metrics</a>
 <a href="/debug/pprof/goroutine">threads</a>
 <a href="/debug/pprof/heap">heap</a>
</div>
<table>{''.join(rows)}</table>
{''.join(blocks)}
</body></html>"""


def register_debug_routes(router: Router,
                          status_fn: Optional[Callable[[], dict]] = None,
                          name: str = "") -> None:
    """Mount /debug/pprof/* (+ /ui when status_fn is given) on a Router."""

    @router.route("GET", "/debug/pprof/profile")
    def pprof_profile(req: Request) -> Response:
        seconds = min(float(req.query.get("seconds", 2)), 60.0)
        return Response(raw=_profile_text(seconds).encode(),
                        headers={"Content-Type": "text/plain; charset=utf-8"})

    @router.route("GET", "/debug/pprof/goroutine")
    def pprof_goroutine(req: Request) -> Response:
        return Response(raw=_thread_dump().encode(),
                        headers={"Content-Type": "text/plain; charset=utf-8"})

    @router.route("GET", "/debug/pprof/heap")
    def pprof_heap(req: Request) -> Response:
        return Response(raw=_heap_text().encode(),
                        headers={"Content-Type": "text/plain; charset=utf-8"})

    if status_fn is not None:
        @router.route("GET", "/ui")
        def status_ui(req: Request) -> Response:
            page = _render_status_html(name or router.name, status_fn())
            return Response(raw=page.encode(),
                            headers={"Content-Type":
                                     "text/html; charset=utf-8"})
