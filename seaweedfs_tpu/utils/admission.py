"""Admission control: shed excess load EARLY instead of timing out late.

A thread-per-connection server under more load than it can serve does
the worst possible thing by default: it accepts everything, every
request queues behind every other, and EVERY caller times out late —
goodput collapses to zero exactly when demand peaks.  The admission
controller bounds the number of requests in flight per server; a
request over the bound is answered 503 + Retry-After in microseconds
(a "fast no"), the shed is counted (SeaweedFS_requests_shed_total) and
journaled (`load_shed`, rate-limited), and the requests that WERE
admitted keep meeting their latency targets.  Because sheds answer 5xx
they also feed the per-route error-ratio burn-rate SLO — a sustained
shed storm pages through the existing alert plane.

Operator/diagnostic routes are exempt by prefix: an operator must be
able to look at a melting server (/metrics, /debug, /cluster, scrub
and admin surfaces), and shedding heartbeats would cascade a load
problem into a false topology collapse.

Wired at the Router.dispatch chokepoint (utils/httpd.py); servers
enable it with max_inflight > 0 (`weed master/volume/filer
-maxInflight N`).  Disabled (the default) it costs one attribute
check per request.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

# prefixes never shed: operator visibility + control plane liveness.
# Shedding /heartbeat would make an overloaded volume server look DEAD
# to the master (peer_down, repairs kicking off) when it is merely
# busy — load problems must not masquerade as topology problems.
DEFAULT_EXEMPT_PREFIXES = (
    "/metrics", "/debug", "/cluster", "/ec/scrub", "/admin",
    "/heartbeat", "/dir/status", "/status", "/stats",
)

# one load_shed journal event per server per window; the counter still
# counts every shed (the journal is a bounded ring — a shed storm must
# not evict the events that explain it)
_EVENT_MIN_INTERVAL_S = 1.0


class AdmissionController:
    """Bounded-inflight gate for one server's router."""

    def __init__(self, max_inflight: int, role: str = "server",
                 exempt_prefixes: tuple = DEFAULT_EXEMPT_PREFIXES,
                 retry_after_s: float = 1.0):
        self.max_inflight = max(1, int(max_inflight))
        self.role = role
        self.exempt_prefixes = tuple(exempt_prefixes)
        self.retry_after_s = retry_after_s
        self._lock = threading.Lock()
        self._inflight = 0  # guarded-by: _lock
        self.shed_total = 0  # guarded-by: _lock
        self._last_event = 0.0  # guarded-by: _lock

    def exempt(self, path: str) -> bool:
        return path.startswith(self.exempt_prefixes)

    def try_acquire(self) -> bool:
        """Admit (True) or shed (False) one request.  On shed, the
        counter is bumped and a rate-limited load_shed event journaled
        — the caller answers 503 without running the handler."""
        with self._lock:
            if self._inflight < self.max_inflight:
                self._inflight += 1
                return True
            self.shed_total += 1
            inflight = self._inflight
            now = time.monotonic()
            emit = now - self._last_event >= _EVENT_MIN_INTERVAL_S
            if emit:
                self._last_event = now
        from ..stats import request_plane_metrics

        request_plane_metrics().shed.inc(self.role)
        if emit:
            from ..observability import events as _events

            _events.emit("load_shed", role=self.role,
                         inflight=inflight,
                         max_inflight=self.max_inflight)
        return False

    def release(self) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - 1)

    def snapshot(self) -> dict:
        with self._lock:
            return {"max_inflight": self.max_inflight,
                    "inflight": self._inflight,
                    "shed_total": self.shed_total}


def maybe_controller(max_inflight: int,
                     role: str) -> Optional[AdmissionController]:
    """The constructor servers call: 0/negative = admission disabled
    (None), matching the -maxInflight CLI default."""
    if max_inflight and int(max_inflight) > 0:
        return AdmissionController(int(max_inflight), role=role)
    return None
