"""Tiered chunk cache: in-memory LRU + on-disk LRU layer.

Equivalent of weed/util/chunk_cache/ (chunk_cache.go: memory cache for
small chunks + three on-disk volumes by size class, 631 LoC).  Keyed by
fid; the filer's reader and the mount use it so hot chunks are served
without re-hitting volume servers.  The on-disk layer stores one file
per chunk under a cache directory with total-size LRU eviction —
simpler than the reference's needle-file layout but the same contract.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Optional


class MemChunkCache:
    """Bytes-bounded LRU (chunk_cache_in_memory.go)."""

    def __init__(self, limit_bytes: int = 64 * 1024 * 1024):
        self.limit = limit_bytes
        self._lock = threading.Lock()
        self._data: OrderedDict[str, bytes] = OrderedDict()
        self._size = 0
        self.hits = 0
        self.misses = 0

    def get(self, fid: str) -> Optional[bytes]:
        with self._lock:
            blob = self._data.get(fid)
            if blob is None:
                self.misses += 1
                return None
            self._data.move_to_end(fid)
            self.hits += 1
            return blob

    def set(self, fid: str, data: bytes) -> None:
        if len(data) > self.limit:
            return
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._size -= len(old)
            self._data[fid] = data
            self._size += len(data)
            while self._size > self.limit and self._data:
                _, evicted = self._data.popitem(last=False)
                self._size -= len(evicted)

    def delete(self, fid: str) -> None:
        with self._lock:
            old = self._data.pop(fid, None)
            if old is not None:
                self._size -= len(old)


class DiskChunkCache:
    """On-disk LRU layer (chunk_cache_on_disk.go): one file per chunk,
    eviction by oldest access when over the size limit."""

    def __init__(self, directory: str, limit_bytes: int = 1 << 30):
        self.dir = directory
        self.limit = limit_bytes
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._size = sum(
            os.path.getsize(os.path.join(directory, f))
            for f in os.listdir(directory))

    def _path(self, fid: str) -> str:
        h = hashlib.md5(fid.encode()).hexdigest()
        return os.path.join(self.dir, h)

    def get(self, fid: str) -> Optional[bytes]:
        path = self._path(fid)
        try:
            with open(path, "rb") as f:
                data = f.read()
            os.utime(path)  # refresh LRU clock
            return data
        except FileNotFoundError:
            return None

    def set(self, fid: str, data: bytes) -> None:
        if len(data) > self.limit:
            return
        path = self._path(fid)
        with self._lock:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
            existed = os.path.exists(path)
            os.replace(tmp, path)
            if not existed:
                self._size += len(data)
            self._evict_locked()

    def _evict_locked(self) -> None:
        if self._size <= self.limit:
            return
        entries = []
        for name in os.listdir(self.dir):
            p = os.path.join(self.dir, name)
            try:
                st = os.stat(p)
                entries.append((st.st_atime, st.st_size, p))
            except FileNotFoundError:
                pass
        entries.sort()
        for _, size, p in entries:
            if self._size <= self.limit:
                break
            try:
                os.remove(p)
                self._size -= size
            except FileNotFoundError:
                pass

    def delete(self, fid: str) -> None:
        path = self._path(fid)
        with self._lock:
            try:
                size = os.path.getsize(path)
                os.remove(path)
                self._size -= size
            except FileNotFoundError:
                pass


class TieredChunkCache:
    """Memory for small chunks, disk for everything (chunk_cache.go
    tiering by size class)."""

    def __init__(self, mem_limit: int = 64 * 1024 * 1024,
                 disk_dir: str = "", disk_limit: int = 1 << 30,
                 mem_chunk_max: int = 1024 * 1024):
        self.mem = MemChunkCache(mem_limit)
        self.disk = DiskChunkCache(disk_dir, disk_limit) if disk_dir else None
        self.mem_chunk_max = mem_chunk_max

    def get(self, fid: str) -> Optional[bytes]:
        blob = self.mem.get(fid)
        if blob is not None:
            return blob
        if self.disk is not None:
            blob = self.disk.get(fid)
            if blob is not None and len(blob) <= self.mem_chunk_max:
                self.mem.set(fid, blob)  # promote
            return blob
        return None

    def set(self, fid: str, data: bytes) -> None:
        if len(data) <= self.mem_chunk_max:
            self.mem.set(fid, data)
        if self.disk is not None:
            self.disk.set(fid, data)

    def delete(self, fid: str) -> None:
        self.mem.delete(fid)
        if self.disk is not None:
            self.disk.delete(fid)
