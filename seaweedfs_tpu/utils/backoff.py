"""Jittered exponential backoff — the one retry-delay policy.

Used by the EC parity-worker supervisor (ec/overlap.py) and the wdclient
master-reconnect loop; any future retry site should use this instead of
hand-rolling the formula, so cap/jitter semantics can't drift between
subsystems.
"""

from __future__ import annotations

import random


def jittered_backoff(base: float, cap: float, attempt: int) -> float:
    """Delay for the attempt-th retry (attempt counts from 0):
    exponential base*2^attempt bounded by cap, with 50-100% jitter so a
    fleet of clients (or a crash-looping supervisor) never retries in
    lockstep.  The jitter is applied INSIDE the cap — the returned delay
    never exceeds cap, and at saturation still spreads over [cap/2, cap]."""
    return random.uniform(0.5, 1.0) * min(cap, base * (2 ** attempt))
