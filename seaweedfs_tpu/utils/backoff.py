"""Jittered exponential backoff + per-destination retry budgets.

jittered_backoff is the one retry-DELAY policy (EC parity-worker
supervisor, wdclient reconnect, http_json_retry); RetryBudget is the
one retry-VOLUME policy: a token bucket per destination that bounds
how many RETRIES (never first attempts) a process sends at a peer.
When a peer goes down, every caller's retries otherwise multiply the
offered load exactly when the peer can least absorb it — the classic
retry storm.  With a budget, a healthy peer absorbs occasional retries
for free (the bucket refills faster than transient blips drain it),
while a down peer drains the bucket once and every further retry is
DENIED: callers degrade to a single attempt and the denial is counted
(SeaweedFS_retry_budget_exhausted_total) and journaled
(`retry_budget_exhausted`) so the storm that didn't happen is still an
observable, alertable moment.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Optional


def jittered_backoff(base: float, cap: float, attempt: int) -> float:
    """Delay for the attempt-th retry (attempt counts from 0):
    exponential base*2^attempt bounded by cap, with 50-100% jitter so a
    fleet of clients (or a crash-looping supervisor) never retries in
    lockstep.  The jitter is applied INSIDE the cap — the returned delay
    never exceeds cap, and at saturation still spreads over [cap/2, cap]."""
    return random.uniform(0.5, 1.0) * min(cap, base * (2 ** attempt))


class RetryBudget:
    """Per-destination token bucket over RETRIES.  Each destination
    (peer url, repair key, ...) gets its own bucket of `burst` tokens
    refilled at `rate` tokens/second; allow(dest) takes one token, and
    an empty bucket denies.  Buckets are created on first sight and
    pruned once full again and idle (bounded memory across churning
    destinations)."""

    def __init__(self, rate: float = 0.5, burst: float = 10.0):
        self.rate = float(rate)
        self.burst = float(burst)
        self._lock = threading.Lock()
        # dest -> [tokens, monotonic_of_last_refill]
        self._buckets: dict[str, list] = {}  # guarded-by: _lock

    def allow(self, dest: str) -> bool:
        """Take one retry token for `dest`; False = budget exhausted
        (degrade to a single attempt, do NOT retry)."""
        now = time.monotonic()
        with self._lock:
            b = self._buckets.get(dest)
            if b is None:
                b = self._buckets[dest] = [self.burst, now]
            b[0] = min(self.burst, b[0] + (now - b[1]) * self.rate)
            b[1] = now
            if b[0] >= 1.0:
                b[0] -= 1.0
                return True
            return False

    def remaining(self, dest: str) -> float:
        """Current token count (refilled to now) — status surfaces."""
        now = time.monotonic()
        with self._lock:
            b = self._buckets.get(dest)
            if b is None:
                return self.burst
            return min(self.burst, b[0] + (now - b[1]) * self.rate)

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            dests = list(self._buckets)
        return {d: round(self.remaining(d), 2) for d in dests}

    def prune(self, max_destinations: int = 1024) -> None:
        """Drop the oldest buckets beyond the cap (destinations churn
        in test clusters; the budget must not grow without bound)."""
        with self._lock:
            while len(self._buckets) > max_destinations:
                self._buckets.pop(next(iter(self._buckets)))

    def reset(self) -> None:
        with self._lock:
            self._buckets.clear()


# --- process-global budget ---------------------------------------------------
# One budget per process (like the tracer and the event journal): every
# retry site draws from the same per-destination buckets, so a peer
# hammered by one subsystem denies retries to all of them.

_GLOBAL: Optional[RetryBudget] = None
_global_lock = threading.Lock()
# event emission rate limit: one retry_budget_exhausted event per
# destination per window (the counter still counts every denial)
_EVENT_MIN_INTERVAL_S = 5.0
_last_event: dict[str, float] = {}  # guarded-by: _global_lock


def get_retry_budget() -> RetryBudget:
    global _GLOBAL
    with _global_lock:
        if _GLOBAL is None:
            _GLOBAL = RetryBudget()
        return _GLOBAL


def retry_allowed(dest: str, kind: str = "http") -> bool:
    """The one call every retry site makes before re-attempting: draw
    from the process-global budget; on denial, bump the
    retry_budget_exhausted counter (labeled by subsystem `kind`) and
    journal a rate-limited `retry_budget_exhausted` event naming the
    destination — then the caller degrades to what it already did."""
    if get_retry_budget().allow(dest):
        return True
    from ..stats import request_plane_metrics

    request_plane_metrics().retry_budget_exhausted.inc(kind)
    now = time.monotonic()
    emit = False
    with _global_lock:
        if now - _last_event.get(dest, 0.0) >= _EVENT_MIN_INTERVAL_S:
            _last_event[dest] = now
            emit = True
    if emit:
        from ..observability import events as _events

        _events.emit("retry_budget_exhausted", dest=dest, kind=kind)
    return False
