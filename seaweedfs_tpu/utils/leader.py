"""LeaderFollowingTransport: one leader-follow policy for every
master-ward client.

Four shippers (traces, events, workload records, heat snapshots) and
wdclient each grew their own copy of the same transport idea: parse the
comma-separated master candidate list, POST to one, rotate to the next
on failure.  That converges eventually — any reachable master proxies
ingests to the raft leader — but after a failover every batch pays a
follower proxy hop until blind rotation happens to land on the new
leader, and five copies of the policy drift.

This helper centralizes it and adds the missing half: LEARNING.  Every
master ingest response carries ``{"leader": "host:port"}`` and every
follower redirect carries a Location header; the transport caches that
hint and sends the next request straight to the leader.  On any
failure the hint is dropped and rotation resumes over the configured
candidates — the pre-hint behavior, so a stale hint can never wedge a
shipper.

The contract the shippers keep: one attempt per call, exceptions
propagate (the caller counts the batch lost — shipping never
backpressures), no internal retries.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from .httpd import http_json


class LeaderFollowingTransport:
    """Candidate rotation + learned-leader short-circuit over a
    comma-separated master list (``master_url_fn`` re-reads it every
    call, so heartbeat-driven re-targeting flows through)."""

    def __init__(self, master_url_fn: Optional[Callable[[], str]] = None,
                 name: str = ""):
        self.master_url_fn = master_url_fn
        self.name = name
        self._lock = threading.Lock()
        self._i = 0  # guarded-by: _lock
        self._leader = ""  # guarded-by: _lock — learned hint
        self.sent = 0  # guarded-by: _lock
        self.failed = 0  # guarded-by: _lock
        self.leader_hits = 0  # guarded-by: _lock

    def candidates(self) -> list[str]:
        raw = self.master_url_fn() if self.master_url_fn else ""
        return [u.strip() for u in (raw or "").split(",") if u.strip()]

    @property
    def leader(self) -> str:
        with self._lock:
            return self._leader

    def target(self) -> str:
        """The address the next request goes to: the learned leader if
        we have one, else the current rotation candidate.  Raises
        ConnectionError with no candidates at all."""
        urls = self.candidates()
        with self._lock:
            if self._leader:
                return self._leader
            if not urls:
                raise ConnectionError("no master url configured")
            return urls[self._i % len(urls)]

    def learn(self, leader: str) -> None:
        """Cache a leader hint (from a response body or a redirect
        Location); unknown/empty values clear nothing."""
        leader = (leader or "").strip()
        if not leader:
            return
        with self._lock:
            self._leader = leader

    def note_failure(self) -> None:
        """One failed attempt: drop the learned hint and rotate the
        candidate cursor so the next call tries somewhere else."""
        with self._lock:
            self._leader = ""
            self._i += 1
            self.failed += 1

    def post(self, path: str, payload: dict,
             timeout: float = 5.0) -> dict:
        """POST one document to the current target; learn the leader
        from the response; on ANY failure rotate and re-raise (the
        caller's loss accounting is the retry policy)."""
        target = self.target()
        try:
            r = http_json("POST", f"http://{target}{path}", payload,
                          timeout=timeout)
        except Exception:
            self.note_failure()
            raise
        with self._lock:
            self.sent += 1
            if self._leader and target == self._leader:
                self.leader_hits += 1
        self.learn(str(r.get("leader") or "")
                   if isinstance(r, dict) else "")
        return r

    def get(self, path: str, timeout: float = 5.0) -> dict:
        """GET from the current target (wdclient lookups); same learn/
        rotate contract as post()."""
        target = self.target()
        try:
            r = http_json("GET", f"http://{target}{path}",
                          timeout=timeout)
        except Exception:
            self.note_failure()
            raise
        with self._lock:
            self.sent += 1
        self.learn(str(r.get("leader") or "")
                   if isinstance(r, dict) else "")
        return r

    def status(self) -> dict:
        with self._lock:
            return {"leader_hint": self._leader, "sent": self.sent,
                    "failed": self.failed,
                    "leader_hits": self.leader_hits}
