"""AES-256-GCM chunk encryption, wire-compatible with weed/util/cipher.go.

The reference seals with a random 12-byte nonce prepended to the
ciphertext (gcm.Seal(nonce, nonce, plaintext, nil)); keys are 32 random
bytes generated per chunk and stored only in the filer's FileChunk
metadata — volume servers hold ciphertext they cannot read.
"""

from __future__ import annotations

import os

from cryptography.hazmat.primitives.ciphers.aead import AESGCM

NONCE_SIZE = 12  # Go's gcm.NonceSize() default
KEY_SIZE = 32


def gen_cipher_key() -> bytes:
    return os.urandom(KEY_SIZE)


def encrypt(plaintext: bytes, key: bytes) -> bytes:
    nonce = os.urandom(NONCE_SIZE)
    return nonce + AESGCM(key).encrypt(nonce, plaintext, None)


def decrypt(ciphertext: bytes, key: bytes) -> bytes:
    if len(ciphertext) < NONCE_SIZE:
        raise ValueError("ciphertext too short")
    return AESGCM(key).decrypt(ciphertext[:NONCE_SIZE],
                               ciphertext[NONCE_SIZE:], None)
