"""Fault injection: named fault points with configurable errors/delays.

SURVEY.md §5 notes the reference has no fault-injection framework and
that the rebuild should carry one.  Fault points are free when disabled
(one dict-emptiness check); tests and chaos drills arm them:

    from seaweedfs_tpu.utils import faultinject as fi

    fi.enable("disk.read", error_rate=0.3)         # 30% of reads raise
    fi.enable("net.request", delay=0.05)           # +50ms per request
    with fi.scoped("disk.sync", error_rate=1.0):   # scoped arming
        ...
    fi.clear()

Instrumented sites (grep for fi.hit to find them all):
    disk.read / disk.write / disk.sync   — DiskFile positional IO
    shard.read                           — EC shard pread
    net.request                          — pooled HTTP client sends
    ec.worker.ack                        — parity worker ack read (parent
                                           side); an injected error is
                                           treated as worker death: the
                                           supervisor SIGKILLs and
                                           respawns the real process,
                                           replaying in-flight dispatches
    ec.shm                               — parity worker spawn/shm attach;
                                           arming it makes respawns fail,
                                           deterministically exhausting
                                           the retry budget (CPU fallback
                                           drills)
    ec.dispatch / ec.drain               — streaming pipeline submit and
                                           drain; an injected error forces
                                           a per-dispatch CPU fallback
    ec.shard.corrupt                     — deterministic bit flip on EC
                                           shard reads (corrupt_block):
                                           armed with params
                                           {"shard": id, "offset": byte,
                                           "bit": 0-7}, any read of that
                                           shard covering that byte comes
                                           back flipped — the bit-rot
                                           drill behind the sidecar
                                           verify-on-use paths

The ec.* points fire in the ENCODING PARENT only: overlap workers are
spawned processes with their own (empty) fault registry, so arming a
point never corrupts worker-side compute — it exercises the parent's
recovery paths deterministically.
"""

from __future__ import annotations

import contextlib
import random
import threading
import time
from typing import Optional

_lock = threading.Lock()
_points: dict[str, dict] = {}
_counts: dict[str, int] = {}

# The CENTRAL fault-point registry: every hit()/corrupt_block() site in
# the package names one of these, and every entry is exercised by at
# least one test — both directions machine-checked by weedlint rule
# W701 (tools/weedlint/rules_faults.py), so a typo'd name can't
# silently never fire and a registered point can't silently never run
# its recovery path.  `weed shell fault.list` prints this table.
FAULT_POINTS: dict[str, str] = {
    "disk.read": "DiskFile positional read (storage/backend.py)",
    "disk.write": "DiskFile positional write (storage/backend.py)",
    "disk.sync": "DiskFile fsync (storage/backend.py)",
    "shard.read": "EC shard pread (ec/ec_volume.py)",
    "net.request": "pooled HTTP client send (utils/httpd.py)",
    "ec.worker.ack": "parity-worker ack read, parent side — injected "
                     "error is treated as worker death: SIGKILL + "
                     "respawn + in-flight replay (ec/overlap.py)",
    "ec.shm": "parity-worker spawn / shm attach — arming makes "
              "respawns fail, draining the restart budget for CPU-"
              "fallback drills (ec/overlap.py)",
    "ec.dispatch": "streaming pipeline submit — injected error forces "
                   "a per-dispatch CPU fallback (ec/streaming.py)",
    "ec.drain": "streaming pipeline drain — injected error forces a "
                "per-dispatch CPU fallback; delay-only arming drives "
                "the slow-drain attribution drills (ec/streaming.py)",
    "ec.shard.corrupt": "deterministic bit flip on EC shard reads "
                        "(corrupt_block): params {shard, offset, bit} "
                        "— the bit-rot drill behind verify-on-use "
                        "(ec/integrity.py paths)",
    "coord.plan": "rebuild/rebalance coordinator planning cycle — an "
                  "injected error must be contained (loop survives, "
                  "last_error surfaces, next cycle re-plans) "
                  "(ops/coordinator.py)",
    "coord.exec": "coordinator plan-execution step (every "
                  "/admin/ec/* leg) — injected error fails the "
                  "current repair/move so re-plan + no-orphan "
                  "cleanup paths run (ops/coordinator.py)",
    "net.delay": "peer-scoped wire slowdown at the pooled-HTTP and "
                 "framed egress: params={'peer': 'host:port'} (absent "
                 "= all peers) + delay=<s>.  Applied deadline-aware "
                 "(deadline.sleep_within), so a caller with an "
                 "X-Weed-Deadline budget still returns on time — the "
                 "scenario engine's slow-network drill "
                 "(utils/httpd.py, utils/framing.py)",
    "net.drop": "peer-scoped probabilistic request loss at the same "
                "egress points: params={'peer': ...}, error_rate<1 "
                "models packet loss / connection resets "
                "(utils/httpd.py, utils/framing.py)",
    "net.partition": "peer-scoped total partition: arm with "
                     "error_rate=1.0 + params={'peer': ...} and every "
                     "send to that peer fails instantly — the "
                     "failure-under-load scenario's rack-loss stand-in "
                     "(utils/httpd.py, utils/framing.py)",
    "loop.block": "reactor inline fast path, ON the event-loop thread "
                  "— delay-only arming blocks the WHOLE dataplane for "
                  "the duration, the loop-stall drill behind the "
                  "loop_lag health key and the loop_stall alert relay "
                  "(utils/eventloop.py)",
    "tier.upload": "tier phase-1 upload, fired with the .tier manifest "
                   "on disk and no remote byte sent yet — delay-only "
                   "arming opens the mid-upload SIGKILL window the "
                   "crash drill proves survivable: local .dat stays "
                   "authoritative, partial remote object is GC'd "
                   "(storage/volume.py tier_upload_begin)",
    "tier.recall": "tier recall download, fired with the manifest in "
                   "'recalling' and only a temp file partial — the "
                   "mid-recall SIGKILL window: remote copy stays "
                   "authoritative, partial temp is dropped "
                   "(storage/volume.py tier_download)",
}


def list_points() -> list[tuple[str, str]]:
    """The registry as sorted (name, description) pairs — what
    `weed shell fault.list` prints."""
    return sorted(FAULT_POINTS.items())


def enable(name: str, error_rate: float = 0.0,
           error: Optional[BaseException] = None,
           delay: float = 0.0, max_hits: int = 0,
           params: Optional[dict] = None) -> None:
    """Arm a fault point.  error_rate in [0,1]; max_hits>0 auto-disarms
    after that many injected faults (deterministic crash tests).
    params carries site-specific fault data for data-mutation points
    (ec.shard.corrupt's shard/offset/bit targeting)."""
    with _lock:
        _points[name] = {
            "error_rate": error_rate,
            "error": error or OSError(f"fault injected at {name}"),
            "delay": delay,
            "max_hits": max_hits,
            "hits": 0,
            "params": dict(params) if params else None,
        }


def disable(name: str) -> None:
    with _lock:
        _points.pop(name, None)


def clear() -> None:
    with _lock:
        _points.clear()
        _counts.clear()


def fired(name: str) -> int:
    """How many times this point actually injected a fault."""
    return _counts.get(name, 0)


@contextlib.contextmanager
def scoped(name: str, **kwargs):
    enable(name, **kwargs)
    try:
        yield
    finally:
        disable(name)


def hit(name: str) -> None:
    """The instrumented call: no-op unless armed (callers guard with
    `if faultinject._points:` for true zero cost on hot paths)."""
    if not _points:
        return
    with _lock:
        p = _points.get(name)
        if p is None:
            return
        if p["max_hits"] and p["hits"] >= p["max_hits"]:
            return
        inject_error = p["error_rate"] and random.random() < p["error_rate"]
        delay = p["delay"]
        if inject_error or delay:
            p["hits"] += 1
            _counts[name] = _counts.get(name, 0) + 1
        err = p["error"] if inject_error else None
    if delay:
        time.sleep(delay)
    if err is not None:
        raise err


def arm_from_env(spec: Optional[str] = None) -> int:
    """Arm fault points from a WEED_FAULTS-style spec string so chaos
    drills can inject faults into SUBPROCESS servers (spawned via
    weed.py) that they cannot reach through in-process enable() calls.

    Format: ``name:key=val,key=val;name2:...`` — e.g.
    ``WEED_FAULTS="tier.upload:delay=5,max_hits=1"``.  Keys: error_rate
    (float), delay (float, seconds), max_hits (int).  Unknown point
    names still arm (the registry check is weedlint's job, and a drill
    may target a point added in the same change).  Returns the number
    of points armed."""
    import os as _os

    if spec is None:
        spec = _os.environ.get("WEED_FAULTS", "")
    armed = 0
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        name, _, kvs = part.partition(":")
        kwargs: dict = {}
        for kv in kvs.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            k = k.strip()
            if k == "max_hits":
                kwargs[k] = int(v)
            elif k in ("error_rate", "delay"):
                kwargs[k] = float(v)
        enable(name.strip(), **kwargs)
        armed += 1
    return armed


def _peer_matches(p: Optional[dict], peer: str) -> bool:
    """Does this armed point's scope cover `peer`?  No params or no
    'peer' key = every peer; otherwise exact netloc match."""
    if p is None:
        return False
    prm = p.get("params")
    if not prm or prm.get("peer") is None:
        return True
    return str(prm.get("peer")) == peer


def hit_peer(name: str, peer: str) -> None:
    """Peer-scoped twin of hit(): fires only when the armed point's
    params name this destination (params absent = all peers).  The
    net.drop / net.partition egress sites ride this so a scenario can
    partition ONE peer while the rest of the cluster serves."""
    if not _points:
        return
    with _lock:
        if not _peer_matches(_points.get(name), peer):
            return
    hit(name)


def peer_delay(name: str, peer: str) -> float:
    """Peer-scoped delay QUERY: returns the armed delay (counting a
    hit) instead of sleeping, so the egress can apply it deadline-aware
    (deadline.sleep_within) — a slow wire must not stall a caller past
    its budget, exactly like a real socket timeout firing during a slow
    network.  0.0 when unarmed / out of scope / out of hits."""
    if not _points:
        return 0.0
    with _lock:
        p = _points.get(name)
        if not _peer_matches(p, peer):
            return 0.0
        delay = p["delay"]
        if not delay:
            return 0.0
        if p["max_hits"] and p["hits"] >= p["max_hits"]:
            return 0.0
        p["hits"] += 1
        _counts[name] = _counts.get(name, 0) + 1
        return delay


def corrupt_block(name: str, shard_id: int, data, file_offset: int = 0):
    """Data-mutation fault (ec.shard.corrupt): deterministically flip
    one bit in a shard read.  Armed with
    ``enable(name, params={"shard": id, "offset": byte, "bit": 0-7})``,
    any read of `shard_id` whose [file_offset, file_offset+len) range
    covers `offset` comes back with that bit flipped — exactly what
    on-media bit rot looks like to the reader.  Returns `data` untouched
    when unarmed or out of range; counts a hit only when it flips.
    Accepts bytes or a 1-D uint8 ndarray (flipped in place when
    writable, else on a copy)."""
    if not _points:
        return data
    with _lock:
        p = _points.get(name)
        prm = p.get("params") if p is not None else None
        if not prm or int(prm.get("shard", -1)) != shard_id:
            return data
        if p["max_hits"] and p["hits"] >= p["max_hits"]:
            return data
        target = int(prm.get("offset", 0))
        if not (file_offset <= target < file_offset + len(data)):
            return data
        p["hits"] += 1
        _counts[name] = _counts.get(name, 0) + 1
        bit = int(prm.get("bit", 0)) & 7
    rel = target - file_offset
    if isinstance(data, (bytes, bytearray, memoryview)):
        buf = bytearray(data)
        buf[rel] ^= 1 << bit
        return bytes(buf)
    arr = data if getattr(data.flags, "writeable", False) else data.copy()
    arr[rel] ^= 1 << bit
    return arr
