"""Minimal protobuf wire-format codec (proto2/proto3 compatible subset).

The HBase native RPC (filer/hbase_store.py) is protobuf-framed; the
image has no protobuf runtime or HBase .proto files, so messages are
built and parsed explicitly against their published field numbers with
this ~100-line codec.  Only the wire types the HBase surface uses:
varint (0), 64-bit (1), length-delimited (2), 32-bit (5).

Encoding helpers return bytes; messages are just concatenations of
encoded fields, which keeps each protocol message definition readable
at its call site (field numbers visible, like a .proto)."""

from __future__ import annotations


def enc_varint(n: int) -> bytes:
    if n < 0:
        # protobuf encodes negative int32/int64 as the 64-bit two's
        # complement (always 10 bytes); without the mask the shift loop
        # below never terminates on negative Python ints
        n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def dec_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = n = 0
    while True:
        b = buf[i]
        i += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, i
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def f_varint(num: int, val: int) -> bytes:
    return enc_varint(num << 3 | 0) + enc_varint(val)


def f_bytes(num: int, data: bytes) -> bytes:
    return enc_varint(num << 3 | 2) + enc_varint(len(data)) + data


def f_string(num: int, s: str) -> bytes:
    return f_bytes(num, s.encode())


def f_msg(num: int, msg: bytes) -> bytes:
    return f_bytes(num, msg)


def delimited(msg: bytes) -> bytes:
    """varint-length-prefixed message (protobuf writeDelimitedTo)."""
    return enc_varint(len(msg)) + msg


def read_delimited(buf: bytes, i: int) -> tuple[bytes, int]:
    n, i = dec_varint(buf, i)
    return buf[i:i + n], i + n


def decode(buf: bytes) -> dict[int, list]:
    """-> {field_number: [values in wire order]}; varints as int,
    length-delimited as bytes, fixed32/64 as int (little-endian)."""
    out: dict[int, list] = {}
    i = 0
    while i < len(buf):
        tag, i = dec_varint(buf, i)
        num, wire = tag >> 3, tag & 7
        if wire == 0:
            val, i = dec_varint(buf, i)
        elif wire == 1:
            val = int.from_bytes(buf[i:i + 8], "little")
            i += 8
        elif wire == 2:
            n, i = dec_varint(buf, i)
            val = buf[i:i + n]
            i += n
        elif wire == 5:
            val = int.from_bytes(buf[i:i + 4], "little")
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        out.setdefault(num, []).append(val)
    return out


def first(fields: dict[int, list], num: int, default=None):
    vals = fields.get(num)
    return vals[0] if vals else default
