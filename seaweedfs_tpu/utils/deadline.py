"""Cluster-wide request-deadline propagation (the X-Weed-Deadline plane).

PAPER.md's layer map shows every hop (client -> master -> volume,
filer -> volume, coordinator -> peers) riding the same HTTP/framed
chokepoints, yet until this module a slow peer could pin a caller for
the full per-call timeout: a client willing to wait 2 seconds could
trigger 30+ seconds of downstream work that nobody would ever read.
This module closes that gap with a deadline that travels WITH the
request:

    X-Weed-Deadline: <remaining seconds, decimal>

The header carries the REMAINING budget (a duration), never an absolute
wall time — processes on different hosts do not share a clock, but a
duration re-anchored to the receiver's monotonic clock only ever loses
the (sub-millisecond) wire time.  Rules, mirroring the trace-context
plane (observability/context.py):

  - INGRESS (utils/httpd.py Router.dispatch): a valid header installs a
    thread-local deadline for the request; an already-expired budget is
    answered 504 BEFORE the handler runs (the caller has given up —
    doing the work anyway is pure waste).  Malformed headers are
    ignored, never 500.  The thread-local is restored afterwards:
    handler threads are pooled per connection and a leaked deadline
    would starve the next request.
  - EGRESS (utils/httpd.py _pooled_request / http_download, the framed
    client): the per-call timeout is clamped to the remaining budget
    and the header re-emitted with what is left — a 2s client deadline
    can never become 30s of downstream work.  A budget already spent
    raises DeadlineExceeded without sending anything.

Servers map DeadlineExceeded to 504 (gateway-timeout-style: "the
upstream budget ran out here"), bump
SeaweedFS_deadline_exceeded_total and journal a `deadline_exceeded`
event — so budget exhaustion is a measured, alertable signal instead
of a mystery timeout.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

DEADLINE_HEADER = "X-Weed-Deadline"

# budgets below this are treated as already expired: a sub-millisecond
# remainder cannot survive even a loopback round trip
MIN_BUDGET_S = 0.001

_tls = threading.local()


class DeadlineExceeded(Exception):
    """The request's deadline budget is spent.  Deliberately NOT an
    OSError subclass: the http helpers' blanket transport-error
    handling must not swallow it (a spent budget is the CALLER's
    signal, not a peer failure), and Router.dispatch maps it to 504."""


class Deadline:
    """An absolute point on THIS process's monotonic clock by which the
    request must be answered."""

    __slots__ = ("at",)

    def __init__(self, at: float):
        self.at = at

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + float(seconds))

    def remaining(self) -> float:
        return self.at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() < MIN_BUDGET_S

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(remaining={self.remaining():.3f}s)"


def parse_deadline(value) -> Optional[Deadline]:
    """Header value -> Deadline re-anchored to the local monotonic
    clock, or None for absent/malformed input (a bad client must not
    500 a server).  A non-positive budget parses to an ALREADY-EXPIRED
    deadline — the caller decided; the ingress answers 504."""
    if not value:
        return None
    try:
        budget = float(str(value).strip())
    except (TypeError, ValueError):
        return None
    if budget != budget or budget in (float("inf"), float("-inf")):
        return None
    return Deadline.after(budget)


def current() -> Optional[Deadline]:
    """The thread's active deadline, or None (no budgeted request)."""
    return getattr(_tls, "deadline", None)


def activate(deadline: Optional[Deadline]):
    """Install `deadline` on this thread; returns the previous value
    for symmetric restore."""
    prev = getattr(_tls, "deadline", None)
    _tls.deadline = deadline
    return prev


def begin_request(headers):
    """Ingress helper: parse + activate in one step.  `headers` is any
    .get()-able (or None for headerless ingresses like the framed-TCP
    fronts — those CLEAR the slot so a pooled connection thread cannot
    leak a previous request's budget).  Returns (deadline_or_None,
    previous) — pass `previous` to end_request() in a finally block."""
    prev = getattr(_tls, "deadline", None)
    ddl = parse_deadline(headers.get(DEADLINE_HEADER)) \
        if headers is not None else None
    _tls.deadline = ddl
    return ddl, prev


def end_request(prev) -> None:
    _tls.deadline = prev


class scope:
    """``with scope(seconds_or_deadline):`` — run a block under a
    deadline (client entry points, the coordinator's per-repair budget,
    scenario drivers).  Accepts seconds, an existing Deadline (carrying
    a caller's budget onto a helper thread), or None (explicitly no
    deadline)."""

    __slots__ = ("deadline", "prev")

    def __init__(self, seconds_or_deadline):
        if seconds_or_deadline is None or \
                isinstance(seconds_or_deadline, Deadline):
            self.deadline = seconds_or_deadline
        else:
            self.deadline = Deadline.after(float(seconds_or_deadline))

    def __enter__(self) -> Optional[Deadline]:
        self.prev = activate(self.deadline)
        return self.deadline

    def __exit__(self, *exc) -> bool:
        _tls.deadline = self.prev
        return False


def clamp(timeout: float) -> float:
    """The effective timeout for one outbound call: min(timeout,
    remaining budget).  Raises DeadlineExceeded when the budget is
    already spent — the egress must not send a request whose answer
    nobody will wait for.  No active deadline passes `timeout`
    through untouched."""
    ddl = getattr(_tls, "deadline", None)
    if ddl is None:
        return timeout
    rem = ddl.remaining()
    if rem < MIN_BUDGET_S:
        raise DeadlineExceeded(
            f"deadline exceeded before send ({rem:.3f}s remaining)")
    return min(float(timeout), rem)


def inject_deadline_headers(headers: dict) -> dict:
    """Stamp the remaining budget onto an outbound request's headers
    (called INSIDE the egress chokepoints, next to the Traceparent
    injection).  No active deadline: untouched."""
    ddl = getattr(_tls, "deadline", None)
    if ddl is not None:
        headers.setdefault(DEADLINE_HEADER,
                           f"{max(ddl.remaining(), 0.0):.3f}")
    return headers


def sleep_within(seconds: float) -> None:
    """Sleep up to `seconds`, clipped by the active deadline; raises
    DeadlineExceeded when the budget runs out first.  The net.delay
    fault point rides this at the egress: a slow wire delays the
    request, but the caller's clock keeps running and the call still
    returns within its budget — exactly how a real socket timeout
    behaves under a slow network."""
    ddl = getattr(_tls, "deadline", None)
    if ddl is None:
        time.sleep(seconds)
        return
    rem = ddl.remaining()
    if rem < MIN_BUDGET_S:
        raise DeadlineExceeded("deadline exceeded before network delay")
    if seconds >= rem:
        time.sleep(max(rem, 0.0))
        raise DeadlineExceeded(
            f"deadline expired during {seconds:.3f}s network delay")
    time.sleep(seconds)
