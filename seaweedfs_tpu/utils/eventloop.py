"""Shared event-loop serving dataplane for the HTTP and framed-TCP fronts.

The thread-per-connection servers (utils/httpd.FastHTTPServer, the
framing.FramedServer accept loop) spend the hot read path's budget on
thread spawns and blocking socket writes: every accepted connection
costs a fresh `threading.Thread`, and a slow client reading a streamed
response pins a whole thread for the duration of the transfer.  The
bench trajectory shows the ceiling clearly — the framing/dispatch layer
caps HTTP reads around ~4k rps while the needle store itself does
~930k 4KB ops/s in batched microbenches.

This module replaces that layer with ONE selector-driven reactor per
process, shared by every server front in it:

  - the loop owns accept + readable/writable readiness for every
    connection, parses HTTP/1.1 requests and framed-TCP frames
    non-blockingly, and keeps connections alive across requests
    (keep-alive and pipelining are the default, not one-thread-one-
    connection);
  - parsed requests dispatch onto a SMALL bounded worker pool
    (`-dataplane.workers`) that runs the untouched `Router.dispatch`
    chokepoint — tracing, deadline adoption, admission control and the
    workload recorder all ride exactly the code they always rode;
  - responses flush on the loop via gather writes (`socket.sendmsg`
    over memoryview slices — response bodies are enqueued as the
    handler's own `bytes` objects, never joined or copied) and
    `os.sendfile` for `Response(file_path=...)` streams, with
    partial-write readiness: a slow client costs one outbox entry, not
    a blocked thread;
  - GET/HEAD object reads whose needle the popularity cache already
    holds (volume_server/needle_cache.py) dispatch INLINE on the loop
    — a cache-hit read completes entirely on the loop with zero
    thread handoffs (the one audited, waived exception to the W505
    no-blocking-on-the-loop lint: the probe guarantees a memory hit,
    and a raced invalidation degrades to one bounded 4KB pread).

Loop-side methods are marked `# loop-callback`; the weedlint W505 rule
walks the call graph from those roots and fails the build if anything
classified blocking by the W504 tables (HTTP egress, time.sleep,
timeout-less queue ops, disk helpers) becomes reachable from the loop.

Ops teams get `SeaweedFS_dataplane_*` metrics (connections, workers,
dispatches, aborts); aborted connections (slow-client outbox overflow,
bounded-deadline stop teardown) count into the
`dataplane_conn_aborts` HEALTH_FAMILIES key and journal a rate-limited
`dataplane_conn_abort` event.

Knobs: `weed -dataplane.workers N <role>` (WEED_DATAPLANE_WORKERS),
and WEED_DATAPLANE=threaded to fall back to the thread-per-connection
servers wholesale.
"""

from __future__ import annotations

import io
import os
import selectors
import socket
import threading
import time
from collections import deque
from typing import Callable, Optional

# per-connection bounds, mirroring the threaded servers' guards
MAX_HEADER_BYTES = 1 << 16          # 64KB request line + headers
MAX_HEADERS = 100
MAX_BODY_BYTES = 1 << 30            # buffered request body cap (413 past it)
MAX_OUT_BUFFERED = 64 << 20         # queued response BYTES before a slow
#                                     client is aborted (sendfile regions
#                                     stream from disk and don't count)
FLUSH_THRESHOLD = 1 << 20           # past this, enqueue() drains the
#                                     socket inline so big responses
#                                     stream instead of accumulating
SLOW_CLIENT_GRACE_S = 30.0          # a backpressured writer waits this
#                                     long for the client to drain the
#                                     outbox before the conn is aborted
RECV_CHUNK = 1 << 16

# absolute ceiling on dispatch workers (core + overflow): far above any
# steady state, just a runaway backstop — overflow workers retire after
# ~2s idle
HARD_WORKER_CAP = 128

# requests on these paths ride the priority dispatch lane: control-
# plane liveness (heartbeats!) and operator visibility must never queue
# behind a burst of bulk object writes (same prefix philosophy as
# utils/admission.DEFAULT_EXEMPT_PREFIXES)
OPS_PRIORITY_PREFIXES = (
    "/metrics", "/debug", "/cluster", "/ec/scrub", "/admin",
    "/heartbeat", "/dir/status", "/status", "/stats", "/raft",
)

_EVENT_MIN_INTERVAL_S = 5.0


def _metrics():
    from ..stats import dataplane_metrics

    return dataplane_metrics()


class _FileSend:
    """One sendfile region queued on a connection's outbox."""

    __slots__ = ("fd", "offset", "remaining")

    def __init__(self, fd: int, offset: int, length: int):
        self.fd = fd
        self.offset = offset
        self.remaining = length

    def close(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass


class _ConnWriter:
    """The `wfile` handed to Router._send on the worker pool: every
    write enqueues the caller's own bytes object on the connection's
    outbox (no join, no copy) and the loop flushes it when the socket
    is writable."""

    __slots__ = ("conn",)

    def __init__(self, conn: "_Conn"):
        self.conn = conn

    def write(self, data) -> int:
        self.conn.enqueue(data)
        return len(data)

    def flush(self) -> None:
        pass


class _LoopHandler:
    """Per-request handler exposing exactly the BaseHTTPRequestHandler
    surface Router.dispatch uses (same contract as httpd._FastHandler),
    with the response side backed by the connection outbox and
    `sendfile` support for Response(file_path=...) streams."""

    __slots__ = ("server", "rfile", "wfile", "client_address", "command",
                 "path", "headers", "close_connection", "_out", "_conn",
                 "queue_wait_s")

    def __init__(self, server, conn: "_Conn", body: bytes, peer):
        self.server = server
        self._conn = conn
        # dispatch-queue wait: stamped by the worker-handoff closure
        # (loop-enqueue -> worker pickup); the inline fast path leaves
        # it 0.  Router.dispatch feeds it to the resource ledger.
        self.queue_wait_s = 0.0
        self.rfile = io.BytesIO(body)
        self.wfile = _ConnWriter(conn)
        self.client_address = peer
        self.command = ""
        self.path = ""
        self.headers = None
        self.close_connection = True
        self._out: list = []

    def send_response(self, status: int, message: str = "") -> None:
        from .httpd import _REASONS, _http_date

        self._out = [b"HTTP/1.1 %d %s\r\nDate: %s\r\n"
                     % (status,
                        (message or _REASONS.get(status, "OK")).encode(),
                        _http_date().encode())]

    def send_header(self, key: str, value) -> None:
        self._out.append(f"{key}: {value}\r\n".encode())
        if key.lower() == "connection" and str(value).lower() == "close":
            self.close_connection = True

    def end_headers(self) -> None:
        self._out.append(b"\r\n")
        self._conn.enqueue(b"".join(self._out))
        self._out = []

    def sendfile(self, path: str, offset: int, length: int) -> bool:
        """Queue a zero-copy file region for the loop to os.sendfile.
        Returns False when the platform/file cannot sendfile so the
        caller falls back to chunked reads through wfile."""
        if not _HAS_SENDFILE:
            return False
        try:
            fd = os.open(path, os.O_RDONLY)
        except OSError:
            return False
        self._conn.enqueue_file(_FileSend(fd, offset, length))
        return True


_HAS_SENDFILE = hasattr(os, "sendfile")


class _Conn:
    """One reactor-owned connection (HTTP or framed-TCP).

    Loop-thread-only state (parse buffers, selector registration) is
    unlocked by design; the outbox and lifecycle flags are shared with
    the worker pool and ride `_lock`."""

    __slots__ = ("reactor", "listener", "sock", "fileno", "peer",
                 "inbuf", "body_needed", "pending", "want_events",
                 "_lock", "outq", "out_bytes", "busy", "closing",
                 "aborted", "flushing")

    def __init__(self, reactor: "Reactor", listener: "_Listener",
                 sock: socket.socket, peer):
        self.reactor = reactor
        self.listener = listener
        self.sock = sock
        self.fileno = sock.fileno()
        self.peer = peer
        # loop-thread-only parse state
        self.inbuf = bytearray()
        self.body_needed = -1      # >=0: header parsed, awaiting body
        self.pending = None        # parsed head awaiting its body
        self.want_events = selectors.EVENT_READ
        # shared with the worker pool (a Condition: flushers notify
        # when the outbox shrinks so a backpressured writer can resume)
        self._lock = threading.Condition()
        self.outq: list = []       # guarded-by: _lock
        self.out_bytes = 0         # guarded-by: _lock
        self.busy = False          # guarded-by: _lock
        self.closing = False       # guarded-by: _lock
        self.aborted = False       # guarded-by: _lock
        self.flushing = False      # single-flusher claim  # guarded-by: _lock

    # --- worker-side API ---------------------------------------------------
    def enqueue(self, data) -> None:
        """Queue response bytes; called from worker threads (via
        Router._send) or from the loop's inline fast path.  Small
        responses accumulate and flush once at request_done; past
        FLUSH_THRESHOLD the enqueuing thread drains the socket AS IT
        WRITES, so a multi-hundred-MB response streams through a
        bounded outbox — only a client that stops reading (kernel
        buffer full, flush cannot drain) ever hits the overflow abort."""
        if not len(data):
            # empty writes (302/204 bodies) must never reach the
            # outbox: an all-empty sendmsg batch returns 0 sent, which
            # the consume loop could not distinguish from "no
            # progress" — the flusher would spin on it forever
            return
        with self._lock:
            if self.aborted:
                return
            self.outq.append(data)
            self.out_bytes += len(data)
            big = self.out_bytes >= FLUSH_THRESHOLD
        if not big:
            return
        self.reactor.flush_conn(self)
        with self._lock:
            over = not self.aborted and self.out_bytes > MAX_OUT_BUFFERED
        if not over:
            return
        if self.reactor.on_loop_thread():
            # the loop must never park.  Crossing the cap here means a
            # pipelining client amassed 64MB+ of unread fast-path
            # responses — the slow-client condition, aborted at once
            # (no grace: the loop cannot wait for a drain)
            with self._lock:
                self.aborted = True
            self.reactor.note_abort("slow_client")
            self.reactor.mark_dirty(self)
            return
        # worker-side BACKPRESSURE — the reactor's equivalent of the
        # threaded server blocking in sendall: hand the socket to the
        # loop (EVENT_WRITE) and wait for the client to drain; only a
        # client that stops reading altogether is aborted
        self.reactor.mark_dirty(self)
        deadline = time.monotonic() + SLOW_CLIENT_GRACE_S
        overflow = False
        with self._lock:
            while not self.aborted and \
                    self.out_bytes > MAX_OUT_BUFFERED:
                if time.monotonic() >= deadline:
                    self.aborted = True
                    overflow = True
                    break
                self._lock.wait(timeout=0.5)
        if overflow:
            self.reactor.note_abort("slow_client")
            self.reactor.mark_dirty(self)

    def enqueue_file(self, fs: _FileSend) -> None:
        with self._lock:
            if self.aborted:
                fs.close()
                return
            self.outq.append(fs)

    def request_done(self, close: bool) -> None:
        """The worker finished one dispatch: flush the response from
        THIS thread (the common whole-response-fits send needs no loop
        round trip at all), then wake the loop only when it has work —
        leftover output to watch for writability, buffered pipelined
        input to parse, or a close to run."""
        with self._lock:
            self.busy = False
            if close:
                self.closing = True
        self.reactor.flush_conn(self)
        with self._lock:
            need_loop = (bool(self.outq) or self.closing
                         or self.aborted)
        # len() on the loop-owned buffer is a GIL-atomic heuristic:
        # a pipelined request that lands AFTER this check re-fires
        # EVENT_READ on its own, so a stale 0 can never strand one
        if need_loop or len(self.inbuf) > 0:
            self.reactor.mark_dirty(self)

    # --- loop-side helpers -------------------------------------------------
    def drain_out(self) -> None:  # loop-callback
        """Release queued output without sending (abort path).  The
        sendfile fds close UNDER the lock — a flusher's send iteration
        holds the same lock, so no stale fd can be mid-sendfile."""
        with self._lock:
            items, self.outq = self.outq, []
            self.out_bytes = 0
            for item in items:
                if isinstance(item, _FileSend):
                    item.close()
            self._lock.notify_all()  # wake backpressured writers


class _Listener:
    """One listening socket registered on the reactor."""

    __slots__ = ("sock", "kind", "router", "handler", "name", "owner",
                 "conns")

    def __init__(self, sock: socket.socket, kind: str, owner,
                 router=None, handler=None, name: str = ""):
        self.sock = sock
        self.kind = kind              # "http" | "framed"
        self.owner = owner            # facade server (_stopping flag)
        self.router = router
        self.handler = handler        # framed: fn(op, key, body) -> bytes
        self.name = name
        self.conns: set = set()       # loop-thread-only


class Reactor:
    """The process-wide selector loop + bounded dispatch worker pool.

    The pool has two lanes and an elastic overflow: operator/control
    requests (heartbeats, /metrics, /cluster, admin) take a PRIORITY
    lane so a burst of bulk object writes can never queue a heartbeat
    into the master's janitor window (a load problem must not
    masquerade as a topology problem — the admission controller's
    rule, applied to scheduling).  When every worker is busy (e.g.
    long-poll subscribe handlers legitimately parked in cond.wait) and
    work is waiting, overflow workers spawn up to a hard cap and
    retire after idling — steady state stays small, blocking handlers
    cannot deadlock the plane."""

    def __init__(self, workers: int = 0):
        self.workers = int(workers) if workers and int(workers) > 0 \
            else max(4, min(16, (os.cpu_count() or 4) * 2))
        self._sel = selectors.DefaultSelector()   # loop-thread-only
        self._lock = threading.Lock()
        # loop-thread-only: mutated exclusively inside _apply_pending
        # (listener add/remove requests travel through _pending)
        self._listeners: dict[int, _Listener] = {}
        self._pending: list = []    # add/remove ops for the loop  # guarded-by: _lock
        self._dirty: set = set()    # conns needing interest recompute  # guarded-by: _lock
        # two-lane dispatch queue + worker accounting, all under _qcond
        self._qcond = threading.Condition()
        self._q_ops: list = []      # control-plane lane  # guarded-by: _qcond
        self._q_data: list = []     # object/data lane  # guarded-by: _qcond
        self._idle = 0              # workers parked in wait  # guarded-by: _qcond
        self._alive = 0             # workers running (core+overflow)  # guarded-by: _qcond
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._started = False       # guarded-by: _lock
        self._last_abort_event = 0.0  # guarded-by: _lock
        self._threads: list[threading.Thread] = []  # guarded-by: _lock
        # hook-style handoff: written once in start() before the loop
        # thread runs, read lock-free by on_loop_thread()
        self._loop_thread: Optional[threading.Thread] = None
        # --- loop saturation telemetry (the resource-ledger plane) ---
        # pre-select tick stamp: the watchdog reads it to detect a loop
        # blocked mid-iteration (a torn read of a float is impossible
        # in CPython; staleness of one tick is the measurement)
        self._tick_ts = time.monotonic()
        # the inline fast-path request currently holding the loop
        # (path str), so a watchdog-detected stall can NAME the route
        self._loop_busy: Optional[str] = None
        # per-iteration loop busy time samples: (monotonic ts, busy_s),
        # appended by the loop, read by loop_lag_stats()
        self._lag_samples: deque = deque(maxlen=512)  # guarded-by: _lock
        self._last_stall_note = 0.0   # watchdog fallback rate limit
        # servers wire their RequestLedger.note_stall here so a stall
        # is recorded with route + trace; None = count-only fallback
        self.stall_hook = None

    # --- lifecycle ---------------------------------------------------------
    def start(self) -> "Reactor":
        with self._lock:
            if self._started:
                return self
            self._started = True
            t = threading.Thread(target=self._run, daemon=True,
                                 name="dataplane-loop")
            self._loop_thread = t
            self._threads.append(t)
            # saturation watchdog: pages (via the ledger stall hook or
            # the loop_stalls counter) when the LOOP ITSELF is blocked
            # — the loop cannot report its own hang
            self._threads.append(threading.Thread(
                target=self._watch, daemon=True,
                name="dataplane-watchdog"))
            for i in range(self.workers):
                w = threading.Thread(target=self._work, daemon=True,
                                     name=f"dataplane-worker-{i}")
                self._threads.append(w)
            threads = list(self._threads)
        with self._qcond:
            self._alive += self.workers
        m = _metrics()
        m.workers.set(self.workers)
        for t in threads:
            t.start()
        return self

    def on_loop_thread(self) -> bool:
        return threading.current_thread() is self._loop_thread

    def wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    def mark_dirty(self, conn: _Conn) -> None:
        with self._lock:
            self._dirty.add(conn)
        self.wake()

    def note_abort(self, reason: str) -> None:
        """Count + journal (rate-limited) one aborted connection."""
        _metrics().conn_aborts.inc(reason)
        now = time.monotonic()
        with self._lock:
            emit = now - self._last_abort_event >= _EVENT_MIN_INTERVAL_S
            if emit:
                self._last_abort_event = now
        if emit:
            from ..observability import events as _events

            try:
                _events.emit("dataplane_conn_abort", reason=reason)
            except Exception:
                pass

    # --- listener registration --------------------------------------------
    def add_http_listener(self, sock: socket.socket, router, owner) -> None:
        sock.setblocking(False)
        lst = _Listener(sock, "http", owner, router=router,
                        name=router.name)
        with self._lock:
            self._pending.append(("add", lst))
        self.wake()

    def add_framed_listener(self, sock: socket.socket, handler,
                            name: str, owner) -> None:
        sock.setblocking(False)
        lst = _Listener(sock, "framed", owner, handler=handler, name=name)
        with self._lock:
            self._pending.append(("add", lst))
        self.wake()

    def remove_listener(self, owner, deadline_s: float = 1.5) -> None:
        """Stop accepting for `owner` and abort its connections.  Blocks
        (bounded) until the loop acknowledged the teardown — the caller
        can rebind the port the moment this returns."""
        done = threading.Event()
        with self._lock:
            self._pending.append(("remove", owner, done))
        self.wake()
        done.wait(timeout=max(deadline_s, 0.1))

    # --- worker pool -------------------------------------------------------
    def submit(self, fn: Callable[[], None], ops: bool = False) -> None:
        """Queue one dispatch; never blocks.  `ops` requests take the
        priority lane.  If no worker is idle, an overflow worker spawns
        (bounded by HARD_WORKER_CAP) so handlers that legitimately park
        — long-poll subscribes, slow disks — cannot starve the plane."""
        spawn = False
        with self._qcond:
            (self._q_ops if ops else self._q_data).append(fn)
            if self._idle == 0 and self._alive < HARD_WORKER_CAP:
                self._alive += 1
                spawn = True
            self._qcond.notify()
        if spawn:
            threading.Thread(target=self._work, args=(True,),
                             daemon=True,
                             name="dataplane-worker-extra").start()

    def _work(self, extra: bool = False) -> None:  # thread-entry
        while True:
            with self._qcond:
                self._idle += 1
                try:
                    while not self._q_ops and not self._q_data:
                        if not self._qcond.wait(timeout=2.0) and extra \
                                and not self._q_ops \
                                and not self._q_data:
                            self._alive -= 1
                            return  # overflow worker idled out
                finally:
                    self._idle -= 1
                fn = (self._q_ops.pop(0) if self._q_ops
                      else self._q_data.pop(0))
            try:
                fn()
            except Exception:
                pass  # dispatch wrappers guard themselves; never die

    # --- the loop ----------------------------------------------------------
    def _run(self) -> None:  # thread-entry
        self._sel.register(self._wake_r, selectors.EVENT_READ, "wake")
        # brand the thread object so observability/ledger can answer
        # "am I ON the loop?" with one attribute read, no singleton
        threading.current_thread()._weed_loop = True
        while True:
            self._apply_pending()
            # sentinel-timer drift: the tick stamp freshens every
            # iteration; the watchdog reads (now - tick - select
            # timeout) as the loop's current lag while blocked
            self._tick_ts = time.monotonic()  # weedlint: disable=W502 single writer (the loop thread); the watchdog only READS this float, and a stale read just delays one lag check by a tick
            try:
                events = self._sel.select(timeout=1.0)
            except OSError:
                continue
            t_busy0 = time.monotonic()
            for key, mask in events:
                data = key.data
                try:
                    if data == "wake":
                        try:
                            while self._wake_r.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    elif isinstance(data, _Listener):
                        self._on_accept(data)
                    elif isinstance(data, _Conn):
                        if mask & selectors.EVENT_READ:
                            self._on_readable(data)
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(data)
                except Exception:
                    # one connection's parse/flush bug must never take
                    # the whole dataplane down with it
                    if isinstance(data, _Conn):
                        try:
                            self._close_conn(data,
                                             abort_reason="loop_error")
                        except Exception:
                            pass
            busy_s = time.monotonic() - t_busy0
            if busy_s >= 0.001:
                # loop-lag sample: how long THIS iteration held the
                # loop (inline dispatches included) — every connection
                # waited that long.  Sub-ms iterations are free and
                # not worth a lock + histogram touch.
                with self._lock:
                    self._lag_samples.append((t_busy0, busy_s))
                try:
                    _metrics().loop_lag.observe(busy_s)
                except Exception:
                    pass

    def loop_lag_stats(self, window_s: float = 60.0) -> dict:
        """Loop saturation snapshot for /debug/ledger and the shipped
        ledger snapshots: lag percentiles over the recent window plus
        dispatch-queue depth and worker-pool occupancy."""
        now = time.monotonic()
        with self._lock:
            samples = sorted(b for (t, b) in self._lag_samples
                             if now - t <= window_s)
        with self._qcond:
            qdepth = len(self._q_ops) + len(self._q_data)
            alive, idle = self._alive, self._idle

        def pct(p: float) -> float:
            if not samples:
                return 0.0
            return samples[min(int(p * len(samples)),
                               len(samples) - 1)]

        return {
            "lag_p50_ms": round(pct(0.50) * 1000.0, 2),
            "lag_p99_ms": round(pct(0.99) * 1000.0, 2),
            "lag_max_ms": round(samples[-1] * 1000.0, 2)
            if samples else 0.0,
            "samples": len(samples),
            "queue_depth": qdepth,
            "workers": alive,
            "workers_busy": max(alive - idle, 0),
        }

    def _watch(self) -> None:  # thread-entry
        """Saturation watchdog: refreshes the queue-depth / occupancy
        gauges and detects a BLOCKED loop from outside it — the tick
        stamp going stale past the select timeout plus the stall
        threshold means nothing (accepts, parses, flushes) is moving."""
        from ..observability.ledger import LOOP_STALL_THRESHOLD_S

        while True:
            time.sleep(0.25)
            try:
                m = _metrics()
                with self._qcond:
                    qo, qd = len(self._q_ops), len(self._q_data)
                    alive, idle = self._alive, self._idle
                m.queue_depth.set("ops", float(qo))
                m.queue_depth.set("data", float(qd))
                m.workers_busy.set(float(max(alive - idle, 0)))
                # 1.0 = the select timeout: an IDLE loop's stamp is
                # legitimately that old
                lag = time.monotonic() - self._tick_ts - 1.0
                if lag < LOOP_STALL_THRESHOLD_S:
                    continue
                route = self._loop_busy or "(loop)"
                hook = self.stall_hook
                if hook is not None:
                    # the ledger records route + exemplar, counts the
                    # loop_stalls family, and rate-limits repeats
                    hook(route, lag, "")
                    continue
                now = time.monotonic()
                if now - self._last_stall_note >= _EVENT_MIN_INTERVAL_S:
                    self._last_stall_note = now  # weedlint: disable=W502 only the watchdog thread ever touches this rate-limit stamp
                    m.loop_stalls.inc()
            except Exception:
                pass  # the watchdog must never die

    def _apply_pending(self) -> None:  # loop-callback
        with self._lock:
            ops, self._pending = self._pending, []
            dirty, self._dirty = self._dirty, set()
        for op in ops:
            if op[0] == "add":
                lst = op[1]
                self._listeners[lst.sock.fileno()] = lst  # weedlint: disable=W502 loop-thread-only: _apply_pending runs exclusively on the reactor loop thread
                try:
                    self._sel.register(lst.sock, selectors.EVENT_READ, lst)
                except (OSError, ValueError, KeyError):
                    pass
            else:  # ("remove", owner, done)
                _kw, owner, done = op
                for fno, lst in list(self._listeners.items()):
                    if lst.owner is not owner:
                        continue
                    del self._listeners[fno]
                    try:
                        self._sel.unregister(lst.sock)
                    except (OSError, ValueError, KeyError):
                        pass
                    try:
                        lst.sock.close()
                    except OSError:
                        pass
                    for conn in list(lst.conns):
                        self._close_conn(conn, abort_reason="stop")
                done.set()
        for conn in dirty:
            try:
                self._refresh_conn(conn)
            except Exception:
                try:
                    self._close_conn(conn, abort_reason="loop_error")
                except Exception:
                    pass

    def _refresh_conn(self, conn: _Conn) -> None:  # loop-callback
        """Recompute a connection's state after worker activity:
        flush, continue parsing pipelined input, close when drained."""
        if conn not in conn.listener.conns:
            return  # already torn down
        with conn._lock:
            aborted = conn.aborted
        if aborted:
            self._close_conn(conn)
            return
        self.flush_conn(conn)
        self._advance(conn)

    def _on_accept(self, lst: _Listener) -> None:  # loop-callback
        for _ in range(64):  # bounded accept burst per readiness
            try:
                sock, peer = lst.sock.accept()
            except (BlockingIOError, OSError):
                return
            try:
                sock.setblocking(False)
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                sock.close()
                continue
            if lst.kind == "framed" and lst.owner is not None:
                ok_fn = getattr(lst.owner, "_whitelist_ok", None)
                if ok_fn is not None and not ok_fn(peer[0]):
                    sock.close()
                    continue
            conn = _Conn(self, lst, sock, peer)
            lst.conns.add(conn)
            _metrics().connections.add(1)
            try:
                self._sel.register(sock, selectors.EVENT_READ, conn)
            except (OSError, ValueError, KeyError):
                lst.conns.discard(conn)
                _metrics().connections.add(-1)
                sock.close()

    def _on_readable(self, conn: _Conn) -> None:  # loop-callback
        try:
            piece = conn.sock.recv(RECV_CHUNK)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._close_conn(conn)
            return
        if not piece:
            # peer half-closed: finish what is in flight, then close
            with conn._lock:
                conn.closing = True
                busy = conn.busy
            if not busy and not conn.inbuf:
                self._close_conn(conn)
            else:
                self._refresh_conn(conn)
            return
        conn.inbuf += piece
        if len(conn.inbuf) > MAX_BODY_BYTES + MAX_HEADER_BYTES:
            # a client streaming past every framing bound while a
            # request is in flight is a memory-exhaustion vector
            self._close_conn(conn, abort_reason="overflow")
            return
        self._advance(conn)

    def _advance(self, conn: _Conn) -> None:  # loop-callback
        """Parse as much buffered input as the one-request-in-flight
        discipline allows, then recompute selector interest."""
        while True:
            with conn._lock:
                if conn.busy or conn.closing or conn.aborted:
                    break
            if conn.listener.kind == "http":
                if not self._parse_http(conn):
                    break
            else:
                if not self._parse_frame(conn):
                    break
        self._update_interest(conn)

    # --- HTTP parsing ------------------------------------------------------
    def _parse_http(self, conn: _Conn) -> bool:  # loop-callback
        """One parse step; True when a request was dispatched (the
        caller loops for pipelining)."""
        if conn.body_needed < 0:
            end = conn.inbuf.find(b"\r\n\r\n")
            if end < 0:
                if len(conn.inbuf) > MAX_HEADER_BYTES:
                    # still inside the request LINE -> 414; past it,
                    # an unbounded header block -> 431 (both mirror
                    # the threaded server's guards)
                    if b"\r\n" not in conn.inbuf:
                        self._reject_http(conn, 414, "URI Too Long")
                    else:
                        self._reject_http(
                            conn, 431,
                            "Request Header Fields Too Large")
                return False
            head = bytes(conn.inbuf[:end])
            del conn.inbuf[:end + 4]
            if not self._parse_http_head(conn, head):
                return False
            if conn.body_needed == 0:
                conn.body_needed = -1
                return self._dispatch_http(conn, b"")
            return True  # fall through: body may already be buffered
        if len(conn.inbuf) < conn.body_needed:
            # (oversized Content-Length already answered 413 at head
            # parse — body_needed is always within MAX_BODY_BYTES here)
            return False
        body = bytes(conn.inbuf[:conn.body_needed])
        del conn.inbuf[:conn.body_needed]
        conn.body_needed = -1
        return self._dispatch_http(conn, body)

    def _parse_http_head(self, conn: _Conn, head: bytes) -> bool:  # loop-callback
        from .httpd import CIHeaders

        lines = head.split(b"\r\n")
        try:
            method, _, rest = lines[0].partition(b" ")
            target, _, version = rest.rpartition(b" ")
            command = method.decode("ascii")
            path = target.decode("iso-8859-1")
        except (UnicodeDecodeError, ValueError):
            self._reject_http(conn, 400, "Bad Request")
            return False
        if not command or not path:
            self._reject_http(conn, 400, "Bad Request")
            return False
        if len(lines) - 1 > MAX_HEADERS:
            self._reject_http(conn, 431, "Request Header Fields Too Large")
            return False
        pairs = []
        for hl in lines[1:]:
            if not hl:
                continue
            k, _, v = hl.partition(b":")
            pairs.append((k.decode("iso-8859-1"),
                          v.strip().decode("iso-8859-1")))
        headers = CIHeaders(pairs)
        if "chunked" in (headers.get("Transfer-Encoding") or "").lower():
            # Request.body only frames Content-Length bodies (same
            # refusal as the threaded server)
            self._reject_http(conn, 501, "Not Implemented")
            return False
        try:
            clen = int(headers.get("Content-Length") or 0)
        except (TypeError, ValueError):
            self._reject_http(conn, 400, "Bad Request")
            return False
        if clen < 0:
            # a negative length would read as the awaiting-headers
            # sentinel and silently orphan the request (never
            # dispatched, never answered): malformed framing is 400
            self._reject_http(conn, 400, "Bad Request")
            return False
        if clen > MAX_BODY_BYTES:
            self._reject_http(conn, 413, "Payload Too Large")
            return False
        conn_hdr = (headers.get("Connection") or "").lower()
        close = (conn_hdr == "close"
                 or (version == b"HTTP/1.0" and conn_hdr != "keep-alive"))
        if (headers.get("Expect") or "").lower() == "100-continue":
            conn.enqueue(b"HTTP/1.1 100 Continue\r\n\r\n")
        conn.pending = (command, path, headers, close)
        conn.body_needed = clen
        return True

    def _reject_http(self, conn: _Conn, status: int,
                     reason: str) -> None:  # loop-callback
        conn.enqueue(("HTTP/1.1 %d %s\r\nContent-Length: 0\r\n"
                      "Connection: close\r\n\r\n"
                      % (status, reason)).encode())
        with conn._lock:
            conn.closing = True
        self.flush_conn(conn)
        self._update_interest(conn)

    def _dispatch_http(self, conn: _Conn, body: bytes) -> bool:  # loop-callback
        command, path, headers, close = conn.pending
        conn.pending = None
        lst = conn.listener
        h = _LoopHandler(lst.owner, conn, body, conn.peer)
        h.command = command
        h.path = path
        h.headers = headers
        h.close_connection = close
        router = lst.router
        with conn._lock:
            conn.busy = True
        probe = getattr(router, "loop_fast_probe", None)
        if probe is not None and command in ("GET", "HEAD") \
                and not body and "Range" not in headers \
                and probe(command, path):
            # cache-probed inline fast path: the needle cache holds this
            # object, so the whole dispatch (trace/deadline/admission/
            # reqlog chokepoint included) completes on the loop with no
            # thread handoff.  Lexically Router.dispatch reaches disk
            # helpers, hence the audited waiver: a raced invalidation
            # degrades to ONE bounded needle pread, never unbounded IO.
            self._loop_busy = path  # weedlint: disable=W502 loop-thread-only write; the watchdog's racy read is the point
            from . import faultinject as fi

            if fi._points:
                # the loop-stall drill's injection site: a delay here
                # blocks the WHOLE dataplane, exactly like a handler
                # that sneaks blocking IO onto the inline fast path
                fi.hit("loop.block")  # weedlint: loop-io fault-injection drill point, inert outside tests
            try:
                router.dispatch(h, command)  # weedlint: loop-io cache-probed fast path: needle cache holds the object; a raced invalidation costs one bounded pread
            except Exception:
                with conn._lock:
                    conn.closing = True
            self._loop_busy = None  # weedlint: disable=W502 loop-thread-only write; the watchdog's racy read is the point
            _metrics().fast_dispatches.inc()
            conn.request_done(close=h.close_connection)
            return True

        t_submit = time.monotonic()

        def run():
            # queue wait = loop enqueue -> worker pickup; the ledger
            # reads it off the handler at settle
            h.queue_wait_s = time.monotonic() - t_submit
            try:
                router.dispatch(h, command)
            except Exception:
                with conn._lock:
                    conn.closing = True
            conn.request_done(close=h.close_connection)

        _metrics().pool_dispatches.inc()
        self.submit(run, ops=path.startswith(OPS_PRIORITY_PREFIXES))
        return True

    # --- framed-TCP parsing ------------------------------------------------
    def _parse_frame(self, conn: _Conn) -> bool:  # loop-callback
        from .framing import U16, U32

        buf = conn.inbuf
        if len(buf) < 3:
            return False
        key_len = U16.unpack_from(buf, 1)[0]
        if len(buf) < 3 + key_len + 4:
            return False
        body_len = U32.unpack_from(buf, 3 + key_len)[0]
        total = 3 + key_len + 4 + body_len
        if len(buf) < total:
            return False
        op = bytes(buf[:1])
        try:
            key = bytes(buf[3:3 + key_len]).decode()
        except UnicodeDecodeError:
            self._close_conn(conn)
            return False
        body = bytes(buf[3 + key_len + 4:total])
        del conn.inbuf[:total]
        lst = conn.listener
        with conn._lock:
            conn.busy = True

        t_submit = time.monotonic()

        def run():
            from .framing import serve_frame

            frame = serve_frame(lst.handler, lst.name, op, key, body,
                                conn.peer[0],
                                ledger=getattr(lst.owner, "ledger",
                                               None),
                                queue_wait_s=time.monotonic()
                                - t_submit)
            conn.enqueue(frame)
            conn.request_done(close=False)

        _metrics().pool_dispatches.inc()
        self.submit(run)
        return True

    # --- writeback ---------------------------------------------------------
    def flush_conn(self, conn: _Conn) -> None:
        """Send as much queued output as the socket accepts right now.
        Bytes items go out in one gather write (sendmsg over memoryview
        slices); _FileSend items stream via os.sendfile.  Callable from
        ANY thread — the dispatching worker flushes its own response so
        the loop only gets involved on partial writes; the `flushing`
        claim keeps exactly one sender per socket so racing flushers
        cannot interleave bytes."""
        with conn._lock:
            if conn.flushing or conn.aborted:
                return
            conn.flushing = True
        try:
            self._flush_locked_out(conn)
        finally:
            with conn._lock:
                conn.flushing = False

    def _flush_locked_out(self, conn: _Conn) -> None:
        # each iteration — including the send syscall — runs under
        # conn._lock: _close_conn tears the socket and any queued
        # sendfile fds down under the SAME lock after marking the conn
        # aborted, so a flusher can never race a close into a stale-fd
        # write (fd reuse would stream bytes into the wrong client).
        # The sends are non-blocking syscalls, so the lock is held for
        # microseconds, never for a stalled peer.
        while True:
            with conn._lock:
                if not conn.outq or conn.aborted:
                    return
                head = conn.outq[0]
                if isinstance(head, _FileSend):
                    fs: _FileSend = head
                    if fs.remaining <= 0:
                        fs.close()
                        conn.outq.pop(0)
                        continue
                    try:
                        sent = os.sendfile(conn.fileno, fs.fd,
                                           fs.offset,
                                           min(fs.remaining, 1 << 20))
                    except (BlockingIOError, InterruptedError):
                        return
                    except OSError:
                        conn.aborted = True
                        conn._lock.notify_all()
                        self.mark_dirty(conn)
                        return
                    if sent == 0:
                        fs.remaining = 0
                        continue
                    fs.offset += sent
                    fs.remaining -= sent
                    continue
                batch = []
                for item in conn.outq:
                    if isinstance(item, _FileSend):
                        break
                    batch.append(memoryview(item)
                                 if not isinstance(item, memoryview)
                                 else item)
                    if len(batch) >= 32:
                        break
                try:
                    sent = conn.sock.sendmsg(batch)
                except (BlockingIOError, InterruptedError):
                    return
                except OSError:
                    conn.aborted = True
                    conn._lock.notify_all()
                    self.mark_dirty(conn)
                    return
                conn.out_bytes -= sent
                # pop fully-sent items; zero-length leftovers pop
                # unconditionally (they represent no bytes and would
                # otherwise wedge the batch head at sent == 0)
                while conn.outq and not isinstance(conn.outq[0],
                                                   _FileSend):
                    n = len(conn.outq[0])
                    if n == 0:
                        conn.outq.pop(0)
                    elif sent >= n:
                        conn.outq.pop(0)
                        sent -= n
                    elif sent > 0:
                        conn.outq[0] = memoryview(conn.outq[0])[sent:]
                        sent = 0
                    else:
                        break
                conn._lock.notify_all()  # backpressured writers resume


    def _update_interest(self, conn: _Conn) -> None:  # loop-callback
        with conn._lock:
            have_out = bool(conn.outq)
            closing = conn.closing
            busy = conn.busy
            aborted = conn.aborted
        if aborted:
            self._close_conn(conn)
            return
        if closing and not have_out and not busy:
            self._close_conn(conn)
            return
        events = selectors.EVENT_READ | (selectors.EVENT_WRITE
                                         if have_out else 0)
        if events != conn.want_events:
            conn.want_events = events
            try:
                self._sel.modify(conn.sock, events, conn)
            except (OSError, ValueError, KeyError):
                self._close_conn(conn)

    def _on_writable(self, conn: _Conn) -> None:  # loop-callback
        self.flush_conn(conn)
        self._update_interest(conn)

    def _close_conn(self, conn: _Conn,
                    abort_reason: str = "") -> None:  # loop-callback
        if conn not in conn.listener.conns:
            return
        conn.listener.conns.discard(conn)
        _metrics().connections.add(-1)
        with conn._lock:
            had_work = bool(conn.outq) or conn.busy
            conn.aborted = True
        conn.drain_out()
        if abort_reason and had_work:
            self.note_abort(abort_reason)
        try:
            self._sel.unregister(conn.sock)
        except (OSError, ValueError, KeyError):
            pass
        # socket teardown under conn._lock: a flusher's send iteration
        # holds the same lock, so the fd cannot be closed (and reused)
        # out from under an in-flight sendfile/sendmsg.  Best-effort
        # graceful close inside: half-close, then drain what already
        # reached the kernel so the close cannot RST away a just-
        # flushed error response (bounded, non-blocking).
        with conn._lock:
            try:
                conn.sock.shutdown(socket.SHUT_WR)
                for _ in range(64):
                    if not conn.sock.recv(RECV_CHUNK):
                        break
            except (BlockingIOError, OSError):
                pass
            try:
                conn.sock.close()
            except OSError:
                pass


# --- process-global reactor --------------------------------------------------

_reactor: Optional[Reactor] = None
_reactor_lock = threading.Lock()
_configured_workers = 0


def configure(workers: Optional[int] = None) -> None:
    """Apply the -dataplane.workers knob; takes effect at first use
    (the pool is sized once per process, like the tracer ring)."""
    global _configured_workers
    if workers is not None and int(workers) > 0:
        with _reactor_lock:
            _configured_workers = int(workers)


def get_reactor() -> Reactor:
    global _reactor
    with _reactor_lock:
        if _reactor is None:
            workers = _configured_workers \
                or int(os.environ.get("WEED_DATAPLANE_WORKERS", "0") or 0)
            _reactor = Reactor(workers=workers)
    return _reactor.start()


def reactor_enabled() -> bool:
    """WEED_DATAPLANE=threaded falls the whole process back to the
    thread-per-connection servers (the pre-reactor dataplane)."""
    return os.environ.get("WEED_DATAPLANE", "reactor") != "threaded"


class ReactorHTTPServer:
    """serve() facade over one HTTP listener on the shared reactor.
    Exposes the surface the rest of the codebase touches:
    server_address, _stopping, serve_forever(), shutdown(),
    server_close() — stop_server() works unchanged."""

    def __init__(self, addr, router):
        self.router = router
        self._stopping = False
        self._done = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(addr)
        self._sock.listen(512)
        self.server_address = self._sock.getsockname()
        self.socket = self._sock
        self._reactor = get_reactor()
        self._reactor.add_http_listener(self._sock, router, self)

    def serve_forever(self) -> None:
        # the reactor already serves; this blocks for compatibility
        # with callers that dedicate a thread to it
        self._done.wait()

    def shutdown(self) -> None:
        """Stop accepting, abort open keep-alive connections, and
        RELEASE the port — all within a bounded deadline (callers
        immediately rebind on master restart)."""
        self._stopping = True
        self._reactor.remove_listener(self, deadline_s=1.5)
        self._done.set()

    def server_close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
