"""Tiny threaded HTTP server framework + JSON client helpers.

The control-plane transport for this rebuild: the reference exposes HTTP for
object IO and /dir/* master endpoints (weed/server/*_handlers*.go) plus gRPC
for admin; here the admin RPCs are HTTP POST endpoints named after their
reference RPCs (a protobuf/gRPC transport can slot in behind the same
handler functions later).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time as _time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class HttpError(Exception):
    def __init__(self, status: int, message: str = "",
                 headers: Optional[dict] = None):
        super().__init__(message or f"HTTP {status}")
        self.status = status
        self.message = message
        self.headers = headers or {}


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler, match: re.Match):
        self.handler = handler
        self.match = match
        parsed = urllib.parse.urlparse(handler.path)
        self.path = parsed.path
        self.query = {k: v[0] for k, v in urllib.parse.parse_qs(
            parsed.query, keep_blank_values=True).items()}
        self.headers = handler.headers
        self._body: Optional[bytes] = None

    @property
    def body(self) -> bytes:
        if self._body is None:
            length = int(self.headers.get("Content-Length") or 0)
            self._body = self.handler.rfile.read(length) if length else b""
        return self._body

    def json(self) -> dict:
        return json.loads(self.body or b"{}")


class Response:
    def __init__(self, data=None, status: int = 200, raw: Optional[bytes] = None,
                 headers: Optional[dict] = None,
                 file_path: Optional[str] = None,
                 file_range: Optional[tuple[int, int]] = None):
        self.data = data
        self.status = status
        self.raw = raw
        # file streaming: the body is (a range of) a file on disk, sent in
        # bounded chunks — a 30GB .dat copy never materializes in memory
        # (the streaming VolumeEcShardRead / CopyFile analog)
        self.file_path = file_path
        self.file_range = file_range  # (offset, length) or None = whole file
        self.headers = headers or {}


class Router:
    """Method+regex route table shared by master/volume/filer servers.

    When `metrics` is set (a stats._ServerMetrics bundle), every dispatch
    increments the request counter and observes latency, labeled by handler
    name — the per-operation labeling of stats/metrics.go collectors."""

    def __init__(self, name: str = "httpd", metrics=None):
        self.name = name
        self.metrics = metrics
        self.routes: list[tuple[str, re.Pattern, Callable]] = []
        # optional exception -> Response mapper, consulted before the
        # default JSON error mapping (the S3 gateway uses it to emit
        # protocol-correct XML errors)
        self.error_handler: Optional[Callable[[Exception], Optional[Response]]] = None

    def route(self, method: str, pattern: str):
        compiled = re.compile("^" + pattern + "$")

        def deco(fn):
            self.routes.append((method, compiled, fn))
            return fn

        return deco

    def dispatch(self, handler: BaseHTTPRequestHandler, method: str) -> None:
        if getattr(handler.server, "_stopping", False):
            # a stopped server's keep-alive connections outlive
            # server_close(); without this, a client pinned to such a
            # connection keeps talking to the ZOMBIE server object while a
            # fresh server owns the port (master-restart convergence bug)
            handler.close_connection = True
            self._send(handler, Response({"error": "server shutting down"},
                                         status=503,
                                         headers={"Connection": "close"}))
            return
        path = urllib.parse.urlparse(handler.path).path
        for m, pattern, fn in self.routes:
            if m != method:
                continue
            match = pattern.match(path)
            if match:
                t0 = _time.perf_counter()
                req = Request(handler, match)
                try:
                    resp = fn(req)
                except Exception as e:  # noqa: BLE001 — server must not die
                    resp = None
                    if self.error_handler is not None:
                        try:
                            resp = self.error_handler(e)
                        except Exception:
                            resp = None
                    if resp is None:
                        if isinstance(e, HttpError):
                            resp = Response({"error": e.message or str(e)},
                                            status=e.status,
                                            headers=e.headers or None)
                        elif isinstance(e, (KeyError, LookupError)):
                            resp = Response({"error": str(e)}, status=404)
                        else:
                            resp = Response(
                                {"error": f"{type(e).__name__}: {e}"}, status=500)
                if self.metrics is not None:
                    self.metrics.request_counter.inc(fn.__name__)
                    self.metrics.request_histogram.observe(
                        fn.__name__, _time.perf_counter() - t0)
                # drain any unread request body first: responding while the
                # client is still mid-upload resets the connection and the
                # client never sees the (often 4xx) status. Discard in
                # bounded chunks — never buffer a rejected upload.
                try:
                    if req._body is None:
                        left = int(handler.headers.get("Content-Length") or 0)
                        while left > 0:
                            n = len(handler.rfile.read(min(left, 1 << 16)) or b"")
                            if n == 0:
                                break
                            left -= n
                        req._body = b""
                except Exception:
                    pass
                self._send(handler, resp)
                return
        self._send(handler, Response({"error": f"no route {method} {path}"}, status=404))

    @staticmethod
    def _send(handler: BaseHTTPRequestHandler, resp: Response) -> None:
        try:
            if resp.file_path is not None:
                import os as _os

                size = _os.path.getsize(resp.file_path)
                off, length = resp.file_range or (0, size)
                length = max(0, min(length, size - off))
                ctype = resp.headers.pop("Content-Type",
                                         "application/octet-stream")
                handler.send_response(resp.status)
                handler.send_header("Content-Type", ctype)
                handler.send_header("Content-Length", str(length))
                for k, v in resp.headers.items():
                    handler.send_header(k, v)
                handler.end_headers()
                if handler.command != "HEAD":
                    with open(resp.file_path, "rb") as f:
                        f.seek(off)
                        left = length
                        while left > 0:
                            piece = f.read(min(left, 1 << 20))
                            if not piece:
                                break
                            handler.wfile.write(piece)
                            left -= len(piece)
                return
            if resp.raw is not None:
                body = resp.raw
                ctype = resp.headers.pop("Content-Type", "application/octet-stream")
            else:
                body = json.dumps(resp.data if resp.data is not None else {}).encode()
                ctype = "application/json"
            handler.send_response(resp.status)
            handler.send_header("Content-Type", ctype)
            # HEAD responses may declare the real entity size explicitly
            explicit_len = resp.headers.pop("Content-Length", None)
            handler.send_header("Content-Length",
                                explicit_len if explicit_len is not None
                                else str(len(body)))
            for k, v in resp.headers.items():
                handler.send_header(k, v)
            handler.end_headers()
            if handler.command != "HEAD":
                handler.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass


# the extra verbs beyond the big five are the WebDAV set (RFC 4918) used by
# the webdav gateway; BaseHTTPRequestHandler dispatches by do_<METHOD> name
EXTRA_METHODS = ("OPTIONS", "PROPFIND", "PROPPATCH", "MKCOL", "MOVE", "COPY",
                 "LOCK", "UNLOCK")


def serve(router: Router, host: str, port: int,
          tls_context=None) -> ThreadingHTTPServer:
    """Start the threaded server; with tls_context (an ssl.SSLContext from
    security.tls.server_context) the listening socket speaks HTTPS and —
    when the context demands client certs — enforces mTLS."""
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # headers and body flush as separate segments; with Nagle on, the
        # client's delayed ACK stalls every keep-alive exchange ~40ms —
        # the difference between ~20 and ~1000 req/s per connection
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def do_GET(self):
            router.dispatch(self, "GET")

        def do_HEAD(self):
            router.dispatch(self, "HEAD")

        def do_POST(self):
            router.dispatch(self, "POST")

        def do_PUT(self):
            router.dispatch(self, "PUT")

        def do_DELETE(self):
            router.dispatch(self, "DELETE")

    for _m in EXTRA_METHODS:
        setattr(Handler, f"do_{_m}",
                (lambda m: lambda self: router.dispatch(self, m))(_m))

    server = ThreadingHTTPServer((host, port), Handler)
    if tls_context is not None:
        server.socket = tls_context.wrap_socket(server.socket,
                                                server_side=True)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name=f"{router.name}:{port}")
    thread.start()
    return server


# --- cluster TLS ------------------------------------------------------------
# One switch for the whole process (security.toml [tls] analog): when a
# client SSL context is installed, every inter-server URL is upgraded from
# http:// to https:// and verified (optionally with a client cert = mTLS).
_client_tls = None


def set_client_tls(context) -> None:
    """Install (or clear, with None) the process-wide client SSL context."""
    global _client_tls
    _client_tls = context


def _prep_url(url: str):
    """Returns (url, ssl_context) with the scheme upgraded when TLS is on."""
    if _client_tls is not None and url.startswith("http://"):
        return "https://" + url[len("http://"):], _client_tls
    return url, (_client_tls if url.startswith("https://") else None)


# --- pooled keep-alive client ------------------------------------------------
# One persistent TCP_NODELAY connection per (thread, scheme, netloc).  A
# fresh TCP connection per request costs handshake + slow-start and (with
# the tiny request/response segments the control plane sends) falls into
# Nagle/delayed-ACK stalls; pooling is the difference between ~400 and
# many thousands of cluster req/s.

import http.client as _http_client


class _ConnPool(threading.local):
    def __init__(self):
        self.conns: dict = {}


_pool = _ConnPool()


def _pool_connect(scheme: str, netloc: str, timeout: float, ssl_ctx):
    if scheme == "https":
        conn = _http_client.HTTPSConnection(netloc, timeout=timeout,
                                            context=ssl_ctx)
    else:
        conn = _http_client.HTTPConnection(netloc, timeout=timeout)
    conn.connect()
    try:
        conn.sock.setsockopt(__import__("socket").IPPROTO_TCP,
                             __import__("socket").TCP_NODELAY, 1)
    except OSError:  # pragma: no cover
        pass
    return conn


def _pooled_request(method: str, url: str, body: Optional[bytes],
                    headers: Optional[dict], timeout: float
                    ) -> tuple[int, bytes, dict]:
    """One request over the pool; raises OSError family on failure.
    A request that fails with a CONNECTION-staleness error on a REUSED
    connection retries once on a fresh one (the server closed the idle
    keep-alive — it never saw the request).  Timeouts NEVER retry: the
    server may be mid-mutation, and transparently resending a POST
    would execute it twice.  Failures on a brand-new connection
    propagate."""
    import socket as _socket

    url, ssl_ctx = _prep_url(url)
    parsed = urllib.parse.urlsplit(url)
    key = (parsed.scheme, parsed.netloc)
    target = (parsed.path or "/") + (f"?{parsed.query}" if parsed.query
                                     else "")
    from . import faultinject as fi

    if fi._points:
        fi.hit("net.request")
    for _ in range(2):
        conn = _pool.conns.get(key)
        reused = conn is not None
        if conn is None:
            conn = _pool_connect(parsed.scheme, parsed.netloc, timeout,
                                 ssl_ctx)
            _pool.conns[key] = conn
        try:
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            conn.request(method, target, body, headers or {})
            resp = conn.getresponse()
            data = resp.read()
            hdrs = dict(resp.headers)
            if resp.will_close:
                conn.close()
                _pool.conns.pop(key, None)
            return resp.status, data, hdrs
        except (TimeoutError, _socket.timeout):
            conn.close()
            _pool.conns.pop(key, None)
            raise
        except Exception:
            conn.close()
            _pool.conns.pop(key, None)
            if not reused:
                raise
    raise OSError("unreachable")  # pragma: no cover


def _pooled_with_redirects(method: str, url: str, body: Optional[bytes],
                           headers: Optional[dict], timeout: float,
                           follow_redirects: bool
                           ) -> tuple[int, bytes, dict]:
    for _ in range(5):
        status, data, hdrs = _pooled_request(method, url, body, headers,
                                             timeout)
        if follow_redirects and status in (301, 302, 303, 307, 308) \
                and hdrs.get("Location"):
            url = urllib.parse.urljoin(url, hdrs["Location"])
            if status == 303:
                method, body = "GET", None
            continue
        return status, data, hdrs
    return status, data, hdrs


# --- client helpers ---------------------------------------------------------

def stop_server(server) -> None:
    """Shut down a serve() result: stop the loop AND close the listening
    socket — otherwise clients queue in the accept backlog and hang
    instead of failing over.  Surviving keep-alive handler threads see
    _stopping and answer 503 + Connection: close, so pooled clients
    migrate to whoever owns the port next."""
    server._stopping = True
    server.shutdown()
    server.server_close()


def http_json(method: str, url: str, payload: Optional[dict] = None,
              timeout: float = 30.0) -> dict:
    data = json.dumps(payload).encode() if payload is not None else None
    headers = {"Content-Type": "application/json"} if data is not None else {}
    try:
        status, body, _ = _pooled_with_redirects(method, url, data, headers,
                                                 timeout, True)
    except (ConnectionError, TimeoutError, OSError) as e:
        raise HttpError(503, f"{url} unreachable: {e}") from None
    if status >= 400:
        try:
            err = json.loads(body).get("error", body.decode(errors="replace"))
        except Exception:
            err = body.decode(errors="replace")
        raise HttpError(status, err) from None
    return json.loads(body) if body else {}


UNSATISFIABLE_RANGE = (-1, 0)


def parse_range(range_header: str, file_size: int) -> Optional[tuple[int, int]]:
    """Parse an RFC 7233 single range against file_size -> (offset, size);
    None for absent/invalid headers (serve the whole body), or
    UNSATISFIABLE_RANGE when the range starts past EOF (serve 416).
    Handles bytes=N-, bytes=N-M, bytes=-N."""
    if not range_header.startswith("bytes="):
        return None
    lo, dash, hi = range_header[6:].partition("-")
    if not dash:
        return None
    try:
        if lo == "":  # suffix range: last N bytes
            n = int(hi)
            if n == 0 or file_size == 0:
                # RFC 7233: a zero-length suffix (or any suffix of an empty
                # file) has no satisfiable byte range
                return UNSATISFIABLE_RANGE
            offset = max(0, file_size - n)
            return offset, file_size - offset
        offset = int(lo)
        if offset >= file_size:
            return UNSATISFIABLE_RANGE
        if hi == "":
            return offset, file_size - offset
        end = min(int(hi), file_size - 1)
        return offset, end - offset + 1
    except ValueError:
        return None


def http_bytes(method: str, url: str, payload: Optional[bytes] = None,
               headers: Optional[dict] = None, timeout: float = 60.0,
               follow_redirects: bool = True) -> tuple[int, bytes, dict]:
    try:
        return _pooled_with_redirects(method, url, payload, headers,
                                      timeout, follow_redirects)
    except (ConnectionError, TimeoutError, OSError) as e:
        # dead/unreachable server: synthetic status 0 so callers fail over
        return 0, str(e).encode(), {}


def http_download(method: str, url: str, dest_path: str,
                  timeout: float = 3600.0,
                  piece_bytes: int = 1 << 20) -> int:
    """Stream a (possibly huge) response body straight to dest_path in
    bounded pieces — the client half of Response(file_path=...) streaming.
    Writes to dest_path.part and renames on success so a dropped transfer
    never leaves a torn file under the final name.  Returns the HTTP
    status (0 = unreachable)."""
    url, ssl_ctx = _prep_url(url)
    req = urllib.request.Request(url, method=method)
    tmp = dest_path + ".part"
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=ssl_ctx) as r:
            with open(tmp, "wb") as f:
                while True:
                    piece = r.read(piece_bytes)
                    if not piece:
                        break
                    f.write(piece)
            os.replace(tmp, dest_path)
            return r.status
    except urllib.error.HTTPError as e:
        return e.code
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError):
        return 0
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
