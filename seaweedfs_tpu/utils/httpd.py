"""Tiny threaded HTTP server framework + JSON client helpers.

The control-plane transport for this rebuild: the reference exposes HTTP for
object IO and /dir/* master endpoints (weed/server/*_handlers*.go) plus gRPC
for admin; here the admin RPCs are HTTP POST endpoints named after their
reference RPCs (a protobuf/gRPC transport can slot in behind the same
handler functions later).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time as _time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from ..observability import context as _trace_context
from ..observability import get_tracer as _get_tracer
from ..observability import reqlog as _reqlog
from ..observability.tracer import NOOP_SPAN as _NOOP_SPAN
from . import deadline as _deadline
from .deadline import DeadlineExceeded

# the process-global workload recorder (observability/reqlog.py): the
# dispatch chokepoint reads ONE attribute per request while recording
# is off
_RECORDER = _reqlog.get_recorder()


class HttpError(Exception):
    def __init__(self, status: int, message: str = "",
                 headers: Optional[dict] = None):
        super().__init__(message or f"HTTP {status}")
        self.status = status
        self.message = message
        self.headers = headers or {}


def qint(query: dict, name: str, default: Optional[int] = None) -> int:
    """Parse an int query param, answering 400 (not 500) to garbage —
    a typo'd ?limit=abc is the CLIENT's mistake and must not burn the
    error-ratio SLO budget.  With no `default` the parameter is
    REQUIRED: absence answers 400 too, never a silent zero.  The
    weedlint W601 rule enforces that every route handler parses params
    this way (or with its own try/ValueError -> HttpError(400))."""
    raw = query.get(name)
    if raw is None or raw == "":
        if default is None:
            raise HttpError(400, f"missing query parameter {name}")
        return default
    try:
        return int(raw)
    except (TypeError, ValueError):
        raise HttpError(400, f"bad query parameter {name}={raw!r}: "
                             f"expected an integer")


def qfloat(query: dict, name: str,
           default: Optional[float] = None) -> float:
    """Float twin of qint: malformed or missing-required input answers
    400, never 500."""
    raw = query.get(name)
    if raw is None or raw == "":
        if default is None:
            raise HttpError(400, f"missing query parameter {name}")
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        raise HttpError(400, f"bad query parameter {name}={raw!r}: "
                             f"expected a number")


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler, match: re.Match):
        self.handler = handler
        self.match = match
        parsed = urllib.parse.urlparse(handler.path)
        # %-escapes are decoded HERE, once, like Go's r.URL.Path (the
        # reference handlers all consume the decoded form); handlers and
        # route regexes see real names, clients re-quote when building URLs.
        # raw_path keeps the wire form for signature canonicalization
        # (SigV4 must see what the client signed, like Go's URL.RawPath)
        self.raw_path = parsed.path
        self.path = urllib.parse.unquote(parsed.path)
        self.query = {k: v[0] for k, v in urllib.parse.parse_qs(
            parsed.query, keep_blank_values=True).items()}
        self.headers = handler.headers
        self._body: Optional[bytes] = None

    @property
    def body(self) -> bytes:
        if self._body is None:
            length = int(self.headers.get("Content-Length") or 0)
            self._body = self.handler.rfile.read(length) if length else b""
        return self._body

    def json(self) -> dict:
        return json.loads(self.body or b"{}")


def parse_form_data(body: bytes, content_type: str) -> dict:
    """Minimal multipart/form-data parser for POST uploads: returns
    {field: str} plus {"file": bytes, "file.name": str} for the file
    part.  Per the S3 POST contract, fields after `file` are ignored."""
    m = re.search(r'boundary="?([^";]+)"?', content_type)
    if not m:
        raise ValueError("no multipart boundary")
    # RFC 2046 delimiters are CRLF--boundary, NOT the bare boundary
    # bytes — a file whose CONTENT contains the boundary string must
    # survive.  Prefixing CRLF makes the first (dashless) delimiter
    # uniform with the rest.
    sep = b"\r\n--" + m.group(1).encode()
    fields: dict = {}
    for part in (b"\r\n" + body).split(sep)[1:]:
        if part.startswith(b"--"):
            break  # closing delimiter
        part = part.lstrip(b" \t")  # transport padding after boundary
        if part.startswith(b"\r\n"):
            part = part[2:]
        head, hsep, payload = part.partition(b"\r\n\r\n")
        if not hsep and not head.strip():
            continue
        disp = ""
        ptype = ""
        for line in head.split(b"\r\n"):
            low = line.lower()
            if low.startswith(b"content-disposition:"):
                disp = line.decode(errors="replace")
            elif low.startswith(b"content-type:"):
                ptype = line.split(b":", 1)[1].strip().decode(errors="replace")
        nm = re.search(r'name="([^"]*)"', disp)
        name = nm.group(1) if nm else ""
        if name.lower() == "file":
            fn = re.search(r'filename="([^"]*)"', disp)
            fields["file"] = payload
            fields["file.name"] = fn.group(1) if fn else ""
            if ptype:
                fields.setdefault("content-type", ptype)
            break  # everything after the file part is ignored
        fields[name.lower()] = payload.decode(errors="replace")
    return fields


def extract_upload(body: bytes, content_type: str) -> tuple[bytes, str, str]:
    """-> (data, filename, mime) for a write-request body: unwraps one
    multipart/form-data file part the way the reference's needle
    ParseUpload does (needle_parse_upload.go:37-76); raw bodies pass
    through with the request Content-Type as the mime."""
    if content_type and content_type.lower().startswith("multipart/form-data"):
        try:
            fields = parse_form_data(body, content_type)
        except ValueError as e:
            raise HttpError(400, str(e))  # client framing error, not a 500
        if "file" in fields:
            # basename only (needle_parse_upload.go:141 path.Base): a
            # crafted filename must not escape the target directory
            fname = fields.get("file.name", "")
            fname = fname.replace("\\", "/").rsplit("/", 1)[-1]
            return fields["file"], fname, fields.get("content-type", "")
    return body, "", content_type


class Response:
    def __init__(self, data=None, status: int = 200, raw: Optional[bytes] = None,
                 headers: Optional[dict] = None,
                 file_path: Optional[str] = None,
                 file_range: Optional[tuple[int, int]] = None):
        self.data = data
        self.status = status
        self.raw = raw
        # file streaming: the body is (a range of) a file on disk, sent in
        # bounded chunks — a 30GB .dat copy never materializes in memory
        # (the streaming VolumeEcShardRead / CopyFile analog)
        self.file_path = file_path
        self.file_range = file_range  # (offset, length) or None = whole file
        self.headers = headers or {}


class Router:
    """Method+regex route table shared by master/volume/filer servers.

    When `metrics` is set (a stats._ServerMetrics bundle), every dispatch
    increments the request counter and observes latency, labeled by handler
    name — the per-operation labeling of stats/metrics.go collectors."""

    def __init__(self, name: str = "httpd", metrics=None):
        self.name = name
        self.metrics = metrics
        self.routes: list[tuple[str, re.Pattern, Callable]] = []
        # optional exception -> Response mapper, consulted before the
        # default JSON error mapping (the S3 gateway uses it to emit
        # protocol-correct XML errors)
        self.error_handler: Optional[Callable[[Exception], Optional[Response]]] = None
        # optional admission controller (utils/admission.py): servers
        # started with -maxInflight > 0 install one; None costs a
        # single attribute check per request
        self.admission = None
        # optional loop fast-path probe (utils/eventloop.py): when set,
        # the reactor asks `probe(method, path) -> bool` whether a
        # GET/HEAD can dispatch INLINE on the event loop (the volume
        # server answers True only for needle-cache-resident objects).
        # None = every request dispatches on the worker pool.
        self.loop_fast_probe = None
        # optional heat accumulator (observability/heat.py): the volume
        # server installs its per-server HeatAccumulator so every
        # object-route response feeds decayed per-volume/per-needle
        # heat.  None costs a single attribute check per request.
        self.heat = None
        # optional resource ledger (observability/ledger.py): servers
        # install their per-server RequestLedger so every dispatched
        # request settles its thread-CPU / bytes / queue-wait into the
        # per-route and per-client cost tables.  None costs a single
        # attribute check per request.
        self.ledger = None
        # deadline_exceeded journal rate limit (the counter counts every
        # 504; the ring must not churn under a deadline storm).  A lost
        # write race costs at most one extra journal event.
        self._last_ddl_event = 0.0

    def _note_deadline_exceeded(self) -> None:
        """Count + journal (rate-limited) one budget-spent 504."""
        from ..stats import request_plane_metrics

        request_plane_metrics().deadline_exceeded.inc(self.name)
        now = _time.monotonic()
        if now - self._last_ddl_event >= 1.0:
            self._last_ddl_event = now
            from ..observability import events as _events

            _events.emit("deadline_exceeded", role=self.name)

    def route(self, method: str, pattern: str):
        compiled = re.compile("^" + pattern + "$")

        def deco(fn):
            self.routes.append((method, compiled, fn))
            if self.metrics is not None:
                # pre-touch each handler's series at registration so
                # /metrics exposes zero-valued counters and full (+Inf/
                # _sum/_count) histograms before first traffic — absent
                # series break rate() dashboards and alerts
                self.metrics.request_counter.labels(fn.__name__)
                self.metrics.request_histogram.labels(fn.__name__)
                errs = getattr(self.metrics, "request_errors", None)
                if errs is not None:
                    errs.labels(fn.__name__)
            return fn

        return deco

    def dispatch(self, handler: BaseHTTPRequestHandler, method: str) -> None:
        if getattr(handler.server, "_stopping", False):
            # a stopped server's keep-alive connections outlive
            # server_close(); without this, a client pinned to such a
            # connection keeps talking to the ZOMBIE server object while a
            # fresh server owns the port (master-restart convergence bug)
            handler.close_connection = True
            self._send(handler, Response({"error": "server shutting down"},
                                         status=503,
                                         headers={"Connection": "close"}))
            return
        path = urllib.parse.unquote(urllib.parse.urlparse(handler.path).path)
        # resource-ledger entry stamp (observability/ledger.py): minted
        # ON the executing thread — thread-CPU clocks are per-thread,
        # so the reactor's worker handoff needs the stamp here, not at
        # parse time (queue wait rides separately in
        # handler.queue_wait_s, stamped by the reactor at handoff)
        ledger = self.ledger
        ltok = ledger.begin() if ledger is not None else None
        # distributed-trace ingress (observability/context.py): adopt the
        # caller's Traceparent (or make a fresh head-based sampling
        # decision) for the duration of this request, restoring the
        # thread-local afterwards — handler threads are pooled per
        # connection, and a leaked context would bleed into the next
        # request.  Gated on tracer.enabled so the dormant hot-path cost
        # stays one attribute check; with tracing on, an unsampled
        # request pays one header parse + one random() and every span
        # call below degrades to the shared no-op.
        tracer = _get_tracer()
        tctx = _prev_ctx = _prev_srv = None
        traced = False
        if tracer.enabled:
            tctx, _prev_ctx = _trace_context.begin_request(handler.headers)
            traced = True
            # stamp this thread with the OWNING server's identity so
            # spans attribute per-server even when several servers share
            # one process tracer (`weed server`, in-process fixtures);
            # servers set router.server_url to their advertised url, the
            # Host header stands in for routers that never did
            _prev_srv = _trace_context.swap_server(
                getattr(self, "server_url", None)
                or handler.headers.get("Host"))
        # deadline ingress (utils/deadline.py): adopt the caller's
        # X-Weed-Deadline (re-anchored to the local monotonic clock)
        # for the duration of this request, restored in the finally —
        # the same pooled-thread hygiene as the trace context.  Costs
        # one header get when absent.
        ddl, _prev_ddl = _deadline.begin_request(handler.headers)
        try:
            for m, pattern, fn in self.routes:
                if m != method:
                    continue
                match = pattern.match(path)
                if not match:
                    continue
                t0 = _time.perf_counter()
                req = Request(handler, match)
                admission = self.admission
                admitted = False
                shed = False
                # request span: the path carries the needle/volume id for
                # object routes (/<vid>,<fid>), so a trace timeline can be
                # joined back to specific keys.  The span re-roots under
                # the caller's span id (the trace context's parent), which
                # is the edge the master-side collector stitches on.
                # gate on the SAMPLED context, not just tracer.enabled:
                # at 1% sampling the other 99% of requests skip even the
                # span-name f-string and attrs dict
                try:
                    if ddl is not None and ddl.expired():
                        # the caller's budget is already spent: a
                        # 504-style answer NOW beats doing work nobody
                        # will read — and the moment is counted +
                        # journaled, so budget exhaustion pages instead
                        # of hiding inside generic timeouts
                        self._note_deadline_exceeded()
                        resp = Response(
                            {"error": "deadline exceeded before "
                                      "dispatch"}, status=504)
                    elif admission is not None \
                            and not admission.exempt(path) \
                            and not admission.try_acquire():
                        # over the inflight bound: shed with a fast 503
                        # + Retry-After instead of queueing into a late
                        # timeout (try_acquire counted + journaled it).
                        # Close the connection so the unread body is
                        # the accept loop's bounded-drain problem, not
                        # a keep-alive desync.
                        handler.close_connection = True
                        shed = True
                        resp = Response(
                            {"error": "overloaded: request shed"},
                            status=503,
                            headers={"Retry-After": "1",
                                     "Connection": "close"})
                    else:
                        admitted = admission is not None \
                            and not admission.exempt(path)
                        if tctx is not None:
                            with tracer.span(f"http.{self.name}.{fn.__name__}",
                                             method=method, path=path):
                                resp = fn(req)
                        else:
                            resp = fn(req)
                except Exception as e:  # noqa: BLE001 — server must not die
                    resp = None
                    if self.error_handler is not None:
                        try:
                            resp = self.error_handler(e)
                        except Exception:
                            resp = None
                    if resp is None:
                        if isinstance(e, DeadlineExceeded):
                            # the budget ran out DURING the handler
                            # (usually at a downstream egress whose
                            # clamp fired): 504, same accounting as the
                            # pre-dispatch check
                            self._note_deadline_exceeded()
                            resp = Response(
                                {"error": str(e) or "deadline exceeded"},
                                status=504)
                        elif isinstance(e, HttpError):
                            # http_bytes signals an UNREACHABLE peer as
                            # synthetic status 0; a handler re-raising
                            # it must answer 502, not emit an invalid
                            # "HTTP/1.1 0" status line — clients parse
                            # sub-200 as an interim response and hang
                            # waiting for the real one (found by the
                            # scenario engine's partition drill)
                            resp = Response({"error": e.message or str(e)},
                                            status=e.status
                                            if e.status >= 100 else 502,
                                            headers=e.headers or None)
                        elif isinstance(e, (KeyError, LookupError)):
                            resp = Response({"error": str(e)}, status=404)
                        else:
                            resp = Response(
                                {"error": f"{type(e).__name__}: {e}"}, status=500)
                try:
                    if self.metrics is not None:
                        self.metrics.request_counter.inc(fn.__name__)
                        if resp.status >= 500:
                            # per-route 5xx counter: the burn-rate SLO's
                            # numerator (guarded: custom metrics bundles
                            # may predate the family)
                            errs = getattr(self.metrics, "request_errors",
                                           None)
                            if errs is not None:
                                errs.inc(fn.__name__)
                        # RED histogram keyed by route; sampled requests
                        # attach their trace id as an exemplar, so a latency
                        # outlier on /metrics links straight to the stitched
                        # trace that explains it
                        self.metrics.request_histogram.observe(
                            fn.__name__, _time.perf_counter() - t0,
                            exemplar=tctx.trace_id if tctx is not None
                            else None)
                    if tctx is not None:
                        # hand the trace id back so callers (bench, tests,
                        # curl -v) can fetch the stitched cluster trace
                        resp.headers.setdefault("X-Trace-Id", tctx.trace_id)
                    # drain any unread request body first: responding while
                    # the client is still mid-upload resets the connection
                    # and the client never sees the (often 4xx) status.
                    # Discard in bounded chunks — never buffer a rejected
                    # upload.  ONLY a shed skips this (it already marked
                    # the connection closing; the accept loop's bounded
                    # pre-close drain protects the 503) — shedding must
                    # stay a microseconds-fast "no", but an ordinary
                    # Connection: close client's rejected upload still
                    # needs the full drain or the close RSTs its error
                    # response away.
                    if req._body is None and not shed:
                        self._drain_body(handler)
                        req._body = b""
                    self._send(handler, resp)
                    if _RECORDER.enabled:
                        # workload flight recorder (observability/
                        # reqlog.py): one sampled access record per
                        # dispatched request, redacted BEFORE it can
                        # reach the ring.  Sits after _send so the
                        # duration covers the transmission (for
                        # streamed reads the send IS the work).
                        self._record_access(handler, method, fn.__name__,
                                            req, resp, shed, ddl, t0)
                    heat = self.heat
                    if heat is not None:
                        # heat accounting (observability/heat.py): the
                        # fid regex inside note_http gates before any
                        # locking, so control-plane routes cost one
                        # attribute check + one failed regex match
                        try:
                            heat.note_http(
                                method, path, resp.status,
                                self._resp_bytes(resp),
                                tctx.trace_id if tctx is not None
                                else "")
                        except Exception:
                            pass  # accounting never breaks serving
                    if ledger is not None:
                        # resource ledger settle (observability/
                        # ledger.py): CPU delta + bytes + queue wait
                        # into the route/client cost tables.  Sits
                        # after _send like the recorder, so on-loop
                        # fast-path stalls measure the whole hold.
                        try:
                            ledger.settle_http(
                                ltok, method, path, fn.__name__,
                                resp.status, len(req._body or b""),
                                self._resp_bytes(resp),
                                handler.client_address[0]
                                if handler.client_address else "",
                                tctx.trace_id if tctx is not None
                                else "",
                                getattr(handler, "queue_wait_s", 0.0),
                                query=req.query)
                        except Exception:
                            pass  # accounting never breaks serving
                finally:
                    # release only after the RESPONSE left: for large
                    # streamed reads (Response(file_path=...)) the send
                    # IS the work — releasing at handler return would
                    # let unbounded concurrent transmissions pile up
                    # behind an "empty" admission gate
                    if admitted:
                        admission.release()
                return
            # 404 fallthrough: the body was never read, so drain it too or
            # the keep-alive loop would parse the leftover bytes as the next
            # request line (request-smuggling-shaped desync).
            self._drain_body(handler)
            self._send(handler, Response(
                {"error": f"no route {method} {path}"}, status=404))
        finally:
            _deadline.end_request(_prev_ddl)
            if traced:
                _trace_context.end_request(_prev_ctx)
                _trace_context.swap_server(_prev_srv)

    @staticmethod
    def _resp_bytes(resp: Response) -> int:
        """Cheap out-byte estimate for heat accounting — attribute
        checks only, never a syscall (reqlog's getsize fallback is too
        expensive for every response): an unranged streamed file reads
        as 0, so byte rates are a floor, not an exact meter."""
        if resp.raw is not None:
            return len(resp.raw)
        if resp.file_range is not None:
            _off, length = resp.file_range
            return length if length >= 0 else 0
        if resp.data is not None:
            return len(str(resp.data))
        return 0

    @staticmethod
    def _record_access(handler, method: str, handler_name: str,
                       req: Request, resp: Response, shed: bool,
                       ddl, t0: float) -> None:
        """One sampled workload access record (observability/reqlog.py).
        Only runs when the recorder is enabled; everything costly
        (redaction, byte accounting) happens here, after the cheap
        gate.  Never raises into the serving path."""
        try:
            if resp.raw is not None:
                out = len(resp.raw)
            elif resp.file_path is not None:
                _off, length = resp.file_range or (0, -1)
                out = length if length >= 0 else \
                    os.path.getsize(resp.file_path)
            elif resp.data is not None:
                # cheap size estimate without re-serializing the body
                out = len(str(resp.data))
            else:
                out = 0
            try:
                bytes_in = int(handler.headers.get("Content-Length") or 0)
            except (TypeError, ValueError):
                bytes_in = 0
            peer = ""
            addr = getattr(handler, "client_address", None)
            if addr:
                peer = str(addr[0])
            path = _reqlog.redact_query(handler.path)
            dur_s = _time.perf_counter() - t0
            # the recorded budget is the caller's budget at INGRESS
            # (what a replay spec's deadline_s should reproduce), not
            # what was left after the handler ran
            _RECORDER.record(
                _reqlog.classify_route(method, req.path, handler_name,
                                       query=req.query),
                method, path, resp.status,
                bytes_in=bytes_in, bytes_out=out,
                duration_ms=dur_s * 1e3,
                deadline_s=(ddl.remaining() + dur_s
                            if ddl is not None else None),
                shed=shed, degraded=resp.status >= 500, peer=peer,
                handler=handler_name)
        except Exception:
            pass  # recording must never break the serving path

    @staticmethod
    def _drain_body(handler: BaseHTTPRequestHandler) -> None:
        try:
            te = (handler.headers.get("Transfer-Encoding") or "").lower()
            if "chunked" in te:
                # Request.body only understands Content-Length; a chunked body
                # can't be framed, so the connection must not be reused.
                handler.close_connection = True
                return
            left = int(handler.headers.get("Content-Length") or 0)
            while left > 0:
                n = len(handler.rfile.read(min(left, 1 << 16)) or b"")
                if n == 0:
                    break
                left -= n
        except Exception:
            try:
                handler.close_connection = True
            except Exception:
                pass

    @staticmethod
    def _send(handler: BaseHTTPRequestHandler, resp: Response) -> None:
        try:
            if resp.file_path is not None:
                import os as _os

                size = _os.path.getsize(resp.file_path)
                off, length = resp.file_range or (0, size)
                length = max(0, min(length, size - off))
                ctype = resp.headers.pop("Content-Type",
                                         "application/octet-stream")
                handler.send_response(resp.status)
                handler.send_header("Content-Type", ctype)
                handler.send_header("Content-Length", str(length))
                for k, v in resp.headers.items():
                    handler.send_header(k, v)
                handler.end_headers()
                if handler.command != "HEAD":
                    # reactor connections take the zero-copy road: the
                    # loop streams the region with os.sendfile and a
                    # slow client costs an outbox entry, not a thread
                    sendfile = getattr(handler, "sendfile", None)
                    if sendfile is not None and sendfile(
                            resp.file_path, off, length):
                        return
                    with open(resp.file_path, "rb") as f:
                        f.seek(off)
                        left = length
                        while left > 0:
                            piece = f.read(min(left, 1 << 20))
                            if not piece:
                                break
                            handler.wfile.write(piece)
                            left -= len(piece)
                return
            if resp.raw is not None:
                body = resp.raw
                ctype = resp.headers.pop("Content-Type", "application/octet-stream")
            else:
                body = json.dumps(resp.data if resp.data is not None else {}).encode()
                ctype = "application/json"
            handler.send_response(resp.status)
            handler.send_header("Content-Type", ctype)
            # HEAD responses may declare the real entity size explicitly
            explicit_len = resp.headers.pop("Content-Length", None)
            handler.send_header("Content-Length",
                                explicit_len if explicit_len is not None
                                else str(len(body)))
            for k, v in resp.headers.items():
                handler.send_header(k, v)
            handler.end_headers()
            if handler.command != "HEAD":
                handler.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass


# the extra verbs beyond the big five are the WebDAV set (RFC 4918) used by
# the webdav gateway; BaseHTTPRequestHandler dispatches by do_<METHOD> name
EXTRA_METHODS = ("OPTIONS", "PROPFIND", "PROPPATCH", "MKCOL", "MOVE", "COPY",
                 "LOCK", "UNLOCK")


# --- fast threaded HTTP/1.1 server -------------------------------------------
# Drop-in replacement for http.server: same Router/handler contract, but the
# request line and headers are parsed by hand instead of through
# email.parser (BaseHTTPRequestHandler's dominant per-request cost), and
# status+headers go out in ONE sendall.  On the 1-core bench this roughly
# doubles object-path req/s (ref: weed/server/volume_server_handlers_read.go
# serves the same hot path from net/http, which does the equivalent
# hand-rolled parsing in Go).  Set WEED_HTTPD=stdlib to fall back.


class CIHeaders:
    """Case-insensitive request-header view: .get/[]/in by any case,
    .items() preserves the wire case (SigV4 canonicalization lowercases
    for itself)."""

    __slots__ = ("_pairs", "_lower")

    def __init__(self, pairs: list):
        self._pairs = pairs
        self._lower = {}
        for k, v in pairs:
            lk = k.lower()
            # first value wins, matching email.Message.get
            if lk not in self._lower:
                self._lower[lk] = v

    def get(self, key: str, default=None):
        return self._lower.get(key.lower(), default)

    def __getitem__(self, key: str):
        return self._lower[key.lower()]

    def __contains__(self, key) -> bool:
        return key.lower() in self._lower

    def __iter__(self):
        return (k for k, _ in self._pairs)

    def items(self):
        return list(self._pairs)

    def keys(self):
        return [k for k, _ in self._pairs]

    def values(self):
        return [v for _, v in self._pairs]

    def __len__(self):
        return len(self._pairs)


_date_cache: tuple[int, str] = (0, "")


def _http_date() -> str:
    """RFC 7231 Date, cached per second (strftime per request is real
    cost at tens of thousands of req/s)."""
    global _date_cache
    now = int(_time.time())
    if _date_cache[0] != now:
        _date_cache = (now, _time.strftime(
            "%a, %d %b %Y %H:%M:%S GMT", _time.gmtime(now)))
    return _date_cache[1]


class _FastHandler:
    """Per-connection handler exposing exactly the BaseHTTPRequestHandler
    surface Router uses: command/path/headers/rfile/wfile/client_address/
    close_connection/server + send_response/send_header/end_headers."""

    __slots__ = ("server", "rfile", "wfile", "client_address", "command",
                 "path", "headers", "close_connection", "_out")

    def __init__(self, server, rfile, wfile, client_address):
        self.server = server
        self.rfile = rfile
        self.wfile = wfile
        self.client_address = client_address
        self.command = ""
        self.path = ""
        self.headers: Optional[CIHeaders] = None
        self.close_connection = True
        self._out: list = []

    def send_response(self, status: int, message: str = "") -> None:
        self._out = [b"HTTP/1.1 %d %s\r\nDate: %s\r\n"
                     % (status, (message or _REASONS.get(status, "OK")).encode(),
                        _http_date().encode())]

    def send_header(self, key: str, value) -> None:
        self._out.append(f"{key}: {value}\r\n".encode())
        if key.lower() == "connection" and str(value).lower() == "close":
            self.close_connection = True

    def end_headers(self) -> None:
        self._out.append(b"\r\n")
        self.wfile.write(b"".join(self._out))
        self._out = []


_REASONS = {200: "OK", 201: "Created", 204: "No Content",
            206: "Partial Content", 301: "Moved Permanently", 302: "Found",
            303: "See Other", 304: "Not Modified", 307: "Temporary Redirect",
            400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
            404: "Not Found", 405: "Method Not Allowed", 409: "Conflict",
            412: "Precondition Failed", 416: "Range Not Satisfiable",
            500: "Internal Server Error", 503: "Service Unavailable"}


class _SockWriter:
    """Unbuffered writer over a socket: each .write is one sendall (the
    Router batches status+headers itself; bodies are already chunked)."""

    __slots__ = ("_sock",)

    def __init__(self, sock):
        self._sock = sock

    def write(self, data) -> int:
        self._sock.sendall(data)
        return len(data)

    def flush(self) -> None:
        pass


class FastHTTPServer:
    """Threaded accept loop + thread-per-connection keep-alive handling.
    Exposes the ThreadingHTTPServer surface the rest of the codebase
    touches: server_address, _stopping, shutdown(), server_close()."""

    daemon_threads = True

    def __init__(self, addr, router: Router, tls_context=None):
        import socket

        self.router = router
        self._tls = tls_context
        self._stopping = False
        self._done = threading.Event()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(addr)
        self._sock.listen(256)
        self.server_address = self._sock.getsockname()
        self.socket = self._sock

    def serve_forever(self) -> None:
        import selectors

        # poll + flag instead of a bare blocking accept: close()ing a
        # socket does NOT wake a thread blocked in accept(), and the
        # kernel keeps the LISTEN alive while that thread holds it — the
        # old port would stay bound and a same-port restart would fail
        sel = selectors.DefaultSelector()
        sel.register(self._sock, selectors.EVENT_READ)
        try:
            while not self._stopping:
                if not sel.select(timeout=0.25):
                    continue
                try:
                    conn, peer = self._sock.accept()
                except OSError:
                    break  # listener closed
                t = threading.Thread(target=self._handle, args=(conn, peer),
                                     daemon=True)
                t.start()
        finally:
            sel.close()
            try:
                self._sock.close()
            except OSError:
                pass
            self._done.set()

    def _handle(self, conn, peer) -> None:
        import socket

        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._tls is not None:
                conn = self._tls.wrap_socket(conn, server_side=True)
            rfile = conn.makefile("rb", buffering=1 << 16)
            wfile = _SockWriter(conn)
            h = _FastHandler(self, rfile, wfile, peer)
            while not self._stopping:
                line = rfile.readline(1 << 16)
                if not line or line in (b"\r\n", b"\n"):
                    break
                if not line.endswith(b"\n"):
                    # a 64KB+ request line would otherwise be split and
                    # parsed as two garbage requests
                    conn.sendall(b"HTTP/1.1 414 URI Too Long\r\n"
                                 b"Content-Length: 0\r\n"
                                 b"Connection: close\r\n\r\n")
                    break
                try:
                    method, _, rest = line.rstrip(b"\r\n").partition(b" ")
                    target, _, version = rest.rpartition(b" ")
                    h.command = method.decode("ascii")
                    h.path = target.decode("iso-8859-1")
                except (UnicodeDecodeError, ValueError):
                    break
                pairs = []
                overflow = False
                while True:
                    hl = rfile.readline(1 << 16)
                    if hl in (b"\r\n", b"\n", b""):
                        break
                    if len(pairs) >= 100 or not hl.endswith(b"\n"):
                        # stdlib's email.parser enforced ~100 headers;
                        # unbounded headers (or an unterminated 64KB+
                        # line) is a memory-exhaustion vector
                        overflow = True
                        break
                    k, _, v = hl.partition(b":")
                    pairs.append((k.decode("iso-8859-1"),
                                  v.strip().decode("iso-8859-1")))
                if overflow:
                    conn.sendall(b"HTTP/1.1 431 Request Header Fields Too "
                                 b"Large\r\nContent-Length: 0\r\n"
                                 b"Connection: close\r\n\r\n")
                    break
                h.headers = CIHeaders(pairs)
                if "chunked" in (h.headers.get("Transfer-Encoding") or "").lower():
                    # Request.body only frames Content-Length bodies; a
                    # chunked body can't be skipped safely, so refuse and
                    # close rather than desync the keep-alive stream
                    conn.sendall(b"HTTP/1.1 501 Not Implemented\r\n"
                                 b"Content-Length: 0\r\n"
                                 b"Connection: close\r\n\r\n")
                    break
                # HTTP/1.1 defaults to keep-alive; 1.0 to close
                conn_hdr = (h.headers.get("Connection") or "").lower()
                h.close_connection = (
                    conn_hdr == "close"
                    or (version == b"HTTP/1.0" and conn_hdr != "keep-alive"))
                if (h.headers.get("Expect") or "").lower() == "100-continue":
                    # curl sends this for big uploads and stalls ~1s
                    # waiting for the interim response
                    conn.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
                self.router.dispatch(h, h.command)
                if h.close_connection:
                    break
        except (OSError, ValueError):
            pass
        finally:
            # half-close + brief drain before close: closing with unread
            # bytes in the receive queue sends RST, which can destroy an
            # already-sent error response (414/431/501 paths reject
            # requests whose remainder is still in flight)
            try:
                conn.shutdown(socket.SHUT_WR)
                conn.settimeout(0.5)
                deadline = _time.monotonic() + 2.0
                drained = 0
                while _time.monotonic() < deadline and drained < (1 << 22):
                    piece = conn.recv(1 << 16)
                    if not piece:
                        break
                    drained += len(piece)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def shutdown(self) -> None:
        """Stop accepting and RELEASE the port before returning (callers
        immediately rebind on master restart)."""
        self._stopping = True  # weedlint: disable=W502 monotonic shutdown latch: single atomic bool store, the accept loop reads it once per iteration and either value is safe
        self._done.wait(timeout=5.0)

    def server_close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def _serve_stdlib(router: Router, host: str, port: int,
                  tls_context=None) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        # headers and body flush as separate segments; with Nagle on, the
        # client's delayed ACK stalls every keep-alive exchange ~40ms —
        # the difference between ~20 and ~1000 req/s per connection
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # quiet by default
            pass

        def do_GET(self):
            router.dispatch(self, "GET")

        def do_HEAD(self):
            router.dispatch(self, "HEAD")

        def do_POST(self):
            router.dispatch(self, "POST")

        def do_PUT(self):
            router.dispatch(self, "PUT")

        def do_DELETE(self):
            router.dispatch(self, "DELETE")

    for _m in EXTRA_METHODS:
        setattr(Handler, f"do_{_m}",
                (lambda m: lambda self: router.dispatch(self, m))(_m))

    server = ThreadingHTTPServer((host, port), Handler)
    if tls_context is not None:
        server.socket = tls_context.wrap_socket(server.socket,
                                                server_side=True)
    server.daemon_threads = True
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name=f"{router.name}:{port}")
    thread.start()
    return server


def serve(router: Router, host: str, port: int, tls_context=None):
    """Start the HTTP front; with tls_context (an ssl.SSLContext from
    security.tls.server_context) the listening socket speaks HTTPS and —
    when the context demands client certs — enforces mTLS.

    Default: register the listener on the shared event-loop dataplane
    (utils/eventloop.py) — keep-alive/pipelined parsing on the reactor,
    dispatch on its bounded worker pool, zero-copy writeback.  TLS
    sockets stay on the threaded server (the reactor's non-blocking
    parse has no handshake state machine).  WEED_DATAPLANE=threaded or
    WEED_HTTPD=threaded force the thread-per-connection FastHTTPServer;
    WEED_HTTPD=stdlib falls all the way back to http.server."""
    if os.environ.get("WEED_HTTPD") == "stdlib":
        return _serve_stdlib(router, host, port, tls_context)
    from . import eventloop

    if tls_context is None and eventloop.reactor_enabled() \
            and os.environ.get("WEED_HTTPD") != "threaded":
        return eventloop.ReactorHTTPServer((host, port), router)
    server = FastHTTPServer((host, port), router, tls_context)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name=f"{router.name}:{server.server_address[1]}")
    thread.start()
    return server


# --- cluster TLS ------------------------------------------------------------
# One switch for the whole process (security.toml [tls] analog): when a
# client SSL context is installed, every inter-server URL is upgraded from
# http:// to https:// and verified (optionally with a client cert = mTLS).
_client_tls = None


def set_client_tls(context) -> None:
    """Install (or clear, with None) the process-wide client SSL context."""
    global _client_tls
    _client_tls = context


def _prep_url(url: str):
    """Returns (url, ssl_context) with the scheme upgraded when TLS is on."""
    if _client_tls is not None and url.startswith("http://"):
        return "https://" + url[len("http://"):], _client_tls
    return url, (_client_tls if url.startswith("https://") else None)


# --- pooled keep-alive client ------------------------------------------------
# One persistent TCP_NODELAY connection per (thread, scheme, netloc).  A
# fresh TCP connection per request costs handshake + slow-start and (with
# the tiny request/response segments the control plane sends) falls into
# Nagle/delayed-ACK stalls; pooling is the difference between ~400 and
# many thousands of cluster req/s.  The connection itself is raw-socket
# HTTP/1.1 rather than http.client: the stdlib client re-parses every
# response through email.parser, which measured ~4x slower than this
# hand-rolled exchange on the cluster hot path.


class _RawConn:
    """Minimal keep-alive HTTP/1.1 exchange over one socket: hand-built
    request bytes out, hand-parsed status/headers/body in.  Supports
    Content-Length and chunked bodies, and read-to-close for legacy
    peers."""

    __slots__ = ("sock", "rfile", "host")

    def __init__(self, scheme: str, netloc: str, timeout: float, ssl_ctx):
        import socket as _socket

        host, _, port_s = netloc.partition(":")
        port = int(port_s) if port_s else (443 if scheme == "https" else 80)
        self.host = netloc
        sock = _socket.create_connection((host, port), timeout=timeout)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        if scheme == "https":
            import ssl as _ssl

            ctx = ssl_ctx or _ssl.create_default_context()
            sock = ctx.wrap_socket(sock, server_hostname=host)
        self.sock = sock
        self.rfile = sock.makefile("rb", buffering=1 << 16)

    def request(self, method: str, target: str, body: Optional[bytes],
                headers: dict) -> tuple[int, bytes, dict, bool]:
        """-> (status, body, headers, will_close)"""
        out = [f"{method} {target} HTTP/1.1\r\nHost: {self.host}\r\n"
               .encode("latin-1")]
        has_len = False
        for k, v in headers.items():
            lk = k.lower()
            if lk == "host":
                continue  # already sent
            if lk == "content-length":
                has_len = True
            out.append(f"{k}: {v}\r\n".encode("latin-1"))
        if body is not None and not has_len:
            out.append(b"Content-Length: %d\r\n" % len(body))
        elif body is None and method in ("POST", "PUT"):
            out.append(b"Content-Length: 0\r\n")
        out.append(b"\r\n")
        if body:
            out.append(body)
        self.sock.sendall(b"".join(out))
        while True:  # interim 1xx responses are swallowed
            line = self.rfile.readline(1 << 16)
            if not line:
                raise ConnectionError("connection closed by peer")
            parts = line.split(None, 2)
            if len(parts) < 2 or not parts[0].startswith(b"HTTP/"):
                raise ConnectionError(f"bad status line {line!r}")
            status = int(parts[1])
            version = parts[0]
            hdrs: dict = {}
            while True:
                hl = self.rfile.readline(1 << 16)
                if hl in (b"\r\n", b"\n", b""):
                    break
                k, _, v = hl.partition(b":")
                hdrs[k.decode("latin-1")] = v.strip().decode("latin-1")
            if status >= 200:
                break
        lower = {k.lower(): v for k, v in hdrs.items()}
        conn_hdr = lower.get("connection", "").lower()
        will_close = (conn_hdr == "close"
                      or (version == b"HTTP/1.0"
                          and conn_hdr != "keep-alive"))
        # body framing
        if method == "HEAD" or status in (204, 304):
            return status, b"", hdrs, will_close
        if lower.get("transfer-encoding", "").lower() == "chunked":
            pieces = []
            while True:
                szline = self.rfile.readline(1 << 16)
                try:
                    n = int(szline.split(b";")[0].strip() or b"0", 16)
                except ValueError:
                    raise ConnectionError(f"bad chunk size {szline!r}")
                if n == 0:
                    # trailers until blank line
                    while self.rfile.readline(1 << 16) not in (b"\r\n", b"\n",
                                                               b""):
                        pass
                    break
                pieces.append(self._read_exact(n))
                self.rfile.read(2)  # CRLF
            return status, b"".join(pieces), hdrs, will_close
        if "content-length" in lower:
            n = int(lower["content-length"])
            return status, self._read_exact(n), hdrs, will_close
        # no framing: body runs to connection close
        data = self.rfile.read()
        return status, data or b"", hdrs, True

    def _read_exact(self, n: int) -> bytes:
        data = self.rfile.read(n)
        if data is None or len(data) != n:
            raise ConnectionError("short body read")
        return data

    def settimeout(self, t: float) -> None:
        self.sock.settimeout(t)

    def close(self) -> None:
        try:
            self.rfile.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class _ConnPool(threading.local):
    def __init__(self):
        self.conns: dict = {}


_pool = _ConnPool()


def _egress_span(method: str, parsed, **attrs):
    """Distributed-trace egress gate, shared by _pooled_request and
    http_download so EVERY outbound hop in the codebase (client SDK,
    replication, gateways, EC copies/remote shard reads, master scrapes)
    rides ONE copy of the sampling logic: open an rpc.client span iff
    this thread holds a trace context AND its head decision sampled the
    request — the open span's id becomes the downstream parent (the
    stitching edge), so callers must inject the Traceparent INSIDE the
    returned span.  An unsampled (or undecided) thread pays one
    thread-local read.  Returns (span_cm, ctx); ctx None means "no
    trace context at all" — skip injection entirely."""
    ctx = _trace_context.current()
    if ctx is None:
        return _NOOP_SPAN, None
    tracer = _get_tracer()
    if tracer.enabled and _trace_context.current_sampled() is not None:
        return tracer.span("rpc.client", method=method,
                           peer=parsed.netloc, path=parsed.path,
                           **attrs), ctx
    return _NOOP_SPAN, ctx


def _pooled_request(method: str, url: str, body: Optional[bytes],
                    headers: Optional[dict], timeout: float
                    ) -> tuple[int, bytes, dict]:
    """One request over the pool; raises OSError family on failure.
    A request that fails with a CONNECTION-staleness error on a REUSED
    connection retries once on a fresh one (the server closed the idle
    keep-alive — it never saw the request).  Timeouts NEVER retry: the
    server may be mid-mutation, and transparently resending a POST
    would execute it twice.  Failures on a brand-new connection
    propagate."""
    import socket as _socket

    url, ssl_ctx = _prep_url(url)
    parsed = urllib.parse.urlsplit(url)
    key = (parsed.scheme, parsed.netloc)
    target = (parsed.path or "/") + (f"?{parsed.query}" if parsed.query
                                     else "")
    from . import faultinject as fi

    if fi._points:
        fi.hit("net.request")
        # peer-scoped network faults (the scenario engine's wire): a
        # partition/drop fails the send instantly; a delay is applied
        # deadline-aware, so a slow wire stalls the request but never
        # the caller past its budget — like a real socket timeout
        fi.hit_peer("net.partition", parsed.netloc)
        fi.hit_peer("net.drop", parsed.netloc)
        _net_delay = fi.peer_delay("net.delay", parsed.netloc)
        if _net_delay:
            _deadline.sleep_within(_net_delay)
    # deadline clamp: the per-call timeout never exceeds the remaining
    # propagated budget (a 2s client deadline must not become 30s of
    # downstream waiting); a spent budget raises before sending
    timeout = _deadline.clamp(timeout)
    span_cm, ctx = _egress_span(method, parsed)
    if ctx is not None or _deadline.current() is not None:
        headers = dict(headers or {})
    with span_cm:
        if ctx is not None:
            _trace_context.inject_trace_headers(headers)
        if _deadline.current() is not None:
            _deadline.inject_deadline_headers(headers)
        for _ in range(2):
            conn = _pool.conns.get(key)
            reused = conn is not None
            if conn is None:
                conn = _RawConn(parsed.scheme, parsed.netloc, timeout,
                                ssl_ctx)
                _pool.conns[key] = conn
            try:
                conn.settimeout(timeout)
                status, data, hdrs, will_close = conn.request(
                    method, target, body, headers or {})
                if will_close:
                    conn.close()
                    _pool.conns.pop(key, None)
                return status, data, hdrs
            except (TimeoutError, _socket.timeout):
                conn.close()
                _pool.conns.pop(key, None)
                ddl = _deadline.current()
                if ddl is not None and ddl.expired():
                    # the deadline was the binding constraint: surface
                    # it as a budget exhaustion (servers answer 504),
                    # not a generic transport timeout
                    raise DeadlineExceeded(
                        f"deadline exceeded awaiting "
                        f"{parsed.netloc}") from None
                raise
            except Exception:
                conn.close()
                _pool.conns.pop(key, None)
                if not reused:
                    raise
    raise OSError("unreachable")  # pragma: no cover


def _pooled_with_redirects(method: str, url: str, body: Optional[bytes],
                           headers: Optional[dict], timeout: float,
                           follow_redirects: bool
                           ) -> tuple[int, bytes, dict]:
    for _ in range(5):
        status, data, hdrs = _pooled_request(method, url, body, headers,
                                             timeout)
        if follow_redirects and status in (301, 302, 303, 307, 308) \
                and hdrs.get("Location"):
            url = urllib.parse.urljoin(url, hdrs["Location"])
            if status == 303:
                method, body = "GET", None
            continue
        return status, data, hdrs
    return status, data, hdrs


# --- client helpers ---------------------------------------------------------

def stop_server(server) -> None:
    """Shut down a serve() result: stop the loop AND close the listening
    socket — otherwise clients queue in the accept backlog and hang
    instead of failing over.  Surviving keep-alive handler threads see
    _stopping and answer 503 + Connection: close, so pooled clients
    migrate to whoever owns the port next."""
    server._stopping = True
    server.shutdown()
    server.server_close()


def http_json(method: str, url: str, payload: Optional[dict] = None,
              timeout: float = 30.0) -> dict:
    data = json.dumps(payload).encode() if payload is not None else None
    headers = {"Content-Type": "application/json"} if data is not None else {}
    try:
        status, body, _ = _pooled_with_redirects(method, url, data, headers,
                                                 timeout, True)
    except (ConnectionError, TimeoutError, OSError) as e:
        raise HttpError(503, f"{url} unreachable: {e}") from None
    if status >= 400:
        try:
            err = json.loads(body).get("error", body.decode(errors="replace"))
        except Exception:
            err = body.decode(errors="replace")
        raise HttpError(status, err) from None
    return json.loads(body) if body else {}


def http_json_retry(method: str, url: str, payload: Optional[dict] = None,
                    timeout: float = 30.0, attempts: int = 3,
                    budget_kind: str = "http") -> dict:
    """http_json with bounded transient-failure retries that draw from
    the per-destination retry budget (utils/backoff.py): each RETRY
    (never the first attempt) takes a token for the peer; a drained
    bucket degrades the call to what it already did and journals
    `retry_budget_exhausted` — retries must not multiply load onto a
    peer that is already down.  Only unreachable/503 answers retry
    (anything else is a real server answer); only idempotent methods
    may retry (a timed-out POST may have executed — resending would
    run it twice).  Retries never extend past an active deadline:
    http_json's egress clamp raises DeadlineExceeded the moment the
    budget is spent."""
    from .backoff import jittered_backoff, retry_allowed

    dest = urllib.parse.urlsplit(url).netloc
    retriable = method.upper() in ("GET", "HEAD")
    last: Optional[HttpError] = None
    for i in range(max(1, int(attempts))):
        if i:
            if not retriable or not retry_allowed(dest, budget_kind):
                break
            _deadline.sleep_within(jittered_backoff(0.05, 1.0, i - 1))
        try:
            return http_json(method, url, payload, timeout=timeout)
        except HttpError as e:
            last = e
            if e.status != 503:
                raise
    raise last  # type: ignore[misc]


UNSATISFIABLE_RANGE = (-1, 0)


def parse_range(range_header: str, file_size: int) -> Optional[tuple[int, int]]:
    """Parse an RFC 7233 single range against file_size -> (offset, size);
    None for absent/invalid headers (serve the whole body), or
    UNSATISFIABLE_RANGE when the range starts past EOF (serve 416).
    Handles bytes=N-, bytes=N-M, bytes=-N."""
    if not range_header.startswith("bytes="):
        return None
    lo, dash, hi = range_header[6:].partition("-")
    if not dash:
        return None
    try:
        if lo == "":  # suffix range: last N bytes
            n = int(hi)
            if n == 0 or file_size == 0:
                # RFC 7233: a zero-length suffix (or any suffix of an empty
                # file) has no satisfiable byte range
                return UNSATISFIABLE_RANGE
            offset = max(0, file_size - n)
            return offset, file_size - offset
        offset = int(lo)
        if offset >= file_size:
            return UNSATISFIABLE_RANGE
        if hi == "":
            return offset, file_size - offset
        end = min(int(hi), file_size - 1)
        return offset, end - offset + 1
    except ValueError:
        return None


def http_bytes(method: str, url: str, payload: Optional[bytes] = None,
               headers: Optional[dict] = None, timeout: float = 60.0,
               follow_redirects: bool = True) -> tuple[int, bytes, dict]:
    try:
        return _pooled_with_redirects(method, url, payload, headers,
                                      timeout, follow_redirects)
    except (ConnectionError, TimeoutError, OSError) as e:
        # dead/unreachable server: synthetic status 0 so callers fail over
        return 0, str(e).encode(), {}


def http_download(method: str, url: str, dest_path: str,
                  timeout: float = 3600.0,
                  piece_bytes: int = 1 << 20) -> int:
    """Stream a (possibly huge) response body straight to dest_path in
    bounded pieces — the client half of Response(file_path=...) streaming.
    Writes to dest_path.part and renames on success so a dropped transfer
    never leaves a torn file under the final name.  Returns the HTTP
    status (0 = unreachable)."""
    url, ssl_ctx = _prep_url(url)
    req = urllib.request.Request(url, method=method)
    parsed = urllib.parse.urlsplit(url)
    from . import faultinject as fi

    if fi._points:
        # same peer-scoped network faults as _pooled_request: bulk
        # transfers ride the same simulated wire
        fi.hit_peer("net.partition", parsed.netloc)
        fi.hit_peer("net.drop", parsed.netloc)
        _net_delay = fi.peer_delay("net.delay", parsed.netloc)
        if _net_delay:
            _deadline.sleep_within(_net_delay)
    # deadline clamp + header: a budgeted caller's bulk fetch inherits
    # the remaining budget, never the 1h default
    timeout = _deadline.clamp(timeout)
    # same trace egress as _pooled_request: bulk transfers (volume copy,
    # EC shard copy) appear on the stitched trace as rpc.client hops and
    # carry the caller's Traceparent downstream
    span_cm, ctx = _egress_span(method, parsed, download=True)
    tmp = dest_path + ".part"
    with span_cm:
        if ctx is not None:
            for k, v in _trace_context.inject_trace_headers({}).items():
                req.add_header(k, v)
        for k, v in _deadline.inject_deadline_headers({}).items():
            req.add_header(k, v)
        return _http_download_body(req, timeout, ssl_ctx, tmp,
                                   dest_path, piece_bytes)


def _http_download_body(req, timeout, ssl_ctx, tmp: str, dest_path: str,
                        piece_bytes: int) -> int:
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=ssl_ctx) as r:
            with open(tmp, "wb") as f:
                while True:
                    piece = r.read(piece_bytes)
                    if not piece:
                        break
                    f.write(piece)
            os.replace(tmp, dest_path)
            return r.status
    except urllib.error.HTTPError as e:
        return e.code
    except (urllib.error.URLError, ConnectionError, TimeoutError, OSError):
        return 0
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
