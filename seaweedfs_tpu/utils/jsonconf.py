"""Shared JSON-config-file-in-the-filer helpers.

Several planes store small JSON config documents as ordinary filer files
(/etc/seaweedfs/identity.json, bucket_quotas.json, /etc/remote.conf,
/etc/remote.mount).  Only a clean 404 maps to the default — transient
5xx must raise, or a caller's read-modify-write would wipe the file.
"""

from __future__ import annotations

import json

from .httpd import HttpError, http_bytes


def read_json_conf(filer_url: str, path: str, default):
    status, body, _ = http_bytes("GET", f"http://{filer_url}{path}",
        timeout=60.0)
    if status == 404:
        return default
    if status != 200:
        raise HttpError(status, body.decode(errors="replace"))
    return json.loads(body)


def write_json_conf(filer_url: str, path: str, obj) -> None:
    status, body, _ = http_bytes(
        "PUT", f"http://{filer_url}{path}",
        json.dumps(obj, indent=2).encode(),
        headers={"Content-Type": "application/json"}, timeout=60.0)
    if status not in (200, 201):
        raise HttpError(status, body.decode(errors="replace"))
