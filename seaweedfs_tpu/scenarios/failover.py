"""Master-failover drill: kill the raft leader mid write-storm, mid
EC repair, and measure what the HA control plane promises.

`run_failover` spawns a REAL in-process quorum (3+ masters peered over
/raft/*, volume servers heartbeating the full master list), spreads an
EC volume across every server, rots one shard so the scrub plane
quarantines it and the alert engine fires, lets the coordinator
quorum-replicate its repair plan and start executing with every
/admin/ec/* leg slowed by the coord.exec fault point — then stops the
leader dead and measures:

  election_time_s      — kill -> exactly one new leader all survivors
                         agree on
  assign_after_kill_s  — kill -> a deadline-scoped /dir/assign served
                         by the new leader
  journal_loss_count   — pre-kill journaled event ids missing from the
                         new leader's /cluster/events (the raft-
                         replicated journal contract: must be 0)
  repair_replan_s      — kill -> the new leader's repair_done for the
                         orphaned volume, with the ORIGINAL alert and
                         cause-trace attribution intact

The pre-kill snapshot is taken of events a FOLLOWER already holds:
raft only promises what a quorum acknowledged, and the election
restriction then guarantees the winner has every one of them.  Events
ingested in the kill window itself are post-kill by definition.

The result document mirrors the scenario engine's shape (routes,
checks, verdict) so bench.py's `master_failover` section and
tools/bench_diff.py floor it like any other scenario.
"""

from __future__ import annotations

import random
import shutil
import tempfile
import threading
import time
from typing import Optional

from ..utils import deadline as _deadline
from ..utils import faultinject as fi
from ..utils.backoff import get_retry_budget
from ..utils.httpd import HttpError, http_bytes, http_json
from ..utils.leader import LeaderFollowingTransport
from .engine import _free_port, _Op, _route_stats
from .spec import ScenarioSpec

# the drill's EC volume id: far above anything the storm's volume
# growth allocates, so the manually-built spread never collides
EC_VID = 999


def _wait(cond, timeout: float, what: str):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = cond()
        if v:
            return v
        time.sleep(0.1)
    raise RuntimeError(f"timed out waiting for {what}")


def _wait_leader(masters, timeout: float = 15.0):
    """One leader, and every live master agrees who it is."""
    def check():
        leaders = [m for m in masters if m.is_leader]
        if len(leaders) == 1 and all(
                m.leader_url == leaders[0].url for m in masters):
            return leaders[0]
        return None
    return _wait(check, timeout, "a stable leader")


def _make_ec_volume(vs, vid: int, needles: int = 30) -> None:
    import numpy as np

    from ..storage.needle import Needle

    v = vs.store.add_volume(vid)
    rng = np.random.default_rng(0xFA11)
    for i in range(1, needles + 1):
        v.write_needle(Needle(cookie=i, id=i,
                              data=rng.bytes(300 + i * 11)))
    vs.store.ec_generate(vid)
    vs.store.ec_mount(vid)


def _spread_shards(servers, vid: int) -> None:
    """Round-robin the volume's shards across every server with real
    /admin/ec/copy legs — each holder ends with < k local shards, so a
    corrupted one is locally unrepairable and MUST cross the wire."""
    from ..ec.layout import TOTAL_SHARDS_COUNT

    src = servers[0]
    n = len(servers)
    layout = {i: [s for s in range(TOTAL_SHARDS_COUNT) if s % n == i]
              for i in range(n)}
    for i, sids in layout.items():
        if i == 0:
            continue
        http_json("POST", f"http://{servers[i].url}/admin/ec/copy",
                  {"volume_id": vid, "shard_ids": sids,
                   "source_data_node": src.url}, timeout=30.0)
        http_json("POST", f"http://{servers[i].url}/admin/ec/mount",
                  {"volume_id": vid}, timeout=30.0)
    drop = [s for s in range(TOTAL_SHARDS_COUNT)
            if s not in layout[0]]
    http_json("POST", f"http://{src.url}/admin/ec/delete",
              {"volume_id": vid, "shard_ids": drop}, timeout=30.0)
    http_json("POST", f"http://{src.url}/admin/ec/mount",
              {"volume_id": vid}, timeout=30.0)
    http_json("POST", f"http://{src.url}/admin/delete_volume",
              {"volume_id": vid}, timeout=30.0)
    for vs in servers:
        vs.heartbeat_now()


def _registry_shards(master, vid: int) -> dict:
    with master.topo.lock:
        locs = master.topo.ec_shard_locations.get(vid, {})
        return {sid: [n.url for n in nodes]
                for sid, nodes in locs.items() if nodes}


def _scrub_once(vs) -> None:
    # suspend the scrubber's busy gate for the forced pass: it exists
    # to defer scan IO behind live traffic, but this drill scans MID
    # write storm on purpose — gated, the pass can pause for as long
    # as the storm keeps the holder above the busy threshold
    prev_busy = vs.scrubber.busy_fn
    vs.scrubber.busy_fn = None
    try:
        http_json("POST", f"http://{vs.url}/ec/scrub/start",
                  {"rate_mb_s": 0, "interval_s": 0}, timeout=30.0)
        _wait(lambda: not http_json(
            "GET", f"http://{vs.url}/ec/scrub/status",
            timeout=10.0)["running"],
            45, f"scrub on {vs.url}")
    finally:
        vs.scrubber.busy_fn = prev_busy


def _storm_loop(ci: int, spec: ScenarioSpec,
                transport: LeaderFollowingTransport, t0: float,
                stop: threading.Event, out: list) -> None:
    """One write-storm client: assign through the leader-following
    transport (any live master serves — followers redirect GETs), PUT
    to the assigned volume server, under the spec deadline."""
    from .workload import SizeSampler, payload_for

    rng = random.Random(spec.seed * 7919 + ci)
    sizes = SizeSampler(spec.sizes)
    seq = 0
    while not stop.is_set():
        t_op = time.monotonic()
        status = 0
        try:
            with _deadline.scope(spec.deadline_s):
                r = transport.get("/dir/assign?count=1", timeout=10.0)
                seq += 1
                payload = payload_for(sizes.sample(rng), ci * 131 + seq)
                status, _b, _h = http_bytes(
                    "POST", f"http://{r['url']}/{r['fid']}", payload,
                    timeout=10.0)
        except _deadline.DeadlineExceeded:
            status = 504
        except HttpError as e:
            status = e.status
        except Exception:
            status = 0
        out.append(_Op("write", t_op - t0, time.monotonic() - t_op,
                       status))
        # sustained storm, not a tight-loop DoS of the test host
        stop.wait(0.02)


def run_failover(spec: Optional[ScenarioSpec] = None,
                 base_dir: Optional[str] = None, log=None) -> dict:
    """Run the master_failover drill end to end; returns the result
    document (routes / measurements / checks / verdict)."""
    from ..master.server import MasterServer
    from ..volume_server.server import VolumeServer
    from .spec import master_failover as _default_spec

    from ..observability import disable_tracing, enable_tracing, get_tracer

    spec = spec or _default_spec()
    say = log or (lambda _m: None)
    exp = spec.expectations
    # tracing on: the scrub verdicts must carry trace ids so the
    # repair's cause_trace attribution has something to preserve
    tracing_was_on = get_tracer().enabled
    if not tracing_was_on:
        enable_tracing()
    n_masters = max(3, spec.n_masters)
    mdirs = [tempfile.mkdtemp(dir=base_dir) for _ in range(n_masters)]
    roots = [tempfile.mkdtemp(dir=base_dir)
             for _ in range(spec.n_volume_servers)]
    ports = [_free_port() for _ in range(n_masters)]
    urls = [f"127.0.0.1:{p}" for p in ports]
    master_list = ",".join(urls)
    result: dict = {"name": spec.name, "spec": spec.to_dict()}
    stop = threading.Event()
    threads: list[threading.Thread] = []
    masters: list = []
    servers: list = []
    try:
        for i, p in enumerate(ports):
            peers = [u for j, u in enumerate(urls) if j != i]
            m = MasterServer(port=p, peers=peers, mdir=mdirs[i],
                             pulse_seconds=0.3,
                             metrics_aggregation_seconds=0.25,
                             coordinator_seconds=0.3).start()
            m.aggregator.min_interval = 0.0
            m.alert_engine.min_interval = 0.0
            m.coordinator.move_rate = 100.0
            m.coordinator.pause("setup")
            masters.append(m)
        leader = _wait_leader(masters)
        say(f"{spec.name}: leader {leader.url} over {n_masters} masters")
        for i in range(spec.n_volume_servers):
            servers.append(VolumeServer(
                [roots[i]], master_list, port=_free_port(),
                rack=f"r{i % 2}", data_center="dc1",
                pulse_seconds=0.3, max_volume_count=16).start())
        _wait(lambda: len(leader.topo.all_nodes())
              >= spec.n_volume_servers, 15, "volume-server registration")
        # pre-grow so storm assigns spread instead of racing growth
        http_json("GET", f"http://{leader.url}/vol/grow"
                         f"?count={2 * spec.n_volume_servers}",
                  timeout=30.0)
        _make_ec_volume(servers[0], EC_VID)
        _spread_shards(servers, EC_VID)
        from ..ec.layout import TOTAL_SHARDS_COUNT
        _wait(lambda: len(_registry_shards(leader, EC_VID))
              == TOTAL_SHARDS_COUNT, 15, "registry to see the EC spread")
        _wait(lambda: leader.alert_engine.evaluations > 0, 10,
              "the first alert evaluation")

        # --- the write storm ------------------------------------------
        t0 = time.monotonic()
        per_client: list[list] = [[] for _ in range(spec.clients)]
        for ci in range(spec.clients):
            tr = LeaderFollowingTransport(lambda: master_list,
                                          name=f"storm{ci}")
            threads.append(threading.Thread(
                target=_storm_loop,
                args=(ci, spec, tr, t0, stop, per_client[ci]),
                daemon=True, name=f"failover-c{ci}"))
        say(f"{spec.name}: {spec.clients} write-storm clients up")
        for t in threads:
            t.start()

        # --- rot a shard; the signal plane must notice ----------------
        sid = 2
        holder = servers[sid % len(servers)]
        fi.enable("ec.shard.corrupt",
                  params={"shard": sid, "offset": 0, "bit": 3},
                  max_hits=1)
        _scrub_once(holder)
        fi.disable("ec.shard.corrupt")
        # the corruption signal, specifically: under storm load the
        # plane also pages infrastructure alerts (loop_stall,
        # loop_lag_increase, reqlog drops) that are orthogonal to the
        # rot this drill plants — capturing those as `firing` would
        # break the attribution contract below even though the repair
        # cites its scrub cause correctly
        def _rot_alerts():
            return {a["name"]
                    for a in leader.alert_engine.to_dict()["alerts"]
                    if a["state"] == "firing"
                    and ("scrub" in a["name"] or "corrupt" in a["name"]
                         or a["name"].startswith("ec_"))} or None
        firing = _wait(_rot_alerts, 25, "a firing corruption alert")
        say(f"{spec.name}: firing={sorted(firing)}")

        # --- repair starts, slowed; plan quorum-replicates ------------
        fi.enable("coord.exec", delay=1.0)
        # resume EVERY coordinator: followers idle behind is_leader_fn,
        # but whichever wins the coming election must not stay parked
        # on the setup pause
        for m in masters:
            m.coordinator.resume()
        followers = [m for m in masters if m is not leader]
        _wait(lambda: any(
            f.coordinator.status()["replicated"]["pending"]
            for f in followers), 25,
            "the repair plan to replicate to a follower")
        # the plan exists, so the alert that seeded it is firing NOW —
        # fold the current rot set into the pre-kill capture (the first
        # counter_increase alert can reach firing a beat before the
        # threshold rule the coordinator actually cites)
        firing |= _rot_alerts() or set()
        # pre-kill zero-loss snapshot: what a follower already holds is
        # what raft promises survives the election
        pre_ids = {e["id"] for e in leader.event_journal.query(limit=0)}
        _wait(lambda: any(
            pre_ids <= {e["id"] for e in f.event_journal.query(limit=0)}
            for f in followers), 15, "journal replication to catch up")

        # --- kill -----------------------------------------------------
        say(f"{spec.name}: killing leader {leader.url} mid-repair "
            f"({len(pre_ids)} journaled events pre-kill)")
        kill_t = time.monotonic()
        # in-process artifact: a real master death takes its in-flight
        # repair threads with it, but stop() only joins them for 2s —
        # sever the old coordinator's egress so the orphaned repair
        # truly dies with its master and the re-plan measured below is
        # the NEW leader's work

        def _dead_post(*_a, **_k):
            raise ConnectionError("master process killed")
        leader.coordinator.executor._post_fn = _dead_post
        leader.stop()
        new_leader = _wait_leader(followers, timeout=25)
        election_s = round(time.monotonic() - kill_t, 2)
        fi.disable("coord.exec")
        say(f"{spec.name}: new leader {new_leader.url} "
            f"after {election_s}s")

        # the new leader's topology refills from volume-server
        # heartbeats (one pulse): a client retries until its assign
        # lands — the measure is election -> first SERVED assign
        assign_budget = float(exp.get("assign_after_kill_max_s", 5.0))
        assign_t = time.monotonic()
        assign_ok = False
        while time.monotonic() - assign_t < assign_budget + 5.0:
            try:
                with _deadline.scope(spec.deadline_s):
                    http_json(
                        "GET",
                        f"http://{new_leader.url}/dir/assign?count=1",
                        timeout=10.0)
                assign_ok = True
                break
            except Exception:
                time.sleep(0.1)
        assign_after_kill_s = round(time.monotonic() - assign_t, 3)

        def missing_ids():
            have = {e["id"]
                    for e in new_leader.event_journal.query(limit=0)}
            return pre_ids - have
        try:
            _wait(lambda: not missing_ids(), 20,
                  "pre-kill events on the new leader")
        except RuntimeError:
            pass  # scored below as journal_loss_count
        journal_loss = len(missing_ids())

        def done_event():
            evs = new_leader.event_journal.query(type_="repair_done",
                                                 limit=0)
            for e in reversed(evs):
                if (e.get("details") or {}).get("vid") == EC_VID:
                    return e
            return None
        repair_budget = float(exp.get("repair_replan_max_s", 45.0))
        ev = None
        try:
            ev = _wait(done_event, repair_budget + 10.0,
                       "the re-planned repair to finish")
        except RuntimeError:
            pass
        repair_replan_s = round(time.monotonic() - kill_t, 2) \
            if ev else None
        detail = (ev or {}).get("details") or {}

        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        ops = sorted((o for lst in per_client for o in lst),
                     key=lambda o: o.t)
        wall = time.monotonic() - t0
        new_alerts = {a["name"]: a for a in
                      new_leader.alert_engine.to_dict()["alerts"]}
        result.update({
            "wall_s": round(wall, 1),
            "total_ops": len(ops),
            "routes": _route_stats(ops, wall),
            "masters": n_masters,
            "killed_leader": leader.url,
            "new_leader": new_leader.url,
            "election_time_s": election_s,
            "assign_after_kill_s": assign_after_kill_s,
            "pre_kill_events": len(pre_ids),
            "journal_loss_count": journal_loss,
            "repair_replan_s": repair_replan_s,
            "repair_attribution": {
                "alert": detail.get("alert", ""),
                "cause_trace": detail.get("cause_trace", ""),
                "fired_pre_kill": sorted(firing)},
            "alerts": {
                "fired_on_new_leader": sorted(
                    n for n, a in new_alerts.items()
                    if a.get("fired_at")),
                "still_firing": sorted(
                    n for n, a in new_alerts.items()
                    if a["state"] == "firing")},
            "raft": new_leader.raft.status(),
        })

        checks: list[dict] = []

        def check(name, ok, value, bound):
            checks.append({"check": name, "ok": bool(ok),
                           "value": value, "bound": bound})

        if "election_max_s" in exp:
            check("election_time_s", election_s <= exp["election_max_s"],
                  election_s, exp["election_max_s"])
        if "journal_loss_max" in exp:
            check("journal_loss_count",
                  journal_loss <= exp["journal_loss_max"],
                  journal_loss, exp["journal_loss_max"])
        if "assign_after_kill_max_s" in exp:
            check("assign_after_kill_s",
                  assign_ok
                  and assign_after_kill_s <= exp["assign_after_kill_max_s"],
                  assign_after_kill_s, exp["assign_after_kill_max_s"])
        if "repair_replan_max_s" in exp:
            check("repair_replan_s",
                  repair_replan_s is not None
                  and repair_replan_s <= exp["repair_replan_max_s"],
                  repair_replan_s, exp["repair_replan_max_s"])
        check("repair_attribution",
              bool(detail.get("alert")) and detail["alert"] in firing
              and bool(detail.get("cause_trace")),
              {"alert": detail.get("alert", ""),
               "cause_trace": detail.get("cause_trace", "")},
              "original alert + cause trace")
        result["checks"] = checks
        result["degraded"] = any(not c["ok"] for c in checks)
        result["verdict"] = "degraded" if result["degraded"] else "pass"
        return result
    finally:
        stop.set()
        fi.clear()
        get_retry_budget().reset()
        for vs in servers:
            try:
                vs.stop()
            except Exception:
                pass
        for m in masters:
            try:
                m.stop()
            except Exception:
                pass
        if not tracing_was_on:
            disable_tracing()
        for d in mdirs + roots:
            shutil.rmtree(d, ignore_errors=True)
