"""SLO capacity probe: the max sustainable rps per route class.

ROADMAP's HTTP-dataplane item needs machine-derived per-route capacity
numbers before the async zero-copy refactor lands, or its 10x claim has
no baseline.  This module produces them:

  measure_rate()   — ONE open-loop measurement: ops are scheduled on a
      fixed global clock (slot k fires at t0 + k/rps, workers pull
      slots from a shared counter) so a saturated server cannot slow
      its own load down — it shows up as schedule lag and a collapsing
      achieved rate, exactly like real arrivals.  Emits achieved rps,
      p50/p99 service latency, error ratio, and max schedule lag.

  find_capacity()  — ramp (double the target until the SLO breaks or
      the schedule cannot be kept) then binary-search the bracket: the
      highest rate the SLO survives is ``capacity_rps``; the first
      breaching step is the ``knee`` (rate + which bound broke).

  probe_cluster()  — drive real route classes (http_read, native_read,
      http_write) against a live cluster, attach the bounding-resource
      attribution (a forced-sample stitched trace fetched from the
      master mid-load names the bounding hop; the server's
      network-vs-server split classifies the resource), and return the
      document the bench ``capacity`` section embeds and
      ``weed shell capacity.probe`` posts to the master.

A measurement is "sustainable" only when BOTH hold: the SLO (p99 and
error ratio) passes AND the achieved rate kept up with the schedule
(>= 92% of target) — a probe that quietly under-delivered its load and
then passed the SLO proves nothing.
"""

from __future__ import annotations

import threading
import time
from dataclasses import asdict, dataclass
from typing import Callable, Optional

from .workload import percentile as _percentile


@dataclass
class CapacitySLO:
    """The declared bar a capacity number is conditional on.  The
    defaults are the dataplane refactor's acceptance SLO: p99 < 5ms,
    error ratio < 0.1%."""
    max_p99_ms: float = 5.0
    max_error_ratio: float = 0.001

    def to_dict(self) -> dict:
        return asdict(self)


def measure_rate(op: Callable[[], bool], rps: float, duration_s: float,
                 workers: int = 0) -> dict:
    """One open-loop step at target ``rps`` for ``duration_s``.  ``op``
    performs one operation and returns ok (False = error; an exception
    counts as an error too).  Worker threads pull slot indices from a
    shared cursor and sleep until their slot's scheduled time — lag
    accumulates when the pool cannot keep up, and the achieved rate is
    computed against the wall, not the schedule."""
    rps = max(float(rps), 0.1)
    interval = 1.0 / rps
    n_slots = max(int(duration_s * rps), 1)
    if workers <= 0:
        # enough concurrency to cover ~40ms of service time at the
        # target rate before the schedule slips, bounded for sanity
        workers = max(4, min(64, int(rps * 0.04) + 1))
    cursor = [0]
    lock = threading.Lock()
    lat_ms: list[float] = []
    errors = [0]
    max_lag = [0.0]
    t0 = time.monotonic()

    def loop():
        while True:
            with lock:
                i = cursor[0]
                if i >= n_slots:
                    return
                cursor[0] += 1
            t_slot = t0 + i * interval
            delay = t_slot - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            lag = time.monotonic() - t_slot
            t_op = time.monotonic()
            try:
                ok = op()
            except Exception:
                ok = False
            dt_ms = (time.monotonic() - t_op) * 1e3
            with lock:
                if ok:
                    lat_ms.append(dt_ms)
                else:
                    errors[0] += 1
                if lag > max_lag[0]:
                    max_lag[0] = lag

    threads = [threading.Thread(target=loop, daemon=True,
                                name=f"cap-{rps:.0f}-{w}")
               for w in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = max(time.monotonic() - t0, 1e-9)
    done = len(lat_ms) + errors[0]
    lat_ms.sort()
    return {
        "target_rps": round(rps, 1),
        "achieved_rps": round(done / wall, 1),
        "ops": done,
        "errors": errors[0],
        "error_ratio": round(errors[0] / done, 5) if done else 1.0,
        "p50_ms": round(_percentile(lat_ms, 0.50), 3),
        "p99_ms": round(_percentile(lat_ms, 0.99), 3),
        "max_lag_ms": round(max_lag[0] * 1e3, 1),
        "workers": workers,
    }


def _sustainable(step: dict, slo: CapacitySLO) -> tuple[bool, str]:
    """-> (ok, breach reason).  The reason string is the knee's
    bound-that-broke attribution."""
    if step["achieved_rps"] < 0.92 * step["target_rps"]:
        return False, "throughput (schedule could not be kept)"
    if step["error_ratio"] > slo.max_error_ratio:
        return False, (f"error_ratio {step['error_ratio']:.3%} > "
                       f"{slo.max_error_ratio:.3%}")
    if step["p99_ms"] > slo.max_p99_ms:
        return False, (f"p99 {step['p99_ms']:.1f}ms > "
                       f"{slo.max_p99_ms:g}ms")
    return True, ""


def find_capacity(op: Callable[[], bool],
                  slo: Optional[CapacitySLO] = None,
                  start_rps: float = 50.0, max_rps: float = 100000.0,
                  step_s: float = 2.0, search_steps: int = 4) -> dict:
    """Ramp + binary search for the max sustainable rps under the SLO.
    Returns capacity_rps (the highest rate that passed; its achieved
    rps, which is what the server really did), the knee (first
    breaching step + which bound broke), and the full ramp so a reader
    can see the curve, not just the answer."""
    slo = slo or CapacitySLO()
    samples: list[dict] = []
    best: Optional[dict] = None
    knee: Optional[dict] = None
    rps = max(float(start_rps), 1.0)
    # ramp: double until the SLO breaks or the cap is reached
    while rps <= max_rps:
        step = measure_rate(op, rps, step_s)
        ok, reason = _sustainable(step, slo)
        step["sustainable"] = ok
        samples.append(step)
        if not ok:
            knee = dict(step, reason=reason)
            break
        best = step
        rps *= 2.0
    if best is None and knee is not None:
        # start_rps itself breached: the capacity lives BELOW the
        # starting guess, not at zero — halve down until a step
        # sustains (or the floor proves the service really cannot
        # serve the SLO at any rate)
        rps = knee["target_rps"] / 2.0
        while rps >= 1.0:
            step = measure_rate(op, rps, step_s)
            ok, reason = _sustainable(step, slo)
            step["sustainable"] = ok
            samples.append(step)
            if ok:
                best = step
                break
            knee = dict(step, reason=reason)
            rps /= 2.0
    if best is not None and knee is not None:
        # binary search the bracket (last good, first bad)
        lo, hi = best["target_rps"], knee["target_rps"]
        for _ in range(max(int(search_steps), 0)):
            mid = (lo + hi) / 2.0
            if hi - lo < max(0.05 * lo, 1.0):
                break
            step = measure_rate(op, mid, step_s)
            ok, reason = _sustainable(step, slo)
            step["sustainable"] = ok
            samples.append(step)
            if ok:
                best, lo = step, mid
            else:
                knee, hi = dict(step, reason=reason), mid
    return {
        "slo": slo.to_dict(),
        "capacity_rps": best["achieved_rps"] if best else 0.0,
        "capacity_target_rps": best["target_rps"] if best else 0.0,
        "capacity_p99_ms": best["p99_ms"] if best else 0.0,
        "knee_rps": knee["target_rps"] if knee else None,
        "knee": ({"p99_ms": knee["p99_ms"],
                  "error_ratio": knee["error_ratio"],
                  "achieved_rps": knee["achieved_rps"],
                  "reason": knee["reason"]} if knee else None),
        "samples": samples,
    }


# --- live route classes ------------------------------------------------------

def _preload_fids(master_url: str, count: int = 64,
                  size: int = 4096) -> list[tuple[str, str]]:
    """Write `count` small objects; -> [(fid, volume url)]."""
    from ..utils.httpd import http_bytes, http_json

    out = []
    payload = b"\xa5" * size
    for i in range(count):
        r = http_json("GET", f"http://{master_url}/dir/assign?count=1",
                      timeout=15.0)
        st, body, _ = http_bytes("POST", f"http://{r['url']}/{r['fid']}",
                                 payload, timeout=30.0)
        if st not in (200, 201):
            raise RuntimeError(f"capacity preload {r['fid']} -> {st}: "
                               f"{body[:120]!r}")
        out.append((r["fid"], r["url"]))
    return out


def _attribute_bound(master_url: str, probe_url: str,
                     fid: str) -> dict:
    """Bounding-resource attribution: force-sample ONE read mid-load,
    fetch its stitched trace from the master, and name the bounding
    hop + the network-vs-server second split.  Best-effort — tracing
    may be off, and a capacity number without attribution is still a
    capacity number."""
    from ..observability import context as _trace_context
    from ..utils.httpd import http_bytes, http_json

    try:
        # the forced request must open its OWN trace, not ride an
        # ambient one (shell commands force-sample themselves: without
        # this scope, `capacity.probe` would fetch the whole command's
        # trace — preloads included — and misattribute the bound)
        prev = _trace_context.activate(None)
        try:
            _st, _b, hdrs = http_bytes(
                "GET", f"http://{probe_url}/{fid}",
                headers={"X-Force-Trace": "1"}, timeout=10.0)
        finally:
            _trace_context.activate(prev)
        trace_id = hdrs.get("X-Trace-Id", "")
        if not trace_id:
            return {"resource": "unknown",
                    "detail": "tracing off (no X-Trace-Id)"}
        deadline = time.time() + 6.0
        doc = None
        while time.time() < deadline:
            try:
                doc = http_json(
                    "GET",
                    f"http://{master_url}/cluster/traces/{trace_id}",
                    timeout=5.0)
                break
            except Exception:
                time.sleep(0.2)
        if not doc:
            return {"resource": "unknown",
                    "detail": "stitched trace never reached collector"}
        an = doc.get("analysis") or {}
        # server_s is the analyzer's PER-SERVER self-time map; the
        # resource classification wants the totals
        server_s = sum(float(v) for v in
                       (an.get("server_s") or {}).values())
        network_s = float(an.get("network_s") or 0.0)
        resource = "server" if server_s >= network_s else "network"
        bounding = an.get("bounding_hop") or {}
        if bounding.get("kind") == "hop":
            hop = (f"{bounding.get('from')} -> {bounding.get('to')} "
                   f"{bounding.get('op')}")
        elif bounding.get("kind") == "local":
            hop = f"{bounding.get('op')} on {bounding.get('server')}"
        else:
            hop = ""
        out = {"resource": resource, "bounding_hop": hop,
               "server_s": round(server_s, 4),
               "network_s": round(network_s, 4),
               "trace_id": trace_id}
        # resource-ledger citation (best-effort): the merged ledger's
        # row for the probed route class says what the route costs
        # cluster-wide RIGHT NOW — CPU share and queue-wait back the
        # single-trace attribution with the population it came from
        try:
            led = http_json(
                "GET", f"http://{master_url}/cluster/ledger?top=64",
                timeout=5.0)
            for row in led.get("routes") or []:
                if row.get("route") == "http_read":
                    out["ledger"] = {
                        "route": "http_read",
                        "cpu_rate_s_per_s": row.get("cpu_rate", 0.0),
                        "cpu_share": row.get("cpu_share", 0.0),
                        "queue_wait_s_per_s":
                            row.get("queue_wait_rate", 0.0),
                        "req_rate": row.get("req_rate", 0.0),
                    }
                    break
        except Exception:
            pass
        return out
    except Exception as e:
        return {"resource": "unknown",
                "detail": f"{type(e).__name__}: {e}"[:200]}


def probe_cluster(master_url: str,
                  routes: tuple = ("http_read", "native_read",
                                   "http_write"),
                  slo: Optional[CapacitySLO] = None,
                  start_rps: float = 100.0, max_rps: float = 50000.0,
                  step_s: float = 2.0, preload: int = 64,
                  write_size: int = 1024) -> dict:
    """Probe a LIVE cluster's per-route-class capacity.  http_read and
    native_read hammer preloaded objects through the pooled HTTP /
    framed-TCP clients; http_write assigns + uploads fresh objects.
    Each class gets its own ramp + search and its own bounding-resource
    attribution.  The returned document is what the master parks at
    POST /cluster/capacity."""
    import random as _random

    from ..utils.framing import tcp_address
    from ..utils.httpd import http_bytes, http_json
    from ..volume_server.tcp import TcpVolumeClient

    slo = slo or CapacitySLO()
    fids = _preload_fids(master_url, count=preload, size=write_size)
    rng = _random.Random(0xCAFE)
    tcp_client = TcpVolumeClient()
    doc: dict = {"slo": slo.to_dict(), "routes": {},
                 "probed_at": round(time.time(), 3),
                 "master": master_url}

    def http_read_op() -> bool:
        fid, url = fids[rng.randrange(len(fids))]
        st, _b, _h = http_bytes("GET", f"http://{url}/{fid}",
                                timeout=10.0)
        return 200 <= st < 300

    def native_read_op() -> bool:
        fid, url = fids[rng.randrange(len(fids))]
        try:
            tcp_client.read(tcp_address(url), fid)
            return True
        except Exception:
            return False

    payload = b"\x5a" * write_size

    def http_write_op() -> bool:
        try:
            r = http_json("GET",
                          f"http://{master_url}/dir/assign?count=1",
                          timeout=10.0)
            st, _b, _h = http_bytes(
                "POST", f"http://{r['url']}/{r['fid']}", payload,
                timeout=10.0)
            return 200 <= st < 300
        except Exception:
            return False

    ops = {"http_read": http_read_op, "native_read": native_read_op,
           "http_write": http_write_op}
    for route in routes:
        op = ops.get(route)
        if op is None:
            doc["routes"][route] = {"error": f"unknown route {route!r}"}
            continue
        res = find_capacity(op, slo, start_rps=start_rps,
                            max_rps=max_rps, step_s=step_s)
        # attribution mid-shape: one forced trace right after the
        # search, while the connection pools and caches are still hot
        fid, url = fids[0]
        res["bounding"] = _attribute_bound(master_url, url, fid)
        doc["routes"][route] = res
    return doc


def render_capacity(doc: dict) -> str:
    """One stable line per route class — the shell view."""
    lines = []
    slo = doc.get("slo") or {}
    lines.append(f"capacity probe (SLO: p99 < {slo.get('max_p99_ms')}ms, "
                 f"errors < {slo.get('max_error_ratio', 0):.2%})")
    for route, res in sorted((doc.get("routes") or {}).items()):
        if "error" in res:
            lines.append(f"  {route:<12} error: {res['error']}")
            continue
        knee = res.get("knee")
        knee_s = (f" knee@{res.get('knee_rps'):g}rps "
                  f"({knee['reason']})" if knee else " (no knee found)")
        bound = (res.get("bounding") or {}).get("resource", "unknown")
        hop = (res.get("bounding") or {}).get("bounding_hop", "")
        lines.append(
            f"  {route:<12} capacity={res.get('capacity_rps', 0):g} rps"
            f" p99={res.get('capacity_p99_ms', 0):g}ms"
            f"{knee_s} bound={bound}"
            + (f" [{hop}]" if hop else ""))
        led = (res.get("bounding") or {}).get("ledger")
        if led:
            lines.append(
                f"  {'':<12} ledger: {led['route']} at "
                f"{led.get('cpu_rate_s_per_s', 0) * 1000:.1f} cpu-ms/s "
                f"({led.get('cpu_share', 0):.0%} of cluster), "
                f"queue-wait "
                f"{led.get('queue_wait_s_per_s', 0) * 1000:.1f} ms/s "
                f"over {led.get('req_rate', 0):g} req/s")
    return "\n".join(lines)
