"""Workload samplers: Zipfian popularity, size mix, op mix.

Pure, seeded, stdlib-only — the engine composes them into client
loops; tests pin their distributions directly.
"""

from __future__ import annotations

import random
from bisect import bisect_left


class ZipfSampler:
    """Zipf(s) over ranks 0..n-1: P(rank r) proportional to
    1/(r+1)^s.  Rank 0 is the hottest object.  Sampling is one
    random() + one bisect over the precomputed CDF."""

    def __init__(self, n: int, s: float = 1.1):
        self.n = max(1, int(n))
        self.s = float(s)
        weights = [1.0 / ((r + 1) ** self.s) for r in range(self.n)]
        total = sum(weights)
        cdf = []
        acc = 0.0
        for w in weights:
            acc += w / total
            cdf.append(acc)
        cdf[-1] = 1.0  # float-sum slack must not strand random()==1.0
        self._cdf = cdf

    def sample(self, rng: random.Random) -> int:
        return bisect_left(self._cdf, rng.random())

    def pmf(self, rank: int) -> float:
        lo = self._cdf[rank - 1] if rank else 0.0
        return self._cdf[rank] - lo


class SizeSampler:
    """Weighted size mix: ((bytes, weight), ...) -> one size per
    sample.  Weights need not sum to 1."""

    def __init__(self, sizes):
        pairs = [(int(b), float(w)) for b, w in sizes] or [(4096, 1.0)]
        total = sum(w for _b, w in pairs)
        cdf = []
        acc = 0.0
        for b, w in pairs:
            acc += w / total
            cdf.append((acc, b))
        cdf[-1] = (1.0, cdf[-1][1])
        self._cdf = cdf

    def sample(self, rng: random.Random) -> int:
        x = rng.random()
        for acc, b in self._cdf:
            if x <= acc:
                return b
        return self._cdf[-1][1]


def percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile over an ASCENDING-sorted list — the one
    quantile convention the engine's route stats and the capacity
    probe share (a fix to the index rule must change both at once)."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[i]


def pick_op(rng: random.Random, read_fraction: float,
            churn_fraction: float) -> str:
    """'read' | 'write' | 'delete' per the spec's mix: churn_fraction
    carves deletes out of the WRITE share (a delete is churn on data
    the run itself wrote)."""
    if rng.random() < read_fraction:
        return "read"
    return "delete" if rng.random() < churn_fraction else "write"


def payload_for(size: int, seed_byte: int) -> bytes:
    """Deterministic compressible-ish payload: one distinct byte
    repeated — cheap to build per op at MB sizes, still distinct per
    object so reads can sanity-check what came back."""
    return bytes((seed_byte & 0xFF,)) * size
