"""Declarative scenario specs: the workload is DATA, the engine runs it.

A ScenarioSpec says what the traffic looks like (Zipfian popularity
over a hot set, size mix, read/write/churn split), what breaks and
when (FaultSpec entries over the W701-checked FAULT_POINTS registry),
what budget every request carries (deadline_s), how the servers defend
themselves (max_inflight admission), and what the run must prove
(expectations -> the degraded verdict).  Specs serialize to/from plain
dicts so the bench JSON can echo exactly what ran.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field


@dataclass
class FaultSpec:
    """One timed fault: arm `point` at at_frac of the run, clear it at
    clear_frac.  `peer` scopes net.* points to one server; the engine
    resolves the placeholder "vs<N>" to the N-th volume server's
    address at run time (a spec cannot know ephemeral ports)."""
    point: str
    at_frac: float = 0.33
    clear_frac: float = 0.66
    error_rate: float = 1.0
    delay: float = 0.0
    peer: str = "vs0"

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass
class ScenarioSpec:
    name: str
    duration_s: float = 12.0
    clients: int = 8
    n_volume_servers: int = 1
    n_masters: int = 1                # >1: HA quorum (failover drills)
    read_fraction: float = 1.0        # remainder is writes (incl. churn)
    churn_fraction: float = 0.0       # fraction of WRITE ops that delete
    submit_fraction: float = 0.0      # fraction of writes via master /submit
    zipf_s: float = 1.1               # popularity skew exponent
    hot_set: int = 128                # distinct objects in the hot set
    # (size_bytes, weight) mix; 4KB needles dominate, with a heavy tail
    sizes: tuple = ((4096, 0.90), (65536, 0.08), (1 << 20, 0.02))
    deadline_s: float = 2.0           # per-request client budget
    # open-loop pacing: > 0 schedules ops at this aggregate rate on a
    # fixed clock (replayed recordings arrive at recorded speed; a slow
    # server gets catch-up bursts, not a slower workload).  0 = closed
    # loop: every client hammers as fast as responses return.
    target_rps: float = 0.0
    max_inflight: int = 0             # server admission bound (0 = off)
    vacuum_every_s: float = 0.0       # >0: periodic /vol/vacuum churn
    # mid-run popularity shift: at this fraction of the run the Zipf
    # head jumps to the cold half of the rank list (the flash-crowd
    # shape the heat plane's shift detector exists to catch); 0 = off
    head_shift_frac: float = 0.0
    # keep the hot set where the master placed it instead of round-
    # robin interleaving ranks across servers — a shift drill needs
    # the head's move to change WHICH VOLUME is hot
    preload_locality: bool = False
    # run the master's heat autoscaler (ops/autoscaler.py) at drill
    # scale: grows answer the Zipf head live, clients re-discover
    # replica locations mid-run, and the result carries an
    # `autoscale` block (grow latency, SLO recovery, thrash count)
    autoscale: bool = False
    faults: tuple = ()                # FaultSpec entries
    fast_alerts: bool = True          # shrink SLO windows to drill scale
    # verdict bounds; absent keys are not checked
    expectations: dict = field(default_factory=dict)
    seed: int = 0xBEE5

    def to_dict(self) -> dict:
        d = asdict(self)
        d["faults"] = [f.to_dict() if isinstance(f, FaultSpec) else dict(f)
                       for f in self.faults]
        d["sizes"] = [list(s) for s in self.sizes]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ScenarioSpec":
        d = dict(d)
        d["faults"] = tuple(FaultSpec(**f) for f in d.get("faults", ()))
        d["sizes"] = tuple((int(b), float(w))
                           for b, w in d.get("sizes", ()))
        return cls(**d)


def read_storm(duration_s: float = 10.0) -> ScenarioSpec:
    """Zipfian hot-set read storm: the 'millions of users fetching the
    same front page' shape.  Pure reads, heavy skew, every request on a
    budget — proves p99 under popularity concentration."""
    return ScenarioSpec(
        name="read_storm", duration_s=duration_s, clients=8,
        n_volume_servers=1, read_fraction=1.0, zipf_s=1.2, hot_set=256,
        deadline_s=2.0,
        expectations={"max_error_ratio": 0.01,
                      "deadline_overrun_max_ms": 250.0})


def write_churn(duration_s: float = 10.0) -> ScenarioSpec:
    """Mixed-size write + delete churn + vacuum: the ingest side.
    Exercises assign/grow under sustained writes of 4KB..1MB objects
    while deletes accumulate garbage and vacuum reclaims it mid-load."""
    return ScenarioSpec(
        name="write_churn", duration_s=duration_s, clients=6,
        n_volume_servers=1, read_fraction=0.30, churn_fraction=0.25,
        zipf_s=1.0, hot_set=96, vacuum_every_s=3.0, deadline_s=3.0,
        expectations={"max_error_ratio": 0.02,
                      "deadline_overrun_max_ms": 250.0})


def failure_under_load(duration_s: float = 21.0) -> ScenarioSpec:
    """The degradation-under-fault proof: Zipfian read-mostly load over
    three servers, one of which is network-partitioned for the middle
    third of the run while part of the write path proxies through the
    master (so the partition surfaces as server-side 5xx and burns the
    SLO).  The verdict demands the healthy fraction keeps serving, the
    accepted requests stay fast, nobody outlives their deadline, and
    the burn-rate alert both fires during the fault and resolves after
    — graceful degradation, machine-checked."""
    return ScenarioSpec(
        name="failure_under_load", duration_s=duration_s, clients=8,
        n_volume_servers=3, read_fraction=0.80, submit_fraction=0.50,
        zipf_s=1.0, hot_set=240, deadline_s=2.0, max_inflight=64,
        faults=(FaultSpec(point="net.partition", at_frac=1 / 3,
                          clear_frac=2 / 3, error_rate=1.0, peer="vs0"),),
        expectations={"fault_rps_ratio_min": 0.60,
                      "fault_p99_factor_max": 5.0,
                      "deadline_overrun_max_ms": 250.0,
                      "alert_fired_any": ["scenario_error_burn",
                                          "peer_down",
                                          "requests_shed_increase",
                                          "deadline_exceeded_increase"],
                      "alert_resolved": True})


def flash_crowd(duration_s: float = 14.0) -> ScenarioSpec:
    """The heat-telemetry proof (observability/heat.py): Zipfian reads
    over two volume servers with locality-preserving preload, then
    mid-run the Zipf head jumps to the cold half of the rank list.
    The master's heat journal must notice — the head-set shift
    detector fires heat_shift/flash_crowd naming the newly hot volume
    within seconds, carrying an exemplar trace id — while the serving
    plane itself stays healthy."""
    return ScenarioSpec(
        name="flash_crowd", duration_s=duration_s, clients=8,
        n_volume_servers=2, read_fraction=1.0, zipf_s=1.3, hot_set=128,
        deadline_s=2.0, preload_locality=True, head_shift_frac=0.45,
        expectations={"max_error_ratio": 0.02,
                      "deadline_overrun_max_ms": 250.0,
                      "alert_fired_any": ["heat_shift", "flash_crowd"],
                      "heat_alert_within_s": 5.0})


def flash_crowd_autoscale(duration_s: float = 18.0) -> ScenarioSpec:
    """The closed-loop acceptance drill (ops/autoscaler.py): the
    flash_crowd shape — Zipf head jumps onto one volume mid-run — but
    with the heat autoscaler ON over three rack-diverse servers.  The
    verdict demands the loop actually closes: a replica-add lands
    within seconds of the shift, the journaled replica_grow carries
    the causing heat alert id and an exemplar trace, the hot set's
    p99 is back inside the SLO within the recovery budget, and the
    thrash guard held (at most one grow/shrink cycle per volume)."""
    return ScenarioSpec(
        name="flash_crowd_autoscale", duration_s=duration_s, clients=8,
        n_volume_servers=3, read_fraction=1.0, zipf_s=1.3, hot_set=128,
        deadline_s=2.0, preload_locality=True, head_shift_frac=0.40,
        autoscale=True,
        expectations={"max_error_ratio": 0.02,
                      "deadline_overrun_max_ms": 500.0,
                      "alert_fired_any": ["heat_shift", "flash_crowd"],
                      "heat_alert_within_s": 5.0,
                      "autoscale_grow_within_s": 8.0,
                      "autoscale_attribution": True,
                      "autoscale_slo_p99_ms": 250.0,
                      "autoscale_recover_within_s": 10.0,
                      "autoscale_max_cycles": 1})


def master_failover(duration_s: float = 16.0) -> ScenarioSpec:
    """The control-plane HA proof (master/consensus.py raft log +
    scenarios/failover.py runner): a 3-master quorum under a write
    storm loses its leader mid EC repair.  The verdict demands a new
    leader within the election budget, /dir/assign serving again
    inside one client deadline, ZERO loss of pre-kill journaled events
    (the raft-replicated journal contract), and the orphaned repair
    re-planned by the new leader with its original alert/trace cause
    attribution intact."""
    return ScenarioSpec(
        name="master_failover", duration_s=duration_s, clients=6,
        n_masters=3, n_volume_servers=4, read_fraction=0.0,
        zipf_s=1.0, hot_set=48, deadline_s=3.0,
        expectations={"election_max_s": 8.0,
                      "journal_loss_max": 0,
                      "assign_after_kill_max_s": 5.0,
                      "repair_replan_max_s": 45.0})


def default_scenarios() -> list[ScenarioSpec]:
    """The three canonical bench scenarios, in run order."""
    return [read_storm(), write_churn(), failure_under_load()]
