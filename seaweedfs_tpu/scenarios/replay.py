"""Trace-driven replay: fit a workload recording into a ScenarioSpec.

The workload flight recorder (observability/reqlog.py) turns live
traffic into a recording document; this module turns that recording
into a *repeatable* scenario the existing engine can drive — five
minutes of production traffic becomes a spec you can replay against a
candidate build, at recorded speed or a ``speed`` multiplier, with the
alert plane live.

``spec_from_recording`` estimates:

  - the read/write/delete op mix straight from the recorded route
    classes (native_* and http_* fold into the same logical ops);
  - the master-proxied write share (``submit_fraction``) from the
    /submit handler records;
  - the size mix by bucketing observed write sizes (falling back to
    read response sizes for read-only recordings) into at most four
    weighted buckets — the ScenarioSpec ``sizes`` shape;
  - Zipf skew from observed key popularity: a log-log least-squares
    fit of frequency against rank (P(r) ~ 1/r^s means
    log f_r = c - s log r), clamped to the sane [0.0, 3.0] band;
  - open-loop pacing: ``target_rps`` is the recorded arrival rate
    times ``speed`` (the engine schedules ops on a fixed clock and
    catches up after a slow op instead of slowing down — closed-loop
    replay would let a degraded build hide by back-pressuring its own
    load);
  - the per-request deadline from the recorded budget median.

``replay_fidelity`` is the machine check that the fit (and optionally
a finished replay run) reproduces the recording: op mix, size mix, and
the hot-set head's probability mass, each within an explicit
tolerance.  Eyeballing is not a verdict.
"""

from __future__ import annotations

import math
from collections import Counter
from random import Random
from typing import Optional

from .spec import ScenarioSpec
from .workload import SizeSampler, ZipfSampler

# recorded route class -> logical replay op
READ_ROUTES = ("http_read", "native_read")
WRITE_ROUTES = ("http_write", "native_write")
DELETE_ROUTES = ("http_delete", "native_delete")
WORKLOAD_ROUTES = READ_ROUTES + WRITE_ROUTES + DELETE_ROUTES


def workload_records(recording: dict) -> list[dict]:
    """The replayable subset: object-plane records, time-ordered.
    Telemetry/ops records (shipper POSTs, scrapes) never replay."""
    records = [r for r in (recording.get("records") or [])
               if r.get("route") in WORKLOAD_ROUTES]
    records.sort(key=lambda r: float(r.get("ts") or 0.0))
    return records


def estimate_zipf_s(counts: list[int]) -> float:
    """Zipf exponent from descending popularity counts via least
    squares on (log rank, log freq).  One distinct key (or none) has
    no measurable skew -> 0.0; the result is clamped to [0.0, 3.0] so
    a pathological sample cannot produce an unusable spec."""
    counts = sorted((c for c in counts if c > 0), reverse=True)
    if len(counts) < 2:
        return 0.0
    xs = [math.log(rank + 1.0) for rank in range(len(counts))]
    ys = [math.log(c) for c in counts]
    n = float(len(xs))
    mx = sum(xs) / n
    my = sum(ys) / n
    denom = sum((x - mx) ** 2 for x in xs)
    if denom <= 0.0:
        return 0.0
    slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
    return max(0.0, min(-slope, 3.0))


def fit_size_mix(sizes: list[int], max_buckets: int = 4) -> tuple:
    """Observed byte sizes -> the ScenarioSpec ``sizes`` shape:
    ((bytes, weight), ...) with at most max_buckets buckets.  Sizes
    bucket by power of two (the workload-relevant resolution — 4KB vs
    64KB vs 1MB matters, 4000 vs 4096 does not); each bucket is
    represented by its observed median so replayed bytes stay honest.
    Ties keep the heaviest buckets."""
    sizes = [s for s in sizes if s > 0]
    if not sizes:
        return ((4096, 1.0),)
    buckets: dict[int, list[int]] = {}
    for s in sizes:
        buckets.setdefault(max(s, 1).bit_length(), []).append(s)
    ranked = sorted(buckets.values(), key=len, reverse=True)[:max_buckets]
    total = sum(len(b) for b in ranked)
    out = []
    for b in ranked:
        b.sort()
        out.append((int(b[len(b) // 2]), round(len(b) / total, 4)))
    out.sort()
    return tuple(out)


def recording_profile(recording: dict) -> dict:
    """The measured shape of a recording — what spec_from_recording
    fits from and what replay_fidelity compares against."""
    records = workload_records(recording)
    reads = [r for r in records if r["route"] in READ_ROUTES]
    writes = [r for r in records if r["route"] in WRITE_ROUTES]
    deletes = [r for r in records if r["route"] in DELETE_ROUTES]
    n = len(records)
    key_counts = Counter(
        str(r.get("path") or "").partition("?")[0] for r in reads)
    write_sizes = [int(r.get("in") or 0) for r in writes]
    read_sizes = [int(r.get("out") or 0) for r in reads]
    ts = [float(r.get("ts") or 0.0) for r in records if r.get("ts")]
    window_s = max(ts) - min(ts) if len(ts) >= 2 else 0.0
    submit_writes = sum(1 for r in writes
                        if (r.get("handler") or "") == "submit")
    budgets = sorted(float(r["ddl_s"]) for r in records
                     if r.get("ddl_s"))
    # sample-rate correction: each record carries the recorder's
    # sampling rate at capture time and stands for ~1/sample real
    # requests — a -sample 0.1 recording must replay at PRODUCTION
    # arrival rate, not a tenth of it.  (Mix fractions are invariant
    # under a uniform rate; the rate only scales arrivals.)
    effective = sum(
        1.0 / min(max(float(r.get("sample") or 1.0), 1e-3), 1.0)
        for r in records)
    return {
        "records": n,
        "window_s": round(window_s, 3),
        "observed_rps": round(effective / window_s, 2)
        if window_s > 0 else 0.0,
        "read_fraction": round(len(reads) / n, 4) if n else 0.0,
        "churn_fraction": round(
            len(deletes) / (len(writes) + len(deletes)), 4)
        if (writes or deletes) else 0.0,
        "submit_fraction": round(submit_writes / len(writes), 4)
        if writes else 0.0,
        "distinct_keys": len(key_counts),
        "top_keys": key_counts.most_common(16),
        "zipf_s": round(estimate_zipf_s(list(key_counts.values())), 3),
        "sizes": fit_size_mix(write_sizes or read_sizes),
        "deadline_p50_s": round(budgets[len(budgets) // 2], 3)
        if budgets else 0.0,
    }


def spec_from_recording(recording: dict, name: str = "replay",
                        speed: float = 1.0,
                        duration_s: Optional[float] = None,
                        clients: int = 8,
                        n_volume_servers: int = 1,
                        seed: int = 0xBEE5) -> ScenarioSpec:
    """Fit a recording document (the /cluster/workload/export shape)
    into a replayable ScenarioSpec.  Raises ValueError on a recording
    with no workload records — an empty spec would "pass" replaying
    nothing."""
    prof = recording_profile(recording)
    if not prof["records"]:
        raise ValueError("recording has no workload records to replay "
                        "(only ops/telemetry traffic was captured)")
    speed = max(float(speed), 0.01)
    if duration_s is None:
        duration_s = prof["window_s"] / speed if prof["window_s"] > 0 \
            else 10.0
    duration_s = max(min(float(duration_s), 300.0), 2.0)
    target_rps = round(prof["observed_rps"] * speed, 2) \
        if prof["observed_rps"] > 0 else 0.0
    hot_set = max(min(prof["distinct_keys"], 4096), 8)
    deadline_s = prof["deadline_p50_s"] or 2.0
    spec = ScenarioSpec(
        name=name,
        duration_s=duration_s,
        clients=max(int(clients), 1),
        n_volume_servers=max(int(n_volume_servers), 1),
        read_fraction=prof["read_fraction"],
        churn_fraction=prof["churn_fraction"],
        submit_fraction=prof["submit_fraction"],
        zipf_s=prof["zipf_s"],
        hot_set=hot_set,
        sizes=prof["sizes"],
        deadline_s=deadline_s,
        target_rps=target_rps,
        seed=seed,
        expectations={"max_error_ratio": 0.02,
                      "deadline_overrun_max_ms": 250.0})
    return spec


# --- fidelity ---------------------------------------------------------------

def _spec_op_mix(spec: ScenarioSpec, samples: int = 4000,
                 seed: int = 17) -> dict:
    """What the spec's samplers will actually produce, measured by
    sampling them — the same code path the engine's client loops run,
    so a fit bug cannot hide behind the formula that produced it."""
    from .workload import pick_op

    rng = Random(seed)
    ops = Counter(pick_op(rng, spec.read_fraction, spec.churn_fraction)
                  for _ in range(samples))
    sizes = SizeSampler(spec.sizes)
    drawn = [sizes.sample(rng) for _ in range(samples)]
    return {"read_fraction": ops["read"] / samples,
            "mean_size": sum(drawn) / samples,
            "delete_fraction": ops["delete"] / samples}


def replay_fidelity(recording: dict, spec: ScenarioSpec,
                    result: Optional[dict] = None,
                    op_tol: float = 0.10, size_tol: float = 0.5,
                    head_tol: float = 0.25,
                    pacing_tol: float = 0.8) -> list[dict]:
    """Machine-check that the fitted spec (and optionally a finished
    replay run's result document) reproduces the recording.  Returns
    the same ``checks`` shape the scenario engine emits: every entry
    carries ok/value/bound, and a replay whose fidelity list has a
    failing entry must not be presented as a faithful reproduction.

      op_mix    — spec-sampled read fraction within op_tol of recorded;
      size_mix  — spec-sampled mean size within (1 ± size_tol)x;
      hot_head  — the recorded top-10 keys' probability mass vs the
                  fitted Zipf head's mass, within head_tol;
      (+ with ``result``) replayed_op_mix — the replay run's measured
      read fraction within 1.5*op_tol of recorded (live runs add
      sampling noise on top of the fit);
      (+ with ``result``) fidelity_pacing — the replay actually
      delivered >= pacing_tol of the spec's open-loop target_rps
      (an under-delivered replay proves nothing about the recorded
      load).
    """
    prof = recording_profile(recording)
    checks: list[dict] = []

    def check(name, ok, value, bound):
        checks.append({"check": name, "ok": bool(ok),
                       "value": value, "bound": bound})

    mix = _spec_op_mix(spec)
    dv = round(abs(mix["read_fraction"] - prof["read_fraction"]), 4)
    check("fidelity_op_mix", dv <= op_tol, dv, op_tol)

    rec_sizes = prof["sizes"]
    rec_mean = sum(b * w for b, w in rec_sizes) / \
        max(sum(w for _b, w in rec_sizes), 1e-9)
    ratio = round(mix["mean_size"] / max(rec_mean, 1.0), 3)
    check("fidelity_size_mix",
          1.0 - size_tol <= ratio <= 1.0 + size_tol, ratio,
          [round(1.0 - size_tol, 2), round(1.0 + size_tol, 2)])

    total_reads = sum(c for _k, c in prof["top_keys"]) if prof[
        "top_keys"] else 0
    all_read_count = max(
        sum(1 for r in workload_records(recording)
            if r["route"] in READ_ROUTES), 1)
    if prof["distinct_keys"] >= 2 and total_reads:
        head_n = min(10, prof["distinct_keys"])
        rec_head = sum(c for _k, c in prof["top_keys"][:head_n]) \
            / all_read_count
        zipf = ZipfSampler(spec.hot_set, spec.zipf_s)
        fit_head = sum(zipf.pmf(r) for r in range(head_n))
        dh = round(abs(fit_head - rec_head), 4)
        check("fidelity_hot_head", dh <= head_tol, dh, head_tol)

    if result is not None:
        routes = result.get("routes") or {}
        total = sum(r["ops"] for r in routes.values())
        reads = (routes.get("read") or {}).get("ops", 0)
        if total:
            dv = round(abs(reads / total - prof["read_fraction"]), 4)
            check("fidelity_replayed_op_mix", dv <= 1.5 * op_tol, dv,
                  round(1.5 * op_tol, 3))
        # open-loop pacing actually delivered: an under-delivered
        # replay (client pool could not keep the recorded schedule
        # against a slow build) must not be presented as "faced
        # recorded arrivals" — the same honesty rule the capacity
        # probe enforces with its achieved >= 92% gate (replay gets
        # more slack: short drills quantize per-client schedules)
        if spec.target_rps > 0 and total:
            wall = float(result.get("wall_s") or spec.duration_s) or 1.0
            achieved = round(total / wall / spec.target_rps, 3)
            check("fidelity_pacing", achieved >= pacing_tol, achieved,
                  pacing_tol)
    return checks
