"""The scenario engine: run one ScenarioSpec against a real in-process
cluster and emit a measured, verdicted result document.

The engine spawns a master + N volume servers IN PROCESS (the chaos-
drill shape: fault points armed here fire at every client AND server
egress, the alert engine evaluates live on the master's telemetry
loop), preloads the Zipfian hot set, then drives client threads for
duration_s.  Every client op runs under the spec's deadline
(utils/deadline.py scope -> X-Weed-Deadline propagates across every
hop), faults arm/clear on the spec's timeline, and an alert poller
records the fire/resolve transitions the degradation causes.

The result document carries per-route RED stats (count, error ratio,
p50/p90/p99), per-phase throughput + accepted-p99 (healthy / fault /
recovery), the request-plane counter deltas (shed, deadline_exceeded,
retry_budget_exhausted), the fault + alert timelines, one stitched
sampled trace, and a `checks` list scoring the spec's expectations —
`verdict` is "pass" only when every check holds.  bench.py's
`scenarios` section embeds these documents verbatim.
"""

from __future__ import annotations

import random
import socket
import tempfile
import threading
import time
from typing import Optional

from ..utils import deadline as _deadline
from ..utils import faultinject as fi
from ..utils.backoff import get_retry_budget
from ..utils.httpd import HttpError, http_bytes, http_json
from .spec import ScenarioSpec
from .workload import (SizeSampler, ZipfSampler, payload_for,
                       percentile as _percentile, pick_op)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Op:
    """One client operation's measurement."""

    __slots__ = ("route", "t", "lat", "status")

    def __init__(self, route: str, t: float, lat: float, status: int):
        self.route = route
        self.t = t          # start offset from load t0, seconds
        self.lat = lat      # wall latency, seconds
        self.status = status

    @property
    def ok(self) -> bool:
        return 200 <= self.status < 300


class _AlertWatch:
    """Samples the master alert engine and keeps the transition
    timeline: which alerts fired when, and whether they resolved."""

    def __init__(self, master, t0: float):
        self.master = master
        self.t0 = t0
        self.fired_at: dict[str, float] = {}
        self.resolved_at: dict[str, float] = {}
        self.timeline: list[dict] = []
        self._last: dict[str, str] = {}

    def sample(self) -> None:
        now = round(time.monotonic() - self.t0, 2)
        try:
            doc = self.master.alert_engine.to_dict()
        except Exception:
            return
        for a in doc.get("alerts", []):
            name, state = a["name"], a["state"]
            if self._last.get(name) == state:
                continue
            first_sight = name not in self._last
            self._last[name] = state
            if first_sight and state == "inactive":
                continue  # baseline, not a transition
            self.timeline.append({"t": now, "alert": name,
                                  "state": state})
            if state == "firing":
                self.fired_at.setdefault(name, now)
            elif state == "resolved" and name in self.fired_at:
                self.resolved_at[name] = now

    def firing_now(self) -> set:
        return {n for n, s in self._last.items() if s == "firing"}


def _shrink_alert_windows(master) -> None:
    """Scenario scale: SLO windows short enough to breach AND resolve
    inside one drill, plus a run-scoped burn-rate rule over the
    MASTER's per-route RED (the proxied write path surfaces a
    partition as master 5xx — exactly the error-budget burn the rule
    exists to catch)."""
    from ..observability.alerts import Rule

    for r in master.alert_engine.rules:
        r.keep_firing_s = 3.0
        if r.kind == "burn_rate":
            r.params.update({"fast_s": 3.0, "slow_s": 8.0,
                             "min_requests": 15})
    master.alert_engine.add_rule(Rule(
        "scenario_error_burn", "burn_rate", severity="critical",
        keep_firing_s=3.0,
        params={"mode": "error_ratio",
                "errors": "SeaweedFS_master_request_errors_total",
                "requests": "SeaweedFS_master_request_total",
                "max_ratio": 0.05, "fast_s": 3.0, "slow_s": 8.0,
                "min_requests": 10},
        description="run-scoped: master 5xx ratio > 5% over the "
                    "drill-scale fast+slow windows"))


def _preload(master_url: str, spec: ScenarioSpec,
             rng: random.Random) -> list[tuple[str, str]]:
    """Write the hot set; returns rank -> (fid, url) REORDERED so
    consecutive ranks round-robin across the servers that hold them —
    the Zipf head's mass then splits evenly, and partitioning one
    server costs ~1/N of the traffic by construction instead of by
    luck."""
    sizes = SizeSampler(spec.sizes)
    by_url: dict[str, list[tuple[str, str]]] = {}
    for rank in range(spec.hot_set):
        r = http_json("GET", f"http://{master_url}/dir/assign?count=1",
                      timeout=15.0)
        fid, url = r["fid"], r["url"]
        payload = payload_for(sizes.sample(rng), rank)
        st, body, _ = http_bytes("POST", f"http://{url}/{fid}", payload,
                                 timeout=30.0)
        if st not in (200, 201):
            raise RuntimeError(
                f"preload write {fid} -> {st}: {body[:120]!r}")
        by_url.setdefault(url, []).append((fid, url))
    ranks: list[tuple[str, str]] = []
    buckets = [list(v) for _u, v in sorted(by_url.items())]
    if spec.preload_locality:
        # keep placement: consecutive ranks stay on the server the
        # master chose, so the Zipf head lives on one server/volume
        # and a mid-run head shift moves heat BETWEEN volumes (what
        # the flash-crowd drill proves) instead of pre-smearing it
        for b in buckets:
            ranks.extend(b)
        return ranks
    while any(buckets):
        for b in buckets:
            if b:
                ranks.append(b.pop(0))
    return ranks


def _client_loop(ci: int, spec: ScenarioSpec, master_url: str,
                 ranks: list, zipf: ZipfSampler, t0: float,
                 stop: threading.Event, out: list,
                 shift: dict) -> None:
    rng = random.Random(spec.seed * 1000003 + ci)
    sizes = SizeSampler(spec.sizes)
    written: list[tuple[str, str]] = []  # this client's own objects
    seq = 0
    # open-loop pacing (replayed recordings): each client owns an
    # interleaved slice of a fixed global schedule — slot k of client
    # ci fires at (ci + k*clients)/target_rps.  The schedule never
    # slips: an op that ran long makes the NEXT slot fire immediately
    # (catch-up), so a degraded server faces the recorded arrival
    # rate instead of quietly back-pressuring its own load.
    pace = spec.target_rps > 0
    interval = spec.clients / spec.target_rps if pace else 0.0
    next_t = t0 + (ci / spec.target_rps) if pace else 0.0
    while not stop.is_set():
        if pace:
            delay = next_t - time.monotonic()
            if delay > 0 and stop.wait(delay):
                break
            next_t += interval
        op = pick_op(rng, spec.read_fraction, spec.churn_fraction)
        if op == "delete" and not written:
            op = "write"
        t_op = time.monotonic()
        status = 0
        try:
            with _deadline.scope(spec.deadline_s):
                if op == "read":
                    # shift["off"] rotates the popularity ranking: rank
                    # r's traffic lands on object (r + off) % n, so a
                    # mid-run off jump moves the WHOLE Zipf head to
                    # previously cold objects (the flash-crowd drill)
                    fid, url = ranks[(zipf.sample(rng) + shift["off"])
                                     % len(ranks)]
                    status, _b, _h = http_bytes(
                        "GET", f"http://{url}/{fid}", timeout=30.0)
                elif op == "write":
                    seq += 1
                    payload = payload_for(sizes.sample(rng),
                                          ci * 31 + seq)
                    if rng.random() < spec.submit_fraction:
                        status, body, _h = http_bytes(
                            "POST", f"http://{master_url}/submit",
                            payload, timeout=30.0)
                        if status == 201:
                            import json as _json

                            doc = _json.loads(body)
                            written.append(
                                (doc["fid"],
                                 doc["fileUrl"].rsplit("/", 1)[0]))
                    else:
                        r = http_json(
                            "GET",
                            f"http://{master_url}/dir/assign?count=1",
                            timeout=30.0)
                        status, _b, _h = http_bytes(
                            "POST", f"http://{r['url']}/{r['fid']}",
                            payload, timeout=30.0)
                        if 200 <= status < 300:
                            written.append((r["fid"], r["url"]))
                else:  # delete
                    fid, url = written.pop(
                        rng.randrange(len(written)))
                    status, _b, _h = http_bytes(
                        "DELETE", f"http://{url}/{fid}", timeout=30.0)
        except _deadline.DeadlineExceeded:
            status = 504
        except HttpError as e:
            status = e.status
        except Exception:
            status = 0
        out.append(_Op(op, t_op - t0, time.monotonic() - t_op, status))


def _route_stats(ops: list, wall_s: float) -> dict:
    by_route: dict[str, list] = {}
    for o in ops:
        by_route.setdefault(o.route, []).append(o)
    out = {}
    for route, rops in sorted(by_route.items()):
        lat = sorted(o.lat for o in rops if o.ok)
        errors = sum(1 for o in rops if not o.ok)
        out[route] = {
            "ops": len(rops),
            "ok": len(rops) - errors,
            "errors": errors,
            "error_ratio": round(errors / len(rops), 4) if rops else 0.0,
            "rps": round(len(rops) / max(wall_s, 1e-9), 1),
            "ok_rps": round((len(rops) - errors) / max(wall_s, 1e-9), 1),
            "p50_ms": round(_percentile(lat, 0.50) * 1e3, 1),
            "p90_ms": round(_percentile(lat, 0.90) * 1e3, 1),
            "p99_ms": round(_percentile(lat, 0.99) * 1e3, 1),
            "shed_503": sum(1 for o in rops if o.status == 503),
            "deadline_504": sum(1 for o in rops if o.status == 504),
        }
    return out


def _phase_stats(ops: list, phases: dict, wall_s: float) -> dict:
    out = {}
    for name, (lo, hi) in phases.items():
        pops = [o for o in ops if lo <= o.t < hi]
        lat = sorted(o.lat for o in pops if o.ok)
        # rate over the phase's REAL extent (the last phase's inclusion
        # bound is open-ended so stragglers land somewhere)
        span = max(min(hi, wall_s) - lo, 1e-9)
        out[name] = {
            "ops": len(pops),
            "ok_rps": round(sum(1 for o in pops if o.ok) / span, 1),
            "error_ratio": round(
                sum(1 for o in pops if not o.ok) / len(pops), 4)
            if pops else 0.0,
            "accepted_p99_ms": round(_percentile(lat, 0.99) * 1e3, 1),
        }
    return out


def _evaluate(spec: ScenarioSpec, result: dict,
              watch: _AlertWatch, fault_window) -> list[dict]:
    """Score the spec's expectations -> the checks list."""
    checks: list[dict] = []
    exp = spec.expectations

    def check(name, ok, value, bound):
        checks.append({"check": name, "ok": bool(ok),
                       "value": value, "bound": bound})

    if "max_error_ratio" in exp:
        total = sum(r["ops"] for r in result["routes"].values())
        errs = sum(r["errors"] for r in result["routes"].values())
        ratio = round(errs / total, 4) if total else 0.0
        check("error_ratio", ratio <= exp["max_error_ratio"], ratio,
              exp["max_error_ratio"])
    if "deadline_overrun_max_ms" in exp:
        over = result["deadline"]["max_overrun_ms"]
        check("deadline_overrun_ms",
              over <= exp["deadline_overrun_max_ms"], over,
              exp["deadline_overrun_max_ms"])
        violations = result["deadline"]["violations"]
        check("deadline_violations", violations == 0, violations, 0)
    if fault_window is not None:
        ph = result["phases"]
        if "fault_rps_ratio_min" in exp:
            base = ph["healthy"]["ok_rps"] or 1e-9
            ratio = round(ph["fault"]["ok_rps"] / base, 3)
            check("fault_rps_ratio", ratio >= exp["fault_rps_ratio_min"],
                  ratio, exp["fault_rps_ratio_min"])
        if "fault_p99_factor_max" in exp:
            base = ph["healthy"]["accepted_p99_ms"] or 1e-9
            factor = round(ph["fault"]["accepted_p99_ms"] / base, 2)
            check("fault_p99_factor",
                  factor <= exp["fault_p99_factor_max"], factor,
                  exp["fault_p99_factor_max"])
    if "alert_fired_any" in exp and watch is not None:
        names = exp["alert_fired_any"]
        fired = [n for n in names if n in watch.fired_at]
        check("alert_fired", bool(fired), fired, names)
        if exp.get("alert_resolved"):
            unresolved = sorted(set(fired) & watch.firing_now())
            check("alert_resolved", not unresolved,
                  unresolved, [])
    if "heat_alert_within_s" in exp:
        heat = result.get("heat") or {}
        lat = heat.get("alert_latency_s")
        check("heat_alert_within_s",
              lat is not None and lat <= exp["heat_alert_within_s"],
              lat, exp["heat_alert_within_s"])
        check("heat_alert_named_volume",
              bool(heat.get("named_volume")),
              heat.get("named_volume"), "nonempty")
    auto = result.get("autoscale") or {}
    if "autoscale_grow_within_s" in exp:
        g = auto.get("first_grow_after_shift_s")
        check("autoscale_grow_within_s",
              g is not None and g <= exp["autoscale_grow_within_s"],
              g, exp["autoscale_grow_within_s"])
    if exp.get("autoscale_attribution"):
        check("autoscale_attribution", bool(auto.get("attributed")),
              auto.get("attributed"), True)
    if "autoscale_recover_within_s" in exp:
        r = auto.get("slo_recovery_s")
        check("autoscale_recover_within_s",
              r is not None and r <= exp["autoscale_recover_within_s"],
              r, exp["autoscale_recover_within_s"])
    if "autoscale_max_cycles" in exp:
        c = auto.get("max_cycles_per_volume", 0)
        check("autoscale_max_cycles", c <= exp["autoscale_max_cycles"],
              c, exp["autoscale_max_cycles"])
    return checks


def run_against(spec: ScenarioSpec, master_url: str,
                log=None) -> dict:
    """Drive a ScenarioSpec against a LIVE cluster (the
    ``workload.replay -against host:port`` mode): same client loops,
    same open-loop pacing, same result/checks document — but the
    servers are whoever answers at ``master_url`` instead of an
    in-process cluster spawned for the run.  This is how a recorded
    workload proves a refactor on real before/after builds: record on
    the old build, replay -against both, bench_diff the numbers.

    No faults are armed and no alert engine is sampled (the live
    cluster's own alert plane keeps running); the hot set is PRELOADED
    onto the target (it writes load objects, like capacity.probe —
    hold the admin lock).  Checks cover the spec's error-ratio and
    deadline expectations; fault-phase expectations are skipped."""
    say = log or (lambda _m: None)
    rng = random.Random(spec.seed)
    say(f"{spec.name}: preloading {spec.hot_set} objects onto "
        f"{master_url}")
    ranks = _preload(master_url, spec, rng)
    zipf = ZipfSampler(len(ranks), spec.zipf_s)
    result: dict = {"name": spec.name, "spec": spec.to_dict(),
                    "against": master_url}
    stop = threading.Event()
    t0 = time.monotonic()
    shift = {"off": 0}  # replay mode never shifts the head
    per_client_ops: list[list] = [[] for _ in range(spec.clients)]
    threads = [threading.Thread(
        target=_client_loop,
        args=(ci, spec, master_url, ranks, zipf, t0, stop,
              per_client_ops[ci], shift),
        daemon=True, name=f"replay-{spec.name}-c{ci}")
        for ci in range(spec.clients)]
    say(f"{spec.name}: driving {spec.clients} clients for "
        f"{spec.duration_s:.0f}s against {master_url}")
    for t in threads:
        t.start()
    try:
        time.sleep(spec.duration_s)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
    ops = [o for lst in per_client_ops for o in lst]
    ops.sort(key=lambda o: o.t)
    wall = spec.duration_s
    overruns = [max(0.0, o.lat - spec.deadline_s) for o in ops]
    result.update({
        "wall_s": round(wall, 1),
        "total_ops": len(ops),
        "routes": _route_stats(ops, wall),
        "phases": _phase_stats(ops, {"healthy": (0.0, wall + 1e9)},
                               wall),
        "deadline": {
            "budget_s": spec.deadline_s,
            "violations": sum(1 for ov in overruns if ov > 0.25),
            "max_overrun_ms": round(max(overruns, default=0.0) * 1e3,
                                    1),
        },
    })
    checks = _evaluate(spec, result, None, None)
    result["checks"] = checks
    result["degraded"] = any(not c["ok"] for c in checks)
    result["verdict"] = "degraded" if result["degraded"] else "pass"
    return result


def run_scenario(spec: ScenarioSpec, base_dir: Optional[str] = None,
                 log=None) -> dict:
    """Run one scenario end to end; returns the result document.
    Always cleans up (servers, fault points, retry-budget buckets) —
    scenarios must compose in one bench process."""
    from ..master.server import MasterServer
    from ..observability import (disable_tracing, enable_tracing,
                                 get_tracer, set_sample_rate)
    from ..observability.context import sample_rate
    from ..stats import request_plane_metrics
    from ..volume_server.server import VolumeServer

    import shutil

    say = log or (lambda _m: None)
    roots = [tempfile.mkdtemp(dir=base_dir)
             for _ in range(spec.n_volume_servers)]
    tracing_was_on = get_tracer().enabled
    prev_rate = sample_rate()
    if not tracing_was_on:
        enable_tracing()
    # only forced requests trace (zero hot-path cost) — except heat
    # drills, where a small head rate gives the shift detector's event
    # an exemplar trace to carry (the accumulator keeps the freshest
    # sampled trace id per volume)
    set_sample_rate(0.02 if spec.head_shift_frac > 0 else 0.0)
    result: dict = {"name": spec.name, "spec": spec.to_dict()}
    stop = threading.Event()
    threads: list[threading.Thread] = []
    master = None
    servers: list = []
    try:
        # everything that can fail (port races on start, registration
        # timeouts) happens INSIDE the try: a scenario that dies half-
        # started must still stop whatever came up — scenarios run
        # back-to-back in one bench process, and a leaked telemetry
        # loop would skew the next one's counters
        # drill-scale autoscaler knobs: second-scale planning, a grow
        # threshold the shifted Zipf head clears within one decay
        # half-life, short hold-down/cooldown so a shrink could
        # physically happen inside the run (the thrash guard must
        # hold by HYSTERESIS, not by the run being too short to flap)
        auto_opts = {"grow_share": 0.30, "cold_share": 0.02,
                     "hold_down_s": 6.0, "regrow_cooldown_s": 6.0,
                     "max_replicas": 3, "move_rate": 2.0,
                     "move_burst": 4.0, "actuation_deadline_s": 30.0} \
            if spec.autoscale else None
        master = MasterServer(port=_free_port(), pulse_seconds=0.3,
                              metrics_aggregation_seconds=0.25,
                              autoscale_seconds=(
                                  1.0 if spec.autoscale else 0.0),
                              autoscale_opts=auto_opts).start()
        master.aggregator.min_interval = 0.0
        master.alert_engine.min_interval = 0.0
        if spec.fast_alerts:
            _shrink_alert_windows(master)
        for i in range(spec.n_volume_servers):
            servers.append(VolumeServer(
                [roots[i]], master.url, port=_free_port(),
                rack=f"r{i}", data_center="dc1", pulse_seconds=0.3,
                max_volume_count=16,
                max_inflight=spec.max_inflight).start())
        plane0 = request_plane_metrics().totals()
        deadline_reg = time.time() + 15
        while time.time() < deadline_reg and \
                len(master.topo.all_nodes()) < spec.n_volume_servers:
            time.sleep(0.05)
        # pre-grow volumes across EVERY server: the first assign's
        # growth is winner-takes-all on the emptiest node, which would
        # quietly put the whole hot set on one server — a partition
        # drill against any OTHER server would then prove nothing
        try:
            http_json("GET", f"http://{master.url}/vol/grow"
                             f"?count={3 * spec.n_volume_servers}",
                      timeout=30.0)
        except HttpError:
            pass  # assign-triggered growth still works
        if spec.head_shift_frac > 0:
            # heat drill scale: second-scale decay so the shifted head
            # dominates the merged ranking within a couple of shipper
            # flushes, a trailing window short enough that the
            # PRE-shift head is still what "trailing" means when the
            # detector compares, and per-volume event rate limiting
            # that cannot swallow the one shift this run proves
            for vs in servers:
                vs.heat.set_half_life(2.0)
            master.heat_journal.trail_s = max(
                3.0, 0.2 * spec.duration_s)
            master.heat_journal.min_event_interval = 2.0
        rng = random.Random(spec.seed)
        say(f"{spec.name}: preloading {spec.hot_set} objects")
        ranks = _preload(master.url, spec, rng)
        zipf = ZipfSampler(len(ranks), spec.zipf_s)

        t0 = time.monotonic()
        t0_wall = time.time()  # event timestamps are wall-clock
        watch = _AlertWatch(master, t0)
        fault_window = None
        if spec.faults:
            lo = min(f.at_frac for f in spec.faults) * spec.duration_s
            hi = max(f.clear_frac for f in spec.faults) * spec.duration_s
            fault_window = (lo, hi)

        def resolve_peer(peer: str) -> Optional[str]:
            if peer.startswith("vs"):
                try:
                    return servers[int(peer[2:])].url
                except (ValueError, IndexError):
                    return None
            return peer or None

        fault_log: list[dict] = []

        def fault_timeline():
            events = []
            for f in spec.faults:
                events.append((f.at_frac * spec.duration_s, "arm", f))
                events.append((f.clear_frac * spec.duration_s,
                               "clear", f))
            for at, action, f in sorted(events, key=lambda e: e[0]):
                while not stop.is_set() and \
                        time.monotonic() - t0 < at:
                    time.sleep(0.05)
                if stop.is_set():
                    break
                peer = resolve_peer(f.peer)
                if action == "arm":
                    fi.enable(f.point, error_rate=f.error_rate,
                              delay=f.delay,
                              params={"peer": peer} if peer else None)
                    say(f"{spec.name}: armed {f.point} on {peer}")
                else:
                    fi.disable(f.point)
                    say(f"{spec.name}: cleared {f.point}")
                fault_log.append({
                    "t": round(time.monotonic() - t0, 2),
                    "action": action, "point": f.point, "peer": peer})

        shift = {"off": 0}  # read-index rotation shared with clients
        shift_t = [0.0]     # when the head actually moved (t0-relative)

        def head_shifter():
            at = spec.head_shift_frac * spec.duration_s
            while not stop.is_set() and time.monotonic() - t0 < at:
                time.sleep(0.05)
            if stop.is_set():
                return
            # aim the new Zipf head at the COLDEST volume's ranks, not
            # a blind half-rotation: the master's placement can stack
            # most fids onto the already-hot volume, and a rotation
            # that lands back on it moves no heat at all — the drill
            # would then (correctly!) see no head-set shift
            vol_of = [fid.partition(",")[0] for fid, _ in ranks]
            warm: dict = {}
            for i, v in enumerate(vol_of):
                warm[v] = warm.get(v, 0.0) + zipf.pmf(i % zipf.n)
            cold = min(warm, key=lambda v: warm[v])
            shift["off"] = next(
                (i for i, v in enumerate(vol_of) if v == cold),
                len(ranks) // 2)
            shift_t[0] = round(time.monotonic() - t0, 2)
            say(f"{spec.name}: Zipf head shifted by {shift['off']} "
                "ranks onto cold volume {} at t={:.1f}s".format(
                    cold, shift_t[0]))

        def alert_poller():
            while not stop.is_set():
                watch.sample()
                time.sleep(0.25)

        def replica_refresher():
            # the autoscaler's grows only help if clients FIND the new
            # replicas: re-lookup every distinct volume in the rank
            # list and spread consecutive ranks round-robin across the
            # current locations (tuple swaps are atomic under the GIL,
            # so the client loops never see a torn entry)
            while not stop.is_set():
                if stop.wait(0.5):
                    break
                vols: dict[str, list[int]] = {}
                for i, (fid, _u) in enumerate(ranks):
                    vols.setdefault(fid.partition(",")[0], []).append(i)
                for vol, idxs in vols.items():
                    try:
                        doc = http_json(
                            "GET", f"http://{master.url}/dir/lookup"
                                   f"?volumeId={vol}", timeout=5.0)
                    except Exception:
                        continue
                    urls = [loc["url"]
                            for loc in doc.get("locations") or []]
                    if not urls:
                        continue
                    for k, i in enumerate(idxs):
                        ranks[i] = (ranks[i][0], urls[k % len(urls)])

        def vacuum_loop():
            while not stop.is_set():
                if stop.wait(spec.vacuum_every_s):
                    break
                try:
                    http_json("GET", f"http://{master.url}/vol/vacuum"
                                     "?garbageThreshold=0.01",
                              timeout=20.0)
                except Exception:
                    pass

        per_client_ops: list[list] = [[] for _ in range(spec.clients)]
        threads = [threading.Thread(
            target=_client_loop,
            args=(ci, spec, master.url, ranks, zipf, t0, stop,
                  per_client_ops[ci], shift),
            daemon=True, name=f"scn-{spec.name}-c{ci}")
            for ci in range(spec.clients)]
        threads.append(threading.Thread(target=fault_timeline,
                                        daemon=True, name="scn-faults"))
        threads.append(threading.Thread(target=alert_poller,
                                        daemon=True, name="scn-alerts"))
        if spec.head_shift_frac > 0:
            threads.append(threading.Thread(target=head_shifter,
                                            daemon=True,
                                            name="scn-shift"))
        if spec.autoscale:
            threads.append(threading.Thread(target=replica_refresher,
                                            daemon=True,
                                            name="scn-replicas"))
        if spec.vacuum_every_s > 0:
            threads.append(threading.Thread(target=vacuum_loop,
                                            daemon=True,
                                            name="scn-vacuum"))
        say(f"{spec.name}: driving {spec.clients} clients for "
            f"{spec.duration_s:.0f}s")
        for t in threads:
            t.start()
        time.sleep(spec.duration_s)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        fi.clear()

        # grace window: alerts that the fault lit must get their chance
        # to resolve (keep_firing_s is drill-scale when fast_alerts)
        watched_names = set(watch.fired_at)
        grace_deadline = time.time() + 8.0
        while time.time() < grace_deadline:
            watch.sample()
            if not (watched_names & watch.firing_now()):
                break
            time.sleep(0.25)

        ops = [o for lst in per_client_ops for o in lst]
        ops.sort(key=lambda o: o.t)
        wall = spec.duration_s
        phases = {"healthy": (0.0, fault_window[0]),
                  "fault": fault_window,
                  "recovery": (fault_window[1], wall + 1e9)} \
            if fault_window else {"healthy": (0.0, wall + 1e9)}
        overruns = [max(0.0, o.lat - spec.deadline_s) for o in ops]
        plane1 = request_plane_metrics().totals()
        result.update({
            "wall_s": round(wall, 1),
            "total_ops": len(ops),
            "routes": _route_stats(ops, wall),
            "phases": _phase_stats(ops, phases, wall),
            "faults": fault_log,
            "alerts": {
                "fired": sorted(watch.fired_at),
                "resolved": sorted(watch.resolved_at),
                "still_firing": sorted(watch.firing_now()),
                "timeline": watch.timeline[:64],
            },
            "counters": {k: plane1[k] - plane0[k] for k in plane1},
            "deadline": {
                "budget_s": spec.deadline_s,
                "violations": sum(1 for ov in overruns if ov > 0.25),
                "max_overrun_ms": round(max(overruns, default=0.0)
                                        * 1e3, 1),
            },
        })

        # one forced-sample stitched trace as proof the request plane
        # still traces end to end under scenario load
        try:
            fid, url = ranks[0]
            st, _b, hdrs = http_bytes(
                "GET", f"http://{url}/{fid}",
                headers={"X-Force-Trace": "1"}, timeout=10.0)
            trace_id = hdrs.get("X-Trace-Id", "")
            doc = None
            t_tr = time.time() + 5
            while time.time() < t_tr and trace_id:
                try:
                    doc = http_json(
                        "GET", f"http://{master.url}/cluster/traces/"
                               f"{trace_id}", timeout=5.0)
                    break
                except HttpError:
                    time.sleep(0.2)
            if doc:
                an = doc.get("analysis") or {}
                result["trace"] = {
                    "trace_id": trace_id,
                    "span_count": doc.get("span_count", 0),
                    "servers": doc.get("servers", []),
                    "bounding_hop": an.get("bounding_hop", ""),
                }
        except Exception:
            pass

        if spec.head_shift_frac > 0:
            # capture the heat plane's verdict BEFORE teardown.  The
            # latency measure uses the shift EVENT stream, not the
            # alert state machine: reads ramping from zero at run
            # start can legitimately read as a heat shift (they are
            # one), so the proof is the first event emitted AT/AFTER
            # the head move — it must name the newly hot volume and
            # carry an exemplar trace, and the journal_event alert
            # must be firing on it
            fired = {n: t for n, t in watch.fired_at.items()
                     if n in ("heat_shift", "flash_crowd")}
            heat_block: dict = {"shift_t": shift_t[0],
                                "alerts_fired": fired}
            # post-shift serving rate over the NEW hot set: the number
            # the autoscale bench compares ON vs OFF (replica grows
            # should lift it; without them the shifted head stays
            # pinned to one server)
            if shift_t[0] and wall > shift_t[0] + 1.0:
                post_ok = sum(1 for o in ops if o.ok
                              and o.route == "read"
                              and o.t >= shift_t[0])
                heat_block["post_shift_read_rps"] = round(
                    post_ok / (wall - shift_t[0]), 1)
            try:
                doc = http_json(
                    "GET", f"http://{master.url}/cluster/heat?top=8",
                    timeout=10.0)
                heat_block["cluster"] = {
                    "volumes": doc.get("volumes", [])[:6],
                    "head": doc.get("head", {}),
                    "zipf": doc.get("zipf", {}),
                    "imbalance": doc.get("imbalance", {}),
                    "shifts": doc.get("shifts", [])[-6:],
                }
                post = [ev for ev in doc.get("shifts", [])
                        if shift_t[0] and float(ev.get("ts") or 0.0)
                        >= t0_wall + shift_t[0]]
                if post:
                    ev = post[0]
                    d = ev.get("details") or {}
                    heat_block.update({
                        "event": ev.get("type"),
                        "alert_latency_s": round(
                            float(ev["ts"]) - t0_wall - shift_t[0], 2),
                        "named_volume": str(d.get("volume", "")),
                        "share": d.get("share"),
                        "prev_share": d.get("prev_share"),
                        "servers": d.get("servers", []),
                        "exemplar_trace": ev.get("trace") or "",
                    })
            except Exception:
                pass
            try:
                for a in master.alert_engine.to_dict()["alerts"]:
                    if a["name"] in ("heat_shift", "flash_crowd") \
                            and a.get("detail"):
                        heat_block["alert_detail"] = a["detail"]
                        heat_block.setdefault(
                            "exemplar_trace",
                            a.get("exemplar_trace", ""))
                        break
            except Exception:
                pass
            result["heat"] = heat_block

        if spec.autoscale:
            # the closed-loop verdict, captured BEFORE teardown: did
            # the autoscaler react to the shift, with attribution, and
            # did the hot set's p99 come back inside the SLO?
            st = master.autoscaler.status()
            grows = [r for r in st.get("recent", ())
                     if r.get("action") == "replica_grow"]
            shift_wall = t0_wall + shift_t[0] if shift_t[0] else None
            first_grow_s = None
            if grows and shift_wall:
                after = [r["at"] - shift_wall for r in grows
                         if r.get("at", 0.0) >= shift_wall - 0.5]
                if after:
                    first_grow_s = round(min(after), 2)
            cycles = [int(t.get("cycles") or 0)
                      for t in st.get("targets", {}).values()]
            auto_block = {
                "status": {k: st.get(k) for k in
                           ("cycles", "grows", "shrinks", "tiers",
                            "recalls", "failures", "targets",
                            "last_error")},
                "grow_events": [{k: r.get(k) for k in
                                 ("at", "vid", "src", "dst", "alert",
                                  "cause_trace", "cause_event")}
                                for r in grows],
                "first_grow_after_shift_s": first_grow_s,
                "attributed": any(r.get("alert") and r.get("cause_trace")
                                  for r in grows),
                "max_cycles_per_volume": max(cycles, default=0),
            }
            # SLO recovery: walk 1s windows of accepted read latency
            # from the shift on; recovery is the end of the first
            # window whose p99 is back inside the bound
            slo_ms = spec.expectations.get("autoscale_slo_p99_ms")
            if slo_ms and shift_t[0]:
                rec_s = None
                w = shift_t[0]
                while w < wall:
                    lat = sorted(o.lat for o in ops if o.ok
                                 and o.route == "read"
                                 and w <= o.t < w + 1.0)
                    if lat and _percentile(lat, 0.99) * 1e3 <= slo_ms:
                        rec_s = round(w + 1.0 - shift_t[0], 2)
                        break
                    w += 1.0
                auto_block["slo_recovery_s"] = rec_s
            result["autoscale"] = auto_block

        checks = _evaluate(spec, result, watch, fault_window)
        result["checks"] = checks
        result["degraded"] = any(not c["ok"] for c in checks)
        result["verdict"] = "degraded" if result["degraded"] else "pass"
        return result
    finally:
        stop.set()
        fi.clear()
        get_retry_budget().reset()
        for vs in servers:
            try:
                vs.stop()
            except Exception:
                pass
        if master is not None:
            try:
                master.stop()
            except Exception:
                pass
        set_sample_rate(prev_rate)
        if not tracing_was_on:
            disable_tracing()
        for root in roots:
            shutil.rmtree(root, ignore_errors=True)
