"""Production-shaped scenario harness (ROADMAP open item 4).

bench.py's cluster sections are uniform-random RPS loops — nothing like
millions of users.  This package drives a spawned cluster with the
shapes production actually has — Zipfian object popularity over a hot
set, a mixed size distribution, delete churn, and MID-LOAD fault
injection from the W701-checked FAULT_POINTS registry — with the
graceful-degradation plane (deadlines, retry budgets, admission
control) and the alert engine live, and emits per-route RED
measurements, per-phase p99s, shed/retry/deadline counters, the alert
timeline, a sampled stitched trace, and a degraded VERDICT against the
spec's expectations.

    from seaweedfs_tpu.scenarios import default_scenarios, run_scenario
    for spec in default_scenarios():
        result = run_scenario(spec)

The bench `scenarios` section runs the three canonical specs (Zipfian
hot-set read storm, mixed-size write+churn, failure-under-load) and
stamps each verdict into the bench JSON.

Two more entry points close the loop with the observability stack:

  - replay.spec_from_recording fits a workload recording
    (observability/reqlog.py, `weed shell workload.export`) into a
    replayable spec — recorded production traffic becomes a
    repeatable scenario, open-loop paced at recorded (or -speed
    scaled) rate, with replay_fidelity machine-checking the
    reproduction;
  - capacity.find_capacity / probe_cluster binary-search the max
    sustainable rps per route class under a declared SLO — the bench
    `capacity` section's numbers and the dataplane refactor's
    acceptance baseline.
"""

from .capacity import CapacitySLO, find_capacity, measure_rate
from .engine import run_against, run_scenario
from .failover import run_failover
from .replay import (recording_profile, replay_fidelity,
                     spec_from_recording)
from .spec import (FaultSpec, ScenarioSpec, default_scenarios,
                   failure_under_load, flash_crowd,
                   flash_crowd_autoscale, master_failover,
                   read_storm, write_churn)
from .workload import SizeSampler, ZipfSampler

__all__ = [
    "FaultSpec", "ScenarioSpec", "default_scenarios", "run_scenario",
    "run_against", "run_failover",
    "read_storm", "write_churn", "failure_under_load", "flash_crowd",
    "flash_crowd_autoscale", "master_failover",
    "ZipfSampler", "SizeSampler",
    "spec_from_recording", "recording_profile", "replay_fidelity",
    "CapacitySLO", "find_capacity", "measure_rate",
]
