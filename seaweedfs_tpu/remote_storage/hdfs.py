"""HDFS remote-storage client over the WebHDFS REST API.

Equivalent of weed/remote_storage/hdfs/hdfs_storage_client.go — the
reference links the HDFS protobuf client; this rebuild uses WebHDFS
(`/webhdfs/v1`, enabled by default on namenodes), so any Hadoop cluster
is reachable with zero dependencies.  Supports simple auth
(`user.name=`) — kerberized clusters need a gateway (knox) in front.

Operations: LISTSTATUS (recursive traverse), OPEN (with offset/length),
CREATE (two-step redirect to the datanode, like the protocol requires),
DELETE, MKDIRS.  "Buckets" map to top-level directories under the
configured root path, mirroring the reference's hdfs mapping.

CAVEAT: protocol-validated against the in-process double
(tests/minihdfs.py), which shares this client's reading of the
WebHDFS REST API — no live namenode runs in CI.
"""

from __future__ import annotations

import json
import urllib.parse
from typing import Iterator

from ..utils.httpd import HttpError, http_bytes
from .client import (
    RemoteConf,
    RemoteLocation,
    RemoteObject,
    RemoteStorageClient,
)


class HdfsRemoteStorage(RemoteStorageClient):
    """conf fields: endpoint = namenode host:port (the HTTP/9870 port),
    root = base path (default "/"), access_key = user.name for simple
    auth (optional)."""

    def __init__(self, conf: RemoteConf):
        self.endpoint = conf.endpoint
        self.root = (conf.root or "/").rstrip("/")
        self.user = conf.access_key

    def _url(self, path: str, op: str, **params) -> str:
        q = {"op": op, **params}
        if self.user:
            q["user.name"] = self.user
        full = f"{self.root}/{path.lstrip('/')}".rstrip("/") or "/"
        return (f"http://{self.endpoint}/webhdfs/v1"
                f"{urllib.parse.quote(full)}?{urllib.parse.urlencode(q)}")

    @staticmethod
    def _check(status: int, body: bytes, ok=(200, 201)) -> dict:
        if status not in ok:
            raise HttpError(status, body.decode(errors="replace"))
        return json.loads(body) if body else {}

    # -- RemoteStorageClient ------------------------------------------------
    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        base = f"{loc.bucket}/{loc.path.lstrip('/')}".rstrip("/")

        def walk(rel: str) -> Iterator[RemoteObject]:
            status, body, _ = http_bytes(
                "GET", self._url(rel, "LISTSTATUS"), timeout=60.0)
            if status == 404:
                return
            doc = self._check(status, body)
            for st in doc.get("FileStatuses", {}).get("FileStatus", []):
                name = st.get("pathSuffix", "")
                child = f"{rel}/{name}" if name else rel
                if st.get("type") == "DIRECTORY":
                    yield from walk(child)
                else:
                    # key is bucket-relative, like the other backends
                    key = "/" + child.split("/", 1)[1] if "/" in child else \
                        "/" + child
                    yield RemoteObject(
                        key, int(st.get("length", 0)),
                        st.get("modificationTime", 0) / 1000.0,
                        str(st.get("modificationTime", "")))

        yield from walk(base)

    def read_file(self, loc: RemoteLocation, key: str,
                  offset: int = 0, size: int = -1) -> bytes:
        if size == 0:
            return b""
        params = {}
        if offset:
            params["offset"] = offset
        if size > 0:
            params["length"] = size
        status, body, _ = http_bytes(
            "GET", self._url(f"{loc.bucket}/{key.lstrip('/')}",
                             "OPEN", **params), timeout=60.0)
        if status not in (200,):
            raise HttpError(status, body.decode(errors="replace"))
        return body

    def write_file(self, loc: RemoteLocation, key: str,
                   data: bytes) -> RemoteObject:
        import time

        # two-step CREATE: the namenode 307-redirects to a datanode URL
        url = self._url(f"{loc.bucket}/{key.lstrip('/')}", "CREATE",
                        overwrite="true")
        status, body, hdrs = http_bytes("PUT", url, follow_redirects=False,
            timeout=60.0)
        if status == 307:
            url = hdrs.get("Location", url)
            status, body, _ = http_bytes("PUT", url, data, timeout=60.0)
        elif status in (200, 201):
            # single-step servers (gateways) accept the body directly
            status, body, _ = http_bytes("PUT", url, data, timeout=60.0)
        self._check(status, body, ok=(200, 201))
        return RemoteObject(key, len(data), time.time())

    def delete_file(self, loc: RemoteLocation, key: str) -> None:
        status, body, _ = http_bytes(
            "DELETE", self._url(f"{loc.bucket}/{key.lstrip('/')}",
                                "DELETE"), timeout=60.0)
        if status not in (200, 404):
            raise HttpError(status, body.decode(errors="replace"))

    def list_buckets(self) -> list[str]:
        status, body, _ = http_bytes("GET", self._url("", "LISTSTATUS"),
            timeout=60.0)
        doc = self._check(status, body)
        return sorted(
            st.get("pathSuffix", "")
            for st in doc.get("FileStatuses", {}).get("FileStatus", [])
            if st.get("type") == "DIRECTORY")

    def create_bucket(self, bucket: str) -> None:
        status, body, _ = http_bytes(
            "PUT", self._url(bucket, "MKDIRS"), timeout=60.0)
        self._check(status, body)

    def delete_bucket(self, bucket: str) -> None:
        status, body, _ = http_bytes(
            "DELETE", self._url(bucket, "DELETE", recursive="true"),
                timeout=60.0)
        if status not in (200, 404):
            raise HttpError(status, body.decode(errors="replace"))
