"""Mount bookkeeping + remote object caching.

Equivalent of filer/remote_storage.go (mappings read from in-FS config)
and filer/read_remote.go CacheRemoteObjectToLocalCluster: remote confs
live at /etc/remote.conf, mount mappings at /etc/remote.mount — both
ordinary filer files, so every filer/gateway sees the same view.
"""

from __future__ import annotations

import json
import time
import urllib.parse
from typing import Optional

from ..utils.httpd import HttpError, http_bytes
from ..utils.jsonconf import read_json_conf as _read_json
from ..utils.jsonconf import write_json_conf as _write_json
from .client import (RemoteConf, RemoteLocation, RemoteObject,
                     RemoteStorageClient, make_client)

REMOTE_CONF_PATH = "/etc/remote.conf"
MOUNTS_PATH = "/etc/remote.mount"


def read_remote_conf(filer_url: str) -> dict[str, RemoteConf]:
    d = _read_json(filer_url, REMOTE_CONF_PATH, {})
    return {name: RemoteConf.from_dict(c) for name, c in d.items()}


def write_remote_conf(filer_url: str, confs: dict[str, RemoteConf]) -> None:
    _write_json(filer_url, REMOTE_CONF_PATH,
                {n: c.to_dict() for n, c in confs.items()})


class RemoteMounts:
    """dir -> RemoteLocation mapping (filer/remote_storage.go)."""

    def __init__(self, mounts: dict[str, RemoteLocation]):
        self.mounts = mounts

    @classmethod
    def read(cls, filer_url: str) -> "RemoteMounts":
        d = _read_json(filer_url, MOUNTS_PATH, {})
        return cls({p: RemoteLocation.from_dict(l) for p, l in d.items()})

    def write(self, filer_url: str) -> None:
        _write_json(filer_url, MOUNTS_PATH,
                    {p: l.to_dict() for p, l in self.mounts.items()})

    def mount_of(self, path: str) -> Optional[tuple[str, RemoteLocation]]:
        """Longest mount-dir prefix covering path."""
        best = None
        for d, loc in self.mounts.items():
            if path == d or path.startswith(d.rstrip("/") + "/"):
                if best is None or len(d) > len(best[0]):
                    best = (d, loc)
        return best


def read_mounts(filer_url: str) -> RemoteMounts:
    return RemoteMounts.read(filer_url)


def remote_key_for(mount_dir: str, loc: RemoteLocation, path: str) -> str:
    rel = path[len(mount_dir.rstrip("/")):]
    return loc.child(rel)


def sync_metadata(filer_url: str, mount_dir: str, loc: RemoteLocation,
                  client: RemoteStorageClient) -> int:
    """remote.meta.sync: import the remote listing as chunkless entries
    carrying RemoteEntry metadata (filer/remote_storage.go pull)."""
    count = 0
    base_key = loc.path.rstrip("/")
    for obj in client.traverse(loc):
        rel = obj.key[len(base_key):] if base_key and \
            obj.key.startswith(base_key) else obj.key
        fpath = mount_dir.rstrip("/") + "/" + rel.lstrip("/")
        stamp = obj.to_extended()["remote.entry"]
        status, body, _ = http_bytes(
            "GET", f"http://{filer_url}/api/stat"
            + urllib.parse.quote(fpath), timeout=60.0)
        if status == 200:
            existing = json.loads(body)
            marker = existing.get("extended", {}).get("remote.entry")
            if marker == stamp:
                continue  # unchanged on the remote
            if marker is None and existing.get("chunks"):
                # locally-created file not yet pushed to the remote:
                # never destroy it with chunkless remote metadata
                continue
        entry = {
            "full_path": fpath,
            "attr": {"mtime": obj.mtime, "crtime": obj.mtime,
                     "mode": 0o644, "mime": ""},
            "chunks": [],
            "extended": obj.to_extended(),
        }
        status, body, _ = http_bytes(
            "POST", f"http://{filer_url}/api/entry",
            json.dumps(entry).encode(),
            headers={"Content-Type": "application/json"}, timeout=60.0)
        if status not in (200, 201):
            raise HttpError(status, body.decode(errors="replace"))
        count += 1
    return count


def cache_remote_object(filer_server, entry) -> bytes:
    """CacheRemoteObjectToLocalCluster (filer/read_remote.go): fetch the
    object from its remote, write it as local chunks, update the entry.
    Returns the content."""
    meta = json.loads(entry.extended["remote.entry"])
    mounts = RemoteMounts.read(filer_server.url)
    hit = mounts.mount_of(entry.full_path)
    if hit is None:
        raise HttpError(404, f"{entry.full_path}: no remote mount")
    mount_dir, loc = hit
    confs = read_remote_conf(filer_server.url)
    conf = confs.get(loc.conf_name)
    if conf is None:
        raise HttpError(500, f"remote conf {loc.conf_name!r} missing")
    client = make_client(conf)
    data = client.read_file(loc, meta["key"])
    # persist as local chunks so subsequent reads are cluster-local
    chunks = filer_server.write_chunks(data)
    from ..filer.entry import Entry

    cached = Entry(full_path=entry.full_path, attr=entry.attr,
                   chunks=chunks, extended=dict(entry.extended))
    filer_server.filer.create_entry(cached)
    return data


def uncache_entry(filer_server, entry) -> None:
    """remote.uncache: drop local chunks, keep the remote metadata."""
    from ..filer.entry import Entry

    if not entry.chunks or "remote.entry" not in entry.extended:
        return
    bare = Entry(full_path=entry.full_path, attr=entry.attr, chunks=[],
                 extended=dict(entry.extended))
    filer_server.filer.create_entry(bare)
