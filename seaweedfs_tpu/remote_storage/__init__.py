"""Remote storage: mount cloud buckets as filer directories.

Equivalent of weed/remote_storage/ (per-vendor clients), pb/remote.proto
(RemoteConf / RemoteStorageLocation / RemoteEntry), filer/read_remote.go
(CacheRemoteObjectToLocalCluster) and the remote.* shell family.

Vendors: "local" (a directory posing as a bucket — the offline dev/test
backend), "s3" (any S3-compatible endpoint over HTTP, including this
framework's own gateway); gcs/azure/hdfs are SDK-gated stubs.
"""

from .client import (LocalRemoteStorage, RemoteConf, RemoteLocation,
                     RemoteStorageClient, S3RemoteStorage, make_client)
from .mounts import (MOUNTS_PATH, REMOTE_CONF_PATH, RemoteMounts,
                     cache_remote_object, read_mounts, read_remote_conf)

__all__ = [
    "RemoteStorageClient", "RemoteConf", "RemoteLocation",
    "LocalRemoteStorage", "S3RemoteStorage", "make_client",
    "RemoteMounts", "read_mounts", "read_remote_conf",
    "cache_remote_object", "MOUNTS_PATH", "REMOTE_CONF_PATH",
]
