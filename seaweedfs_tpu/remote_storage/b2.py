"""Backblaze B2 remote storage over the NATIVE b2api/v2 REST protocol.

SDK-free like every other remote family here (the reference's b2 client
rides gitlab.com/kurin/blazer, ref: weed/replication/sink/b2sink/
b2_sink.go + weed/remote_storage) — this client speaks the documented
wire protocol directly: b2_authorize_account (Basic auth), bucket CRUD,
b2_list_file_names paging, the get-upload-url/upload two-step with
X-Bz-Content-Sha1, ranged downloads and delete-by-file-version.
Auth tokens refresh transparently on 401 (they expire server-side).
CAVEAT: protocol-validated against the in-process double
(tests/minib2.py), which shares this client's reading of the
b2api/v2 docs — no live B2 account in CI.
"""

from __future__ import annotations

import hashlib
import json
import threading
import urllib.parse
from base64 import b64encode
from typing import Iterator, Optional

from ..utils.httpd import http_bytes
from .client import (
    RemoteConf,
    RemoteLocation,
    RemoteObject,
    RemoteStorageClient,
)

B2_API_BASE = "https://api.backblazeb2.com"


class B2RemoteStorage(RemoteStorageClient):
    """conf: access_key = application key id, secret_key = application
    key; extra["endpoint"]/conf.endpoint overrides the auth host (tests
    point it at the in-process double)."""

    def __init__(self, conf: RemoteConf):
        self.key_id = conf.access_key
        self.app_key = conf.secret_key
        self.auth_base = conf.endpoint or B2_API_BASE
        self._lock = threading.Lock()
        self._auth: Optional[dict] = None
        self._bucket_ids: dict[str, str] = {}

    # --- auth + plumbing --------------------------------------------------
    def _authorize(self) -> dict:
        basic = b64encode(f"{self.key_id}:{self.app_key}".encode()).decode()
        status, body, _ = http_bytes(
            "GET", f"{self.auth_base}/b2api/v2/b2_authorize_account",
            headers={"Authorization": f"Basic {basic}"}, timeout=60.0)
        if status != 200:
            raise PermissionError(f"b2 authorize failed: {status} "
                                  f"{body[:200].decode(errors='replace')}")
        return json.loads(body)

    def _auth_state(self, refresh: bool = False) -> dict:
        # the authorize round trip runs OUTSIDE the lock (weedlint W504:
        # holding _lock across B2 egress would stall every concurrent
        # caller behind one slow auth); two racing refreshes both hit
        # b2_authorize_account, which is idempotent — last writer wins
        # and both tokens are valid
        with self._lock:
            auth = self._auth
            if auth is not None and not refresh:
                return auth
        auth = self._authorize()
        with self._lock:
            self._auth = auth
            self._bucket_ids.clear()
        return auth

    def _call(self, op: str, payload: dict) -> dict:
        """POST an api operation; one token refresh on 401."""
        for attempt in range(2):
            auth = self._auth_state(refresh=attempt > 0)
            status, body, _ = http_bytes(
                "POST", f"{auth['apiUrl']}/b2api/v2/{op}",
                json.dumps(payload).encode(),
                headers={"Authorization": auth["authorizationToken"]},
                    timeout=60.0)
            if status == 401 and attempt == 0:
                continue
            if status != 200:
                raise OSError(f"b2 {op}: {status} "
                              f"{body[:200].decode(errors='replace')}")
            return json.loads(body)
        raise OSError(f"b2 {op}: unauthorized after refresh")

    def _bucket_id(self, bucket: str) -> str:
        with self._lock:
            cached = self._bucket_ids.get(bucket)
        if cached:
            return cached
        auth = self._auth_state()
        out = self._call("b2_list_buckets",
                         {"accountId": auth["accountId"]})
        with self._lock:
            for b in out.get("buckets", []):
                self._bucket_ids[b["bucketName"]] = b["bucketId"]
            got = self._bucket_ids.get(bucket)
        if not got:
            raise FileNotFoundError(f"b2 bucket {bucket!r} not found")
        return got

    # --- RemoteStorageClient ----------------------------------------------
    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        prefix = loc.path.strip("/")
        prefix = prefix + "/" if prefix else ""
        start = None
        while True:
            payload = {"bucketId": self._bucket_id(loc.bucket),
                       "maxFileCount": 1000, "prefix": prefix}
            if start:
                payload["startFileName"] = start
            out = self._call("b2_list_file_names", payload)
            for f in out.get("files", []):
                yield RemoteObject(
                    key="/" + f["fileName"],
                    size=int(f["contentLength"]),
                    mtime=int(f.get("uploadTimestamp", 0)) / 1000.0,
                    etag=f.get("contentSha1", ""))
            start = out.get("nextFileName")
            if not start:
                return

    def read_file(self, loc: RemoteLocation, key: str,
                  offset: int = 0, size: int = -1) -> bytes:
        auth = self._auth_state()
        name = urllib.parse.quote(key.lstrip("/"))
        headers = {"Authorization": auth["authorizationToken"]}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        status, body, _ = http_bytes(
            "GET", f"{auth['downloadUrl']}/file/{loc.bucket}/{name}",
            headers=headers, timeout=60.0)
        if status not in (200, 206):
            raise FileNotFoundError(f"b2 read {key}: {status}")
        return body

    def write_file(self, loc: RemoteLocation, key: str,
                   data: bytes) -> RemoteObject:
        up = self._call("b2_get_upload_url",
                        {"bucketId": self._bucket_id(loc.bucket)})
        sha1 = hashlib.sha1(data).hexdigest()
        status, body, _ = http_bytes(
            "POST", up["uploadUrl"], data,
            headers={
                "Authorization": up["authorizationToken"],
                "X-Bz-File-Name": urllib.parse.quote(key.lstrip("/")),
                "Content-Type": "b2/x-auto",
                "X-Bz-Content-Sha1": sha1,
            }, timeout=60.0)
        if status != 200:
            raise OSError(f"b2 upload {key}: {status} "
                          f"{body[:200].decode(errors='replace')}")
        doc = json.loads(body)
        return RemoteObject(key="/" + doc["fileName"],
                            size=int(doc["contentLength"]),
                            mtime=int(doc.get("uploadTimestamp", 0)) / 1000.0,
                            etag=doc.get("contentSha1", sha1))

    def delete_file(self, loc: RemoteLocation, key: str) -> None:
        name = key.lstrip("/")
        payload = {"bucketId": self._bucket_id(loc.bucket),
                   "startFileName": name, "maxFileCount": 1,
                   "prefix": name}
        out = self._call("b2_list_file_names", payload)
        for f in out.get("files", []):
            if f["fileName"] == name:
                self._call("b2_delete_file_version",
                           {"fileName": name, "fileId": f["fileId"]})
                return
        # absent already: delete is idempotent

    def list_buckets(self) -> list[str]:
        auth = self._auth_state()
        out = self._call("b2_list_buckets", {"accountId": auth["accountId"]})
        return sorted(b["bucketName"] for b in out.get("buckets", []))

    def create_bucket(self, bucket: str) -> None:
        auth = self._auth_state()
        self._call("b2_create_bucket",
                   {"accountId": auth["accountId"], "bucketName": bucket,
                    "bucketType": "allPrivate"})
        with self._lock:
            self._bucket_ids.clear()

    def delete_bucket(self, bucket: str) -> None:
        self._call("b2_delete_bucket",
                   {"accountId": self._auth_state()["accountId"],
                    "bucketId": self._bucket_id(bucket)})
        with self._lock:
            self._bucket_ids.clear()
