"""Azure Blob Storage remote-storage client over the REST API.

Equivalent of weed/remote_storage/azure/azure_storage_client.go — the
reference uses the Azure SDK; this rebuild speaks the Blob service REST
API directly (SharedKey authorization, x-ms-version 2020-10-02) so any
Azure account or azurite/compatible emulator works with zero SDK
dependencies.

Operations used: List Containers, Create/Delete Container, List Blobs
(flat, marker paging), Put Blob (BlockBlob), Get Blob (with Range),
Delete Blob.  SharedKey signing follows the documented canonicalization:
HMAC-SHA256 of the verb + standard headers + canonicalized x-ms-*
headers + canonicalized resource, keyed by the base64-decoded account
key.

CAVEAT: protocol-validated against the in-process double
(tests/miniazure.py), which shares this client's reading of the
Blob REST + SharedKey signing docs — no live account in CI.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import urllib.parse
import xml.etree.ElementTree as ET
from email.utils import formatdate, parsedate_to_datetime
from typing import Iterator

from ..utils.httpd import HttpError, http_bytes
from .client import (
    RemoteConf,
    RemoteLocation,
    RemoteObject,
    RemoteStorageClient,
)

API_VERSION = "2020-10-02"


class AzureRemoteStorage(RemoteStorageClient):
    """conf fields: access_key = account name, secret_key = base64
    account key, endpoint = host[:port] (defaults to
    ``{account}.blob.core.windows.net``; set it for azurite-style
    emulators, where the account name becomes the first path segment)."""

    def __init__(self, conf: RemoteConf):
        self.account = conf.access_key
        self.key = base64.b64decode(conf.secret_key) if conf.secret_key \
            else b""
        ep = conf.endpoint
        if ep and "://" in ep:
            self.scheme, ep = ep.split("://", 1)
        else:
            # real service: always https (accounts default to
            # secure-transfer-required); explicit host:port endpoints
            # (emulators) default to http
            self.scheme = "http" if ep else "https"
        self.endpoint = ep or f"{self.account}.blob.core.windows.net"
        # emulator convention: custom endpoint paths are /{account}/...
        self.path_style = bool(ep)

    # -- signing ------------------------------------------------------------
    def _canonical_resource(self, path: str, query: dict) -> str:
        # canonicalized resource = "/" + account + URI path.  With a
        # custom (emulator) endpoint the URI path itself starts with
        # /{account}, so the account appears TWICE — matching azurite's
        # documented canonicalization.
        uri_path = f"/{self.account}{path}" if self.path_style else path
        res = f"/{self.account}{uri_path}"
        for k in sorted(query):
            res += f"\n{k.lower()}:{query[k]}"
        return res

    def _request(self, method: str, path: str, query: dict | None = None,
                 body: bytes = b"", headers: dict | None = None):
        query = query or {}
        headers = dict(headers or {})
        headers["x-ms-date"] = formatdate(usegmt=True)
        headers["x-ms-version"] = API_VERSION
        if method == "PUT" and "x-ms-blob-type" not in headers and body:
            headers["x-ms-blob-type"] = "BlockBlob"
        canon_headers = "".join(
            f"{k}:{v}\n" for k, v in sorted(
                (k.lower(), v) for k, v in headers.items()
                if k.lower().startswith("x-ms-")))
        length = str(len(body)) if body else ""
        string_to_sign = "\n".join([
            method,
            "",                      # Content-Encoding
            "",                      # Content-Language
            length,                  # Content-Length ("" when 0)
            "",                      # Content-MD5
            headers.get("Content-Type", ""),
            "",                      # Date (x-ms-date is used instead)
            "",                      # If-Modified-Since
            "",                      # If-Match
            "",                      # If-None-Match
            "",                      # If-Unmodified-Since
            headers.get("Range", ""),
        ]) + "\n" + canon_headers + self._canonical_resource(path, query)
        if self.key:
            sig = base64.b64encode(hmac.new(
                self.key, string_to_sign.encode(), hashlib.sha256).digest())
            headers["Authorization"] = \
                f"SharedKey {self.account}:{sig.decode()}"
        url_path = (f"/{self.account}{path}" if self.path_style else path)
        q = urllib.parse.urlencode(sorted(query.items()))
        url = (f"{self.scheme}://{self.endpoint}"
               f"{urllib.parse.quote(url_path)}") + (
            f"?{q}" if q else "")
        return http_bytes(method, url, body or None, headers=headers,
            timeout=60.0)

    # -- helpers ------------------------------------------------------------
    @staticmethod
    def _blob_path(loc: RemoteLocation, key: str) -> str:
        return f"/{loc.bucket}/{key.lstrip('/')}"

    # -- RemoteStorageClient ------------------------------------------------
    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        marker = ""
        prefix = loc.path.strip("/")
        while True:
            query = {"restype": "container", "comp": "list"}
            if prefix:
                query["prefix"] = prefix + "/"
            if marker:
                query["marker"] = marker
            status, body, _ = self._request(
                "GET", f"/{loc.bucket}", query)
            if status != 200:
                raise HttpError(status, body.decode(errors="replace"))
            root = ET.fromstring(body)
            for blob in root.iter("Blob"):
                name = blob.findtext("Name", "")
                props = blob.find("Properties")
                size = int(props.findtext("Content-Length", "0")) \
                    if props is not None else 0
                mtime_s = props.findtext("Last-Modified", "") \
                    if props is not None else ""
                try:
                    mtime = parsedate_to_datetime(mtime_s).timestamp()
                except (TypeError, ValueError):
                    mtime = 0.0
                etag = (props.findtext("Etag", "")
                        if props is not None else "").strip('"')
                yield RemoteObject("/" + name, size, mtime, etag)
            marker = root.findtext("NextMarker", "") or ""
            if not marker:
                return

    def read_file(self, loc: RemoteLocation, key: str,
                  offset: int = 0, size: int = -1) -> bytes:
        if size == 0:
            return b""  # an inverted Range header would draw a 416
        headers = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        status, body, _ = self._request(
            "GET", self._blob_path(loc, key), headers=headers)
        if status not in (200, 206):
            raise HttpError(status, body.decode(errors="replace"))
        return body

    def write_file(self, loc: RemoteLocation, key: str,
                   data: bytes) -> RemoteObject:
        import time

        status, body, _ = self._request(
            "PUT", self._blob_path(loc, key), body=data,
            headers={"x-ms-blob-type": "BlockBlob"})
        if status not in (200, 201):
            raise HttpError(status, body.decode(errors="replace"))
        return RemoteObject(key, len(data), time.time(),
                            hashlib.md5(data).hexdigest())

    def delete_file(self, loc: RemoteLocation, key: str) -> None:
        status, body, _ = self._request(
            "DELETE", self._blob_path(loc, key))
        if status not in (202, 404):
            raise HttpError(status, body.decode(errors="replace"))

    def list_buckets(self) -> list[str]:
        status, body, _ = self._request("GET", "/", {"comp": "list"})
        if status != 200:
            raise HttpError(status, body.decode(errors="replace"))
        root = ET.fromstring(body)
        return sorted(c.findtext("Name", "")
                      for c in root.iter("Container"))

    def create_bucket(self, bucket: str) -> None:
        status, body, _ = self._request(
            "PUT", f"/{bucket}", {"restype": "container"})
        if status not in (201, 409):  # 409 = already exists
            raise HttpError(status, body.decode(errors="replace"))

    def delete_bucket(self, bucket: str) -> None:
        status, body, _ = self._request(
            "DELETE", f"/{bucket}", {"restype": "container"})
        if status not in (202, 404):
            raise HttpError(status, body.decode(errors="replace"))
