"""filer.remote.gateway: mirror S3 bucket lifecycle into cloud storage.

Equivalent of weed/command/filer_remote_gateway*.go: tails the filer's
meta log scoped to /buckets and keeps a configured remote storage in
step — a newly created bucket becomes a remote mount (and, where the
backend supports it, a remote bucket); a deleted bucket unmounts (and
optionally deletes remotely); object mutations inside mapped buckets
ride one RemoteSyncer per bucket, exactly the filer.remote.sync engine.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..utils.httpd import http_json
from .client import RemoteLocation, make_client
from .mounts import RemoteMounts, read_remote_conf
from .sync import RemoteSyncer

BUCKETS_DIR = "/buckets"


class RemoteGateway:
    def __init__(self, filer_url: str, remote_conf_name: str,
                 bucket_prefix: str = "",
                 delete_remote_buckets: bool = False,
                 poll_interval: float = 0.5,
                 since_ns: Optional[int] = None):
        self.filer_url = filer_url
        self.conf_name = remote_conf_name
        conf = read_remote_conf(filer_url).get(remote_conf_name)
        if conf is None:
            raise ValueError(f"remote conf {remote_conf_name!r} missing")
        self.client = make_client(conf)
        self.bucket_prefix = bucket_prefix
        self.delete_remote_buckets = delete_remote_buckets
        self.poll_interval = poll_interval
        self.since_ns = time.time_ns() if since_ns is None else since_ns
        self.mapped = 0
        self.unmapped = 0
        self._syncers: dict[str, RemoteSyncer] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # buckets mounted before the gateway started keep syncing
        for d, loc in RemoteMounts.read(filer_url).mounts.items():
            if d.startswith(BUCKETS_DIR + "/") and \
                    loc.conf_name == remote_conf_name:
                self._start_syncer(d)

    # --- bucket lifecycle -------------------------------------------------
    def _remote_bucket(self, name: str) -> str:
        return f"{self.bucket_prefix}{name}" if self.bucket_prefix else name

    def _map_bucket(self, name: str) -> None:
        mount_dir = f"{BUCKETS_DIR}/{name}"
        mounts = RemoteMounts.read(self.filer_url)
        if mount_dir in mounts.mounts:
            return
        remote = self._remote_bucket(name)
        try:
            self.client.create_bucket(remote)
        except (AttributeError, NotImplementedError):
            pass  # backend without bucket semantics: prefix-only mapping
        mounts.mounts[mount_dir] = RemoteLocation(
            conf_name=self.conf_name, bucket=remote, path="/")
        mounts.write(self.filer_url)
        self._start_syncer(mount_dir)
        self.mapped += 1

    def _unmap_bucket(self, name: str) -> None:
        mount_dir = f"{BUCKETS_DIR}/{name}"
        syncer = self._syncers.pop(mount_dir, None)
        if syncer is not None:
            syncer.stop()
        mounts = RemoteMounts.read(self.filer_url)
        loc = mounts.mounts.pop(mount_dir, None)
        if loc is not None:
            mounts.write(self.filer_url)
            if self.delete_remote_buckets:
                try:
                    self.client.delete_bucket(loc.bucket)
                except (AttributeError, NotImplementedError):
                    pass
        self.unmapped += 1

    def _start_syncer(self, mount_dir: str) -> None:
        try:
            self._syncers[mount_dir] = RemoteSyncer(
                self.filer_url, mount_dir,
                poll_interval=self.poll_interval).start()
        except ValueError:
            pass  # mount raced away

    # --- event loop --------------------------------------------------------
    def poll_once(self) -> int:
        r = http_json(
            "GET", f"http://{self.filer_url}/api/meta/log"
                   f"?since_ns={self.since_ns}&path_prefix={BUCKETS_DIR}",
                       timeout=30.0)
        n = 0
        for event in r.get("events", []):
            entry = event.get("new_entry") or event.get("old_entry") or {}
            path = entry.get("full_path", "")
            # bucket-level events only: /buckets/<name> exactly
            if not path.startswith(BUCKETS_DIR + "/"):
                continue
            name = path[len(BUCKETS_DIR) + 1:]
            if "/" in name or not name:
                continue
            if event["op"] == "create" and event.get("new_entry"):
                self._map_bucket(name)
                n += 1
            elif event["op"] == "delete" and not event.get("new_entry"):
                self._unmap_bucket(name)
                n += 1
        self.since_ns = int(r.get("next_ns", self.since_ns))
        return n

    def run_until_caught_up(self, timeout: float = 30.0) -> int:
        deadline = time.time() + timeout
        total = 0
        while time.time() < deadline:
            n = self.poll_once()
            total += n
            if n == 0:
                return total
        return total

    def start(self) -> "RemoteGateway":
        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:
                    pass
                self._stop.wait(self.poll_interval)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="remote-gateway")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for syncer in self._syncers.values():
            syncer.stop()
