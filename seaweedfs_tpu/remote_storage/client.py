"""Remote storage clients (weed/remote_storage/*/).

Interface mirrors remote_storage.RemoteStorageClient: Traverse,
ReadFile, WriteFile, DeleteFile, write/remove directory are no-ops for
object stores.
"""

from __future__ import annotations

import os
import urllib.parse
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from ..utils.httpd import HttpError, http_bytes


@dataclass
class RemoteConf:
    """pb/remote.proto RemoteConf: named credentials + vendor type."""
    name: str
    type: str = "local"
    # vendor-specific settings
    root: str = ""                # local: the directory posing as cloud
    endpoint: str = ""            # s3
    access_key: str = ""
    secret_key: str = ""
    extra: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"name": self.name, "type": self.type, "root": self.root,
                "endpoint": self.endpoint, "access_key": self.access_key,
                "secret_key": self.secret_key, "extra": self.extra}

    @classmethod
    def from_dict(cls, d: dict) -> "RemoteConf":
        return cls(**{k: d.get(k, "") for k in
                      ("name", "type", "root", "endpoint", "access_key",
                       "secret_key")} | {"extra": d.get("extra", {})})


@dataclass
class RemoteLocation:
    """pb/remote.proto RemoteStorageLocation: conf name + bucket + path."""
    conf_name: str
    bucket: str = ""
    path: str = "/"

    def to_dict(self) -> dict:
        return {"conf_name": self.conf_name, "bucket": self.bucket,
                "path": self.path}

    @classmethod
    def from_dict(cls, d: dict) -> "RemoteLocation":
        return cls(d["conf_name"], d.get("bucket", ""),
                   d.get("path", "/") or "/")

    def child(self, rel: str) -> str:
        """Remote key for a path relative to the mount."""
        base = self.path.rstrip("/")
        return f"{base}/{rel.lstrip('/')}" if rel.strip("/") else base or "/"


@dataclass
class RemoteObject:
    """RemoteEntry essentials: what the filer stores about one object."""
    key: str            # path within the bucket
    size: int
    mtime: float
    etag: str = ""

    def to_extended(self) -> dict:
        import json

        return {"remote.entry": json.dumps(
            {"key": self.key, "size": self.size, "mtime": self.mtime,
             "etag": self.etag})}


class RemoteStorageClient:
    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        raise NotImplementedError

    def read_file(self, loc: RemoteLocation, key: str,
                  offset: int = 0, size: int = -1) -> bytes:
        raise NotImplementedError

    def write_file(self, loc: RemoteLocation, key: str,
                   data: bytes) -> RemoteObject:
        raise NotImplementedError

    def delete_file(self, loc: RemoteLocation, key: str) -> None:
        raise NotImplementedError

    def list_buckets(self) -> list[str]:
        raise NotImplementedError

    def create_bucket(self, bucket: str) -> None:
        """Optional: backends without bucket semantics may leave this
        unimplemented (filer.remote.gateway maps by prefix then)."""
        raise NotImplementedError

    def delete_bucket(self, bucket: str) -> None:
        raise NotImplementedError


class LocalRemoteStorage(RemoteStorageClient):
    """remote_storage for a plain directory — 'bucket' = subdirectory."""

    def __init__(self, conf: RemoteConf):
        self.root = conf.root
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, loc: RemoteLocation, key: str) -> str:
        path = os.path.normpath(
            os.path.join(self.root, loc.bucket, key.lstrip("/")))
        if not (path + "/").startswith(os.path.normpath(self.root) + "/"):
            raise ValueError(f"path escape: {key!r}")
        return path

    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        base = self._abs(loc, loc.path)
        if not os.path.isdir(base):
            return
        for dirpath, _, files in os.walk(base):
            for f in sorted(files):
                p = os.path.join(dirpath, f)
                rel = os.path.relpath(
                    p, os.path.join(self.root, loc.bucket))
                st = os.stat(p)
                yield RemoteObject("/" + rel.replace(os.sep, "/"),
                                  st.st_size, st.st_mtime)

    def read_file(self, loc: RemoteLocation, key: str,
                  offset: int = 0, size: int = -1) -> bytes:
        with open(self._abs(loc, key), "rb") as f:
            f.seek(offset)
            return f.read() if size < 0 else f.read(size)

    def write_file(self, loc: RemoteLocation, key: str,
                   data: bytes) -> RemoteObject:
        path = self._abs(loc, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)
        st = os.stat(path)
        return RemoteObject(key, st.st_size, st.st_mtime)

    def delete_file(self, loc: RemoteLocation, key: str) -> None:
        try:
            os.remove(self._abs(loc, key))
        except FileNotFoundError:
            pass

    def list_buckets(self) -> list[str]:
        return sorted(d for d in os.listdir(self.root)
                      if os.path.isdir(os.path.join(self.root, d)))

    def create_bucket(self, bucket: str) -> None:
        os.makedirs(os.path.join(self.root, bucket), exist_ok=True)

    def delete_bucket(self, bucket: str) -> None:
        import shutil

        path = os.path.join(self.root, bucket)
        if os.path.isdir(path):
            shutil.rmtree(path)


class S3RemoteStorage(RemoteStorageClient):
    """S3-compatible endpoint over plain HTTP (+SigV4 when keyed) —
    works against this framework's own gateway or any other."""

    def __init__(self, conf: RemoteConf):
        self.endpoint = conf.endpoint
        self.access_key, self.secret_key = conf.access_key, conf.secret_key

    def _url(self, loc: RemoteLocation, key: str = "",
             query: str = "") -> str:
        u = f"http://{self.endpoint}/{loc.bucket}"
        if key:
            u += "/" + urllib.parse.quote(key.lstrip("/"))
        if query:
            u += "?" + query
        return u

    def _signed(self, method: str, url: str) -> str:
        if not self.access_key:
            return url
        from ..gateway.s3_auth import presign_v4

        return presign_v4(method, url, self.access_key, self.secret_key)

    def traverse(self, loc: RemoteLocation) -> Iterator[RemoteObject]:
        import xml.etree.ElementTree as ET

        token = ""
        prefix = loc.path.strip("/")
        while True:
            q = "list-type=2"
            if prefix:
                q += "&prefix=" + urllib.parse.quote(prefix + "/")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token)
            status, body, _ = http_bytes(
                "GET", self._signed("GET", self._url(loc, query=q)),
                    timeout=60.0)
            if status != 200:
                raise HttpError(status, body.decode(errors="replace"))
            ns = {"s3": body.split(b"xmlns=", 1)[1].split(b'"')[1].decode()} \
                if b"xmlns=" in body else {}
            root = ET.fromstring(body)

            def find_all(tag):
                return root.findall(f"s3:{tag}", ns) if ns \
                    else root.findall(tag)

            for item in find_all("Contents"):
                def text(tag, default=""):
                    el = item.find(f"s3:{tag}", ns) if ns else item.find(tag)
                    return el.text if el is not None and el.text else default

                import email.utils

                mtime_s = text("LastModified")
                try:
                    import datetime

                    mtime = datetime.datetime.fromisoformat(
                        mtime_s.replace("Z", "+00:00")).timestamp()
                except ValueError:
                    mtime = 0.0
                yield RemoteObject("/" + text("Key"), int(text("Size", "0")),
                                  mtime, text("ETag").strip('"'))
            tok_el = (root.find("s3:NextContinuationToken", ns) if ns
                      else root.find("NextContinuationToken"))
            if tok_el is None or not tok_el.text:
                return
            token = tok_el.text

    def read_file(self, loc: RemoteLocation, key: str,
                  offset: int = 0, size: int = -1) -> bytes:
        if size == 0:
            return b""  # an inverted Range header would draw a 416
        headers = {}
        if offset or size >= 0:
            end = "" if size < 0 else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        status, body, _ = http_bytes(
            "GET", self._signed("GET", self._url(loc, key)),
            headers=headers or None, timeout=60.0)
        if status not in (200, 206):
            raise HttpError(status, body.decode(errors="replace"))
        return body

    def write_file(self, loc: RemoteLocation, key: str,
                   data: bytes) -> RemoteObject:
        import time

        status, body, _ = http_bytes(
            "PUT", self._signed("PUT", self._url(loc, key)), data,
                timeout=60.0)
        if status not in (200, 201):
            raise HttpError(status, body.decode(errors="replace"))
        return RemoteObject(key, len(data), time.time())

    def delete_file(self, loc: RemoteLocation, key: str) -> None:
        http_bytes("DELETE", self._signed("DELETE", self._url(loc, key)),
            timeout=60.0)

    def list_buckets(self) -> list[str]:
        import xml.etree.ElementTree as ET

        status, body, _ = http_bytes(
            "GET", self._signed("GET", f"http://{self.endpoint}/"),
                timeout=60.0)
        if status != 200:
            raise HttpError(status, body.decode(errors="replace"))
        root = ET.fromstring(body)
        names = [el.text for el in root.iter()
                 if el.tag.endswith("Name") and el.text]
        return sorted(n for n in names if n)

    def create_bucket(self, bucket: str) -> None:
        url = f"http://{self.endpoint}/{bucket}"
        status, body, _ = http_bytes("PUT", self._signed("PUT", url),
            timeout=60.0)
        if status not in (200, 409):  # 409 = already exists
            raise HttpError(status, body.decode(errors="replace"))

    def delete_bucket(self, bucket: str) -> None:
        url = f"http://{self.endpoint}/{bucket}"
        status, body, _ = http_bytes("DELETE", self._signed("DELETE", url),
            timeout=60.0)
        if status not in (204, 404):
            raise HttpError(status, body.decode(errors="replace"))





def make_client(conf: RemoteConf) -> RemoteStorageClient:
    if conf.type == "local":
        return LocalRemoteStorage(conf)
    if conf.type == "s3":
        return S3RemoteStorage(conf)
    if conf.type == "azure":
        from .azure import AzureRemoteStorage

        return AzureRemoteStorage(conf)
    if conf.type == "hdfs":
        from .hdfs import HdfsRemoteStorage

        return HdfsRemoteStorage(conf)
    if conf.type == "b2":
        from .b2 import B2RemoteStorage

        return B2RemoteStorage(conf)
    if conf.type == "gcs":
        # GCS interoperability mode speaks the S3 XML API with HMAC keys
        # — same client, defaulting the host to the interop endpoint
        import dataclasses

        if not conf.endpoint:
            conf = dataclasses.replace(conf,
                                       endpoint="storage.googleapis.com")
        return S3RemoteStorage(conf)
    raise ValueError(f"unknown remote storage type {conf.type!r}")
