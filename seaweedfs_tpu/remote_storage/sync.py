"""filer.remote.sync: push local changes under a remote mount back to
the cloud.

Equivalent of weed/command/filer_remote_sync.go: tails the filer meta
log scoped to the mount directory and applies local mutations to the
remote (uploads on create/update, deletes on delete/rename-out).
Cache/uncache events — where the entry's RemoteEntry metadata is
unchanged — are skipped, so remote.cache does not echo an upload.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from typing import Optional

from ..utils.httpd import HttpError, http_bytes, http_json
from .client import RemoteLocation, make_client
from .mounts import (RemoteMounts, read_remote_conf, remote_key_for)


class RemoteSyncer:
    def __init__(self, filer_url: str, mount_dir: str,
                 since_ns: Optional[int] = None,
                 poll_interval: float = 0.5):
        self.filer_url = filer_url
        self.mount_dir = mount_dir.rstrip("/")
        mounts = RemoteMounts.read(filer_url)
        loc = mounts.mounts.get(self.mount_dir)
        if loc is None:
            raise ValueError(f"{mount_dir} is not a remote mount")
        self.loc = loc
        conf = read_remote_conf(filer_url).get(loc.conf_name)
        if conf is None:
            raise ValueError(f"remote conf {loc.conf_name!r} missing")
        self.client = make_client(conf)
        self.since_ns = time.time_ns() if since_ns is None else since_ns
        self.poll_interval = poll_interval
        self.pushed = 0
        self._stop = threading.Event()
        # remote.entry values we stamped ourselves: the resulting update
        # events must not trigger re-uploads (would loop forever)
        self._stamped: set[tuple[str, str]] = set()

    # --- event application ------------------------------------------------
    def _key_for(self, path: str) -> str:
        return remote_key_for(self.mount_dir, self.loc, path)

    def _in_mount(self, path: str) -> bool:
        return path == self.mount_dir \
            or path.startswith(self.mount_dir + "/")

    @staticmethod
    def _is_cache_event(old: Optional[dict], new: Optional[dict]) -> bool:
        """remote.cache / remote.uncache only toggle local chunks; the
        RemoteEntry metadata stays identical — nothing to push."""
        if not old or not new:
            return False
        if old["full_path"] != new["full_path"]:
            return False  # a rename copies extended; it is NOT a cache op
        o = old.get("extended", {}).get("remote.entry")
        n = new.get("extended", {}).get("remote.entry")
        return o is not None and o == n

    def apply(self, event: dict) -> bool:
        old, new = event.get("old_entry"), event.get("new_entry")
        op = event["op"]
        dirbit = 0o20000000000
        if self._is_cache_event(old, new):
            return False
        if new:
            marker = new.get("extended", {}).get("remote.entry")
            if marker and (new["full_path"], marker) in self._stamped:
                self._stamped.discard((new["full_path"], marker))
                return False  # our own stamp echoing back
        if op in ("create", "update") and new:
            if not self._in_mount(new["full_path"]):
                return False
            if new["attr"]["mode"] & dirbit:
                return False
            # metadata import (meta.sync) creates chunkless entries WITH
            # remote metadata — those came FROM the remote; skip
            if not new.get("chunks") and \
                    "remote.entry" in new.get("extended", {}):
                return False
            data = self._fetch(new["full_path"])
            if data is None:
                return False
            obj = self.client.write_file(
                self.loc, self._key_for(new["full_path"]), data)
            self._stamp(new, obj)
            self.pushed += 1
            return True
        if op == "delete" and old:
            if not self._in_mount(old["full_path"]) \
                    or old["attr"]["mode"] & dirbit:
                return False
            self.client.delete_file(self.loc,
                                    self._key_for(old["full_path"]))
            self.pushed += 1
            return True
        if op == "rename" and old and new:
            applied = False
            if self._in_mount(old["full_path"]) \
                    and not old["attr"]["mode"] & dirbit:
                self.client.delete_file(self.loc,
                                        self._key_for(old["full_path"]))
                applied = True
            if self._in_mount(new["full_path"]) \
                    and not new["attr"]["mode"] & dirbit:
                data = self._fetch(new["full_path"])
                if data is not None:
                    obj = self.client.write_file(
                        self.loc, self._key_for(new["full_path"]), data)
                    self._stamp(new, obj)
                    applied = True
            if applied:
                self.pushed += 1
            return applied
        return False

    def _fetch(self, path: str) -> Optional[bytes]:
        status, body, _ = http_bytes(
            "GET", f"http://{self.filer_url}" + urllib.parse.quote(path),
                timeout=60.0)
        if status == 404:
            return None
        if status != 200:
            raise HttpError(status, body.decode(errors="replace"))
        return body

    def _stamp(self, entry_dict: dict, obj) -> None:
        """Record the new RemoteEntry on the filer entry so subsequent
        syncs recognize it as up to date.  The CURRENT entry is re-read
        and merged — posting the (possibly stale) event snapshot back
        would roll back a newer write and GC its chunks."""
        path = entry_dict["full_path"]
        status, body, _ = http_bytes(
            "GET", f"http://{self.filer_url}/api/stat"
            + urllib.parse.quote(path), timeout=60.0)
        if status != 200:
            return  # entry vanished; nothing to stamp
        current = json.loads(body)
        current.pop("file_size", None)
        current.pop("is_directory", None)
        extended = dict(current.get("extended", {}))
        stamp = obj.to_extended()
        extended.update(stamp)
        current["extended"] = extended
        self._stamped.add((path, stamp["remote.entry"]))
        # update_only: a delete landing between the stat above and this
        # write must NOT be resurrected as a chunkless ghost entry
        http_bytes("POST",
                   f"http://{self.filer_url}/api/entry?update_only=true",
                   json.dumps(current).encode(),
                   headers={"Content-Type": "application/json"}, timeout=60.0)

    # --- loop -------------------------------------------------------------
    def poll_once(self) -> int:
        r = http_json(
            "GET", f"http://{self.filer_url}/api/meta/log?"
            f"since_ns={self.since_ns}&path_prefix="
            + urllib.parse.quote(self.mount_dir), timeout=30.0)
        n = 0
        for ev in r["events"]:
            if self.apply(ev):
                n += 1
        self.since_ns = r["next_ns"]
        return n

    def run_until_caught_up(self, timeout: float = 30.0) -> int:
        total = 0
        deadline = time.time() + timeout
        while time.time() < deadline:
            n = self.poll_once()
            total += n
            if n == 0:
                return total
        return total

    def start(self) -> "RemoteSyncer":
        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:
                    pass
                self._stop.wait(self.poll_interval)

        threading.Thread(target=loop, daemon=True,
                         name=f"remote-sync-{self.mount_dir}").start()
        return self

    def stop(self) -> None:
        self._stop.set()
