"""Server-side select over stored JSON/CSV objects.

Equivalent of weed/query/json/query_json.go + the `Query` RPC
(weed/server/volume_grpc_query.go): the volume server evaluates a
projection + filter against needle contents so only matching rows travel
back to the client.  The query shape mirrors the reference's
QueryRequest.Filter {field, operand, value} and InputSerialization
(JSON documents / JSON lines / CSV with header).
"""

from __future__ import annotations

import csv
import io
import json
from typing import Any, Iterator, Optional

OPERANDS = (">", ">=", "<", "<=", "=", "!=", "prefix", "contains")


def dig(doc: Any, dotted: str) -> Any:
    """Path lookup 'a.b.2.c' through dicts and lists (query_json.go's
    gjson-style access, restricted to plain paths)."""
    node = doc
    for part in dotted.split("."):
        if isinstance(node, dict):
            if part not in node:
                return None
            node = node[part]
        elif isinstance(node, list):
            try:
                node = node[int(part)]
            except (ValueError, IndexError):
                return None
        else:
            return None
    return node


def _coerce_pair(a: Any, b: Any) -> tuple[Any, Any]:
    """Compare numerically when both sides look numeric, else as strings."""
    try:
        return float(a), float(b)
    except (TypeError, ValueError):
        return str(a), str(b)


def match_filter(doc: Any, filt: Optional[dict]) -> bool:
    if not filt:
        return True
    field = filt.get("field", "")
    op = filt.get("operand", "=")
    want = filt.get("value")
    got = dig(doc, field) if field else doc
    if op in ("prefix", "contains"):
        if got is None:
            return False
        s, w = str(got), str(want)
        return s.startswith(w) if op == "prefix" else w in s
    if got is None:
        return op == "!=" and want is not None
    a, b = _coerce_pair(got, want)
    if op == "=":
        return a == b
    if op == "!=":
        return a != b
    try:
        if op == ">":
            return a > b
        if op == ">=":
            return a >= b
        if op == "<":
            return a < b
        if op == "<=":
            return a <= b
    except TypeError:
        return False
    raise ValueError(f"unknown operand {op!r}")


def project(doc: Any, select: Optional[list[str]]) -> Any:
    if not select:
        return doc
    return {path: dig(doc, path) for path in select}


def iter_documents(data: bytes, input_format: str = "json") -> Iterator[Any]:
    """Decode an object's bytes into documents:
    - "json": one document, or a top-level array (one doc per element)
    - "jsonl": one document per line
    - "csv": header row names columns, one dict per data row
    """
    if input_format == "json":
        doc = json.loads(data)
        if isinstance(doc, list):
            yield from doc
        else:
            yield doc
    elif input_format == "jsonl":
        for line in data.splitlines():
            line = line.strip()
            if line:
                yield json.loads(line)
    elif input_format == "csv":
        reader = csv.DictReader(io.StringIO(data.decode()))
        yield from reader
    else:
        raise ValueError(f"unknown input format {input_format!r}")


def execute_query(data: bytes, select: Optional[list[str]] = None,
                  filt: Optional[dict] = None,
                  input_format: str = "json") -> list[Any]:
    """Filter + project one stored object -> matching rows."""
    rows = []
    for doc in iter_documents(data, input_format):
        if match_filter(doc, filt):
            rows.append(project(doc, select))
    return rows
