from .json_query import execute_query, project, match_filter

__all__ = ["execute_query", "project", "match_filter"]
