"""Heat autoscaler: the master's closed loop from heat signal to action.

PR 16 built the SIGNAL (observability/heat.py -> the master's
ClusterHeatJournal with Zipf head tracking and flash_crowd /
heat_shift events) and PR 12 built the ACTUATOR (the rack-aware
planner/executor in ops/coordinator.py); this module connects them so
the cluster absorbs flash crowds and sheds cold data without a human
in the loop:

  hot path    volumes entering the Zipf head — or named outright by a
              flash_crowd event (event-driven wake through the heat
              journal's on_ingest hook, exactly like the EC
              coordinator's journal subscription) — GROW read replicas
              across racks through the shared placement_rank diversity
              pools.  Every replica-add is journaled carrying the
              causing heat alert id and its exemplar trace.  Replicas
              SHRINK back only after a sustained-cold hold-down
              (hysteresis, not instantaneous reversal) and under a
              token-bucket move budget, so a flapping head cannot
              churn the cluster; a per-volume cycle cap backstops the
              hysteresis (the thrash guard the flash-crowd drill
              checks).
  cold path   full volumes cold past a threshold tier their `.dat` to
              a remote BackendStorage with the crash-safe two-phase
              protocol in storage/volume.py: upload + verify
              (size & crc32) leaves the manifest `pending`, the
              tier_committed record rides the RAFT LOG (the durable
              commit point), and only then does the volume server
              delete the local copy — a crash at any step leaves
              either the local file or a committed remote copy, never
              neither.  Reads read-through the remote object; heat
              returning triggers an automatic verified RECALL.

All actuation state (replica targets, added-replica ledger, tier
records, hold-down clocks) replicates through the raft log as the
"autoscale" entry kind, so a master failover mid-actuation RESUMES
in-flight plans on the new leader — a grow whose copy already landed
is closed out against the live topology instead of re-copied (zero
duplicate replica adds, which /admin/volume_copy's 409 double-checks).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from ..utils import deadline as _deadline
from ..utils import faultinject
from .coordinator import ClusterView, NodeView, PlanExecutor, placement_rank

# journal event types that wake the planner immediately (the heat
# on_ingest hook is the primary wake; these catch replayed/shipped
# batches and alert transitions)
_WAKE_EVENT_TYPES = ("flash_crowd", "heat_shift", "alert_fired")
# heat event type -> the journal_event alert rule it fires (the rules
# in observability/alerts.py are named after the event type itself)
_ALERT_FOR_TYPE = {
    "flash_crowd": "flash_crowd",
    "heat_shift": "heat_shift",
}


class HeatAutoscaler:  # weedlint: concurrent-class
    """Master-side heat -> replication/tiering loop.  Reached
    concurrently: its own cycle thread, HTTP router threads
    (status/pause/resume/manual tier), the heat journal's ingest
    thread (on_heat) and whatever thread ships cluster events
    (on_events).  All mutable state rides _lock; the HTTP actuation
    legs run strictly outside it."""

    def __init__(self, topo, server: str = "",
                 heat_fn: Optional[Callable[[], dict]] = None,
                 stale_peers_fn: Optional[Callable[[], list]] = None,
                 is_leader_fn: Optional[Callable[[], bool]] = None,
                 admin_locked_fn: Optional[Callable[[], bool]] = None,
                 interval_s: float = 5.0,
                 grow_share: float = 0.3, max_replicas: int = 3,
                 cold_share: float = 0.05, hold_down_s: float = 30.0,
                 regrow_cooldown_s: float = 30.0,
                 max_cycles_per_volume: int = 2,
                 move_rate: float = 1.0, move_burst: float = 4.0,
                 tier_backend: str = "", tier_after_s: float = 60.0,
                 tier_full_frac: float = 0.85,
                 volume_size_limit: int = 30 * 1000 * 1000 * 1000,
                 actuation_deadline_s: float = 600.0,
                 post_fn: Optional[Callable] = None,
                 replicate_fn: Optional[Callable[[dict], None]] = None):
        self.topo = topo
        self.server = server
        self.heat_fn = heat_fn or (lambda: {})
        self.stale_peers_fn = stale_peers_fn or (lambda: [])
        self.is_leader_fn = is_leader_fn or (lambda: True)
        self.admin_locked_fn = admin_locked_fn or (lambda: False)
        self.interval_s = float(interval_s)
        # hot-path knobs: a volume in the journal's head with at least
        # grow_share of cluster heat (or named by a flash_crowd event)
        # grows toward max_replicas, one replica per cycle
        self.grow_share = float(grow_share)
        self.max_replicas = max(1, int(max_replicas))
        # hysteresis: a grown volume must stay under cold_share for a
        # full hold_down_s before ONE added replica is dropped, and a
        # shrunk volume cannot re-grow inside regrow_cooldown_s
        self.cold_share = float(cold_share)
        self.hold_down_s = float(hold_down_s)
        self.regrow_cooldown_s = float(regrow_cooldown_s)
        self.max_cycles_per_volume = int(max_cycles_per_volume)
        self.move_rate = float(move_rate)
        self.move_burst = float(move_burst)
        # cold-path knobs: tiering stays off until a backend is named
        self.tier_backend = tier_backend or ""
        self.tier_after_s = float(tier_after_s)
        self.tier_full_frac = float(tier_full_frac)
        self.volume_size_limit = int(volume_size_limit)
        # one propagated deadline per actuation (utils/deadline.py) so
        # a wedged volume server can't pin the loop past the budget
        self.actuation_deadline_s = float(actuation_deadline_s)
        self.executor = PlanExecutor(post_fn=post_fn)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # vid -> {"added": [urls], "grown_at", "shrunk_at", "cycles"}
        self._targets: dict[int, dict] = {}  # guarded-by: _lock
        # placement snapshot, refreshed by _volume_map (the cycle
        # thread and manual tier_volume HTTP callers both refresh it)
        self._nodes: dict[str, NodeView] = {}  # guarded-by: _lock
        # vid -> wall time the volume was first seen cold (hold-down)
        self._cold_since: dict[int, float] = {}  # guarded-by: _lock
        # vid -> committed tier record {"server", "backend", "key"}
        self._tiered: dict[int, dict] = {}  # guarded-by: _lock
        # causes: vid -> {"event","type","trace","alert"} + firing set
        self._causes: dict[int, dict] = {}  # guarded-by: _lock
        self._alerts: dict[str, dict] = {}  # guarded-by: _lock
        self.paused = False  # guarded-by: _lock
        self.pause_reason = ""  # guarded-by: _lock
        self.cycles = 0  # guarded-by: _lock
        self.last_cycle_at = 0.0  # guarded-by: _lock
        self.last_error = ""  # guarded-by: _lock
        self.grows_done = 0  # guarded-by: _lock
        self.shrinks_done = 0  # guarded-by: _lock
        self.tiers_done = 0  # guarded-by: _lock
        self.recalls_done = 0  # guarded-by: _lock
        self.failures = 0  # guarded-by: _lock
        self.recent: deque = deque(maxlen=64)  # guarded-by: _lock
        # token-bucket actuation budget (grow/shrink moves)
        self._tokens = float(move_burst)  # guarded-by: _lock
        self._tokens_at = time.monotonic()  # guarded-by: _lock
        # --- replicated actuation records (master HA) ---------------
        # grow/shrink/tier lifecycle records ride the raft log as the
        # "autoscale" entry kind: a leader killed mid-actuation leaves
        # its planned record on a quorum, and resume_replicated() on
        # the NEW leader RESUMES the plan (closing it against the live
        # topology when the actuation already landed) with the original
        # alert/trace cause attribution.
        self.replicate_fn = replicate_fn
        # vid -> latest unfinished record (grow_planned / tier_pending)
        self._replicated: dict[int, dict] = {}  # guarded-by: _lock
        self._replog: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "HeatAutoscaler":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="heat-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    @property
    def enabled(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def pause(self, reason: str = "api") -> None:
        with self._lock:
            self.paused = True
            self.pause_reason = reason

    def resume(self) -> None:
        with self._lock:
            self.paused = False
            self.pause_reason = ""
        self._wake.set()

    # --- event / heat subscription ---------------------------------------
    def on_heat(self, merged: dict) -> None:  # thread-entry
        """Heat-journal on_ingest hook: wake the planner the moment a
        volume crosses the grow threshold — cheap share math, lock-only,
        never HTTP (runs on whatever thread POSTed the heat batch)."""
        vols = merged.get("volumes") or {}
        total = sum(float(v.get("heat") or 0.0) for v in vols.values())
        if total <= 1e-9:
            return
        wake = False
        with self._lock:
            for vid, agg in vols.items():
                share = float(agg.get("heat") or 0.0) / total
                grown = len((self._targets.get(vid) or {}).get(
                    "added") or ())
                if share >= self.grow_share and \
                        (1 + grown < self.max_replicas
                         or vid in self._tiered):
                    wake = True
                    self._causes.setdefault(vid, {
                        "event": "", "type": "head_entry",
                        "trace": agg.get("trace") or "",
                        "alert": ""})
        if wake:
            self._wake.set()

    def on_events(self, events: list[dict]) -> None:  # thread-entry
        """Cluster-journal ingest hook (chained after the EC
        coordinator's): record which heat alert/event/trace made each
        volume urgent, and wake the planner."""
        wake = False
        with self._lock:
            for e in events:
                etype = e.get("type") or ""
                det = e.get("details") or {}
                if etype == "alert_fired":
                    self._alerts[str(det.get("alert") or "")] = {
                        "event": e.get("id", ""),
                        "trace": det.get("exemplar_trace")
                        or e.get("trace") or ""}
                    wake = True
                elif etype == "alert_resolved":
                    self._alerts.pop(str(det.get("alert") or ""), None)
                elif etype in _WAKE_EVENT_TYPES:
                    try:
                        vid = int(det.get("volume"))
                    except (TypeError, ValueError):
                        continue
                    self._causes[vid] = {
                        "event": e.get("id", ""), "type": etype,
                        "trace": e.get("trace") or "",
                        "alert": _ALERT_FOR_TYPE.get(etype, "")}
                    wake = True
        if wake:
            self._wake.set()

    def _cause_alert_locked(self, vid: int) -> str:  # holds: _lock
        """The firing heat alert id this volume's actuation answers:
        the cause event's mapped rule when firing, else any firing
        heat rule, else the static mapping."""
        cause = self._causes.get(vid, {})
        mapped = cause.get("alert", "")
        if mapped and mapped in self._alerts:
            return mapped
        for name in ("flash_crowd", "heat_shift"):
            if name in self._alerts:
                return name
        return mapped

    def _cause(self, vid: int) -> dict:
        with self._lock:
            c = self._causes.get(vid, {})
            return {"alert": self._cause_alert_locked(vid),
                    "cause_trace": c.get("trace", ""),
                    "cause_event": c.get("event", "")}

    # --- replicated actuation records (master HA) -------------------------
    def _record(self, op: str, vid: int, cause: dict,  # leader-only
                **extra) -> None:
        """Journal one actuation lifecycle record: apply locally, then
        hand to replicate_fn (the master's synchronous raft append) so
        it survives this leader.  Called OUTSIDE _lock."""
        at = round(time.time(), 3)
        rec = {"id": f"{vid}:{op}:{at:.3f}", "op": op, "vid": vid,
               "at": at, "alert": cause.get("alert", ""),
               "cause_trace": cause.get("cause_trace", ""),
               "cause_event": cause.get("cause_event", ""), **extra}
        self.apply_replicated(rec)
        if self.replicate_fn is not None:
            try:
                self.replicate_fn(rec)
            except Exception:
                pass  # replication loss must never fail the actuation

    def apply_replicated(self, rec: dict) -> None:  # raft-apply, thread-entry
        """Land one actuation record (leader's local write or a
        follower's apply loop).  Idempotent: records dedup by id; the
        pending map is last-write-wins per volume; the added-replica
        ledger and tier registry fold in so a promoted follower knows
        what the old leader added/tiered."""
        try:
            vid = int(rec.get("vid"))
        except (TypeError, ValueError):
            return
        op = str(rec.get("op") or "")
        with self._lock:
            rid = str(rec.get("id") or f"{vid}:{op}:{rec.get('at')}")
            self._replog[rid] = dict(rec)
            while len(self._replog) > 256:
                self._replog.popitem(last=False)
            if op in ("grow_planned", "tier_pending"):
                self._replicated[vid] = dict(rec)
            elif op in ("grow_done", "grow_failed", "tier_done",
                        "tier_failed", "shrink_done", "recall_done"):
                self._replicated.pop(vid, None)
            if op == "grow_done" and rec.get("dst"):
                t = self._targets.setdefault(
                    vid, {"added": [], "cycles": 0})
                if rec["dst"] not in t["added"]:
                    t["added"].append(rec["dst"])
                t["grown_at"] = float(rec.get("at") or 0.0)
            elif op == "shrink_done" and rec.get("dst"):
                t = self._targets.get(vid)
                if t is not None and rec["dst"] in t.get("added", []):
                    t["added"].remove(rec["dst"])
                    t["shrunk_at"] = float(rec.get("at") or 0.0)
                    t["cycles"] = int(t.get("cycles") or 0) + 1
            elif op == "tier_done":
                self._tiered[vid] = {
                    "server": rec.get("server", ""),
                    "backend": rec.get("backend", ""),
                    "key": rec.get("key", ""),
                    "at": float(rec.get("at") or 0.0)}
            elif op == "recall_done":
                self._tiered.pop(vid, None)

    def export_replicated(self) -> dict:
        """The replicable actuation state (raft snapshot leg)."""
        with self._lock:
            return {"pending": {str(vid): dict(r)
                                for vid, r in self._replicated.items()},
                    "log": [dict(r) for r in self._replog.values()],
                    "targets": {str(vid): dict(t)
                                for vid, t in self._targets.items()},
                    "tiered": {str(vid): dict(t)
                               for vid, t in self._tiered.items()}}

    def import_replicated(self, doc: dict) -> None:  # raft-apply
        """Install a snapshot of the actuation state (idempotent:
        replays merge by record id / volume id)."""
        for rec in (doc or {}).get("log") or []:
            self.apply_replicated(rec)
        with self._lock:
            for vid_s, rec in ((doc or {}).get("pending") or {}).items():
                try:
                    self._replicated[int(vid_s)] = dict(rec)
                except (TypeError, ValueError):
                    continue
            for vid_s, t in ((doc or {}).get("targets") or {}).items():
                try:
                    self._targets[int(vid_s)] = dict(t)
                except (TypeError, ValueError):
                    continue
            for vid_s, t in ((doc or {}).get("tiered") or {}).items():
                try:
                    self._tiered[int(vid_s)] = dict(t)
                except (TypeError, ValueError):
                    continue

    def resume_replicated(self) -> None:
        """Promotion hook: re-arm every planned-but-unfinished
        actuation from the replicated records — the orphaned plan's
        cause attribution (alert + trace + event) survives the
        election, and the run_cycle resume pass closes plans whose
        actuation already landed instead of re-running them."""
        with self._lock:
            for vid, rec in self._replicated.items():
                self._causes.setdefault(vid, {
                    "event": rec.get("cause_event", ""),
                    "type": "replicated_plan",
                    "trace": rec.get("cause_trace", ""),
                    "alert": rec.get("alert", "")})
        self._wake.set()

    # --- the loop ---------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            if not self.is_leader_fn():
                continue
            with self._lock:
                paused = self.paused
            if paused:
                continue
            if self.admin_locked_fn():
                # an operator holds the shell's exclusive admin lock:
                # their volume surgery must not duel with ours
                continue
            try:
                self.run_cycle()
                with self._lock:
                    self.last_error = ""
            except Exception as e:  # keep the loop alive; surface it
                with self._lock:
                    self.last_error = f"{type(e).__name__}: {e}"[:300]

    def run_cycle(self) -> dict:
        """One observe->plan->actuate round (synchronous — tests, the
        bench drill and the scenario engine call it directly)."""
        doc = self.heat_fn() or {}
        shares = {}
        traces = {}
        for row in doc.get("volumes") or []:
            try:
                vid = int(row.get("volume"))
            except (TypeError, ValueError):
                continue
            shares[vid] = float(row.get("share") or 0.0)
            if row.get("trace"):
                traces[vid] = row["trace"]
        head = set()
        for vid in (doc.get("head") or {}).get("volumes") or []:
            try:
                head.add(int(vid))
            except (TypeError, ValueError):
                continue
        vols = self._volume_map()
        now = time.time()
        resumed_vids = self._resume_pending(vols)
        resumed = len(resumed_vids)
        grown = self._run_grows(vols, shares, traces, head, now,
                                skip=resumed_vids)
        recalled = self._run_recalls(vols, shares, head)
        shrunk = self._run_shrinks(vols, shares, now)
        tiered = self._run_tiers(vols, shares, now)
        with self._lock:
            self.cycles += 1
            self.last_cycle_at = now
        return {"grown": grown, "shrunk": shrunk, "tiered": tiered,
                "recalled": recalled, "resumed": resumed}

    # --- topology snapshot ------------------------------------------------
    def _volume_map(self) -> dict[int, dict]:
        """vid -> {holders: [urls], collection, size, read_only} for
        every REPLICA volume, read off the live topology under its lock
        (stale peers excluded — an unreachable holder can neither serve
        the flash crowd nor accept a tier command)."""
        try:
            stale = set(self.stale_peers_fn() or ())
        except Exception:
            stale = set()
        out: dict[int, dict] = {}
        nodes: dict[str, NodeView] = {}
        with self.topo.lock:
            for n in self.topo.all_nodes():
                rack = n.rack.name if n.rack else "DefaultRack"
                dc = n.dc.name if n.dc else "DefaultDataCenter"
                nodes[n.url] = NodeView(
                    url=n.url, rack=rack, dc=dc,
                    free=float(n.free_space()),
                    ec_shards=n.ec_shard_count(),
                    alive=n.url not in stale)
                for vid, v in n.volumes.items():
                    e = out.setdefault(vid, {
                        "holders": [], "collection": v.collection,
                        "size": 0, "read_only": False})
                    e["holders"].append(n.url)
                    e["size"] = max(e["size"], int(v.size))
                    e["read_only"] = e["read_only"] or bool(v.read_only)
        with self._lock:
            self._nodes = nodes
        return out

    def _placement_view(self, vid: int, holders: list[str],
                        collection: str) -> ClusterView:
        """A ClusterView seeding the volume's replica set as shard 0
        holders, so placement_rank's rack/DC diversity pools rank
        replica targets exactly like EC shard targets."""
        with self._lock:
            nodes = dict(self._nodes)
        view = ClusterView(nodes=nodes)
        view.shards[vid] = {0: list(holders)}
        view.collections[vid] = collection
        return view

    def _take_move_token(self) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.move_burst,
                self._tokens + (now - self._tokens_at) * self.move_rate)
            self._tokens_at = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    # --- resume (master HA) -----------------------------------------------
    def _resume_pending(self, vols: dict[int, dict]) -> set[int]:
        """Close or re-drive plans inherited from a dead leader.  A
        grow whose copy already landed (the dst holds the volume in
        the live topology) is closed WITHOUT re-copying — zero
        duplicate replica adds; one that never landed re-executes to
        the SAME dst.  A pending tier re-issues the idempotent commit
        leg (the raft record already holds the commit decision).
        Returns the touched vids so this cycle's grow pass skips them
        (its volume map predates the resume actuations)."""
        with self._lock:
            pending = [(vid, dict(r))
                       for vid, r in self._replicated.items()]
        resumed: set[int] = set()
        for vid, rec in pending:
            op = rec.get("op")
            cause = {"alert": rec.get("alert", ""),
                     "cause_trace": rec.get("cause_trace", ""),
                     "cause_event": rec.get("cause_event", "")}
            if op == "grow_planned":
                dst = rec.get("dst") or ""
                info = vols.get(vid)
                if info is None or not dst:
                    self._record("grow_failed", vid, cause,
                                 error="volume vanished before resume")
                    continue
                if dst in info["holders"]:
                    # the old leader's copy landed: close the plan
                    self._finish_grow(vid, rec.get("src") or "", dst,
                                      cause, resumed_from=rec.get("id"))
                else:
                    self._execute_grow(vid, info, dst, cause,
                                       resumed_from=rec.get("id"))
                resumed.add(vid)
            elif op == "tier_pending":
                server = rec.get("server") or ""
                if server:
                    self._commit_tier(vid, server, dict(rec), cause)
                    resumed.add(vid)
        return resumed

    # --- hot path: grow ---------------------------------------------------
    def _run_grows(self, vols, shares, traces, head, now,
                   skip=()) -> int:
        candidates = []
        with self._lock:
            cause_vids = set(self._causes)
        for vid in sorted(head | cause_vids, key=lambda v: -shares.get(v, 0.0)):
            info = vols.get(vid)
            if info is None or vid in skip:
                continue
            if shares.get(vid, 0.0) < self.grow_share and vid not in head:
                continue
            if len(info["holders"]) >= self.max_replicas:
                continue
            with self._lock:
                if vid in self._tiered or vid in self._replicated:
                    continue  # tiered (recall path) or plan in flight
                t = self._targets.get(vid) or {}
                if int(t.get("cycles") or 0) >= \
                        self.max_cycles_per_volume:
                    continue  # thrash guard: this volume flapped enough
                if now - float(t.get("shrunk_at") or 0.0) < \
                        self.regrow_cooldown_s:
                    continue  # hysteresis: just shrunk, don't flap back
            candidates.append((vid, info))
        grown = 0
        for vid, info in candidates:
            if self._stop.is_set():
                break
            if not self._take_move_token():
                break  # budget spent; the rest keeps next cycle
            with self._lock:
                if traces.get(vid) and vid not in self._causes:
                    self._causes[vid] = {
                        "event": "", "type": "head_entry",
                        "trace": traces[vid], "alert": ""}
            cause = self._cause(vid)
            view = self._placement_view(vid, info["holders"],
                                        info["collection"])
            dst = next(iter(placement_rank(
                view, vid, 0, exclude=tuple(info["holders"]))), None)
            if dst is None:
                continue  # no rack-diverse target alive
            # quorum-replicate the plan BEFORE executing: a leader
            # killed mid-copy leaves this record for its successor,
            # which resumes (not restarts) the add against this dst
            self._record("grow_planned", vid, cause, dst=dst,
                         src=info["holders"][0],
                         share=round(shares.get(vid, 0.0), 4))
            if self._execute_grow(vid, info, dst, cause):
                grown += 1
        return grown

    def _execute_grow(self, vid: int, info: dict, dst: str,
                      cause: dict, resumed_from: str = "") -> bool:
        src = next(iter(info["holders"]), "")
        if not src:
            self._record("grow_failed", vid, cause,
                         error="no alive holder to copy from")
            return False
        try:
            with _deadline.scope(self.actuation_deadline_s):
                self.executor.admin_post(dst, "/admin/volume_copy", {
                    "volume_id": vid,
                    "collection": info["collection"],
                    "source_data_node": src})
                self.executor.refresh_heartbeats([dst])
        except Exception as e:
            # the destination already holding the volume is SUCCESS
            # arriving by another path (the old leader's copy landed
            # after our topology snapshot): never a duplicate add
            if "already here" not in str(e):
                with self._lock:
                    self.failures += 1
                    self.recent.appendleft({
                        "at": round(time.time(), 3), "vid": vid,
                        "action": "grow_failed", "dst": dst,
                        "error": f"{type(e).__name__}: {e}"[:200],
                        **cause})
                from ..observability import events as _events

                _events.emit("autoscale_failed",
                             server=self.server or None, vid=vid,
                             action="grow", dst=dst,
                             error=f"{type(e).__name__}: {e}"[:200],
                             **cause)
                self._record("grow_failed", vid, cause, dst=dst,
                             error=f"{type(e).__name__}: {e}"[:200])
                return False
        self._finish_grow(vid, src, dst, cause,
                          resumed_from=resumed_from)
        return True

    def _finish_grow(self, vid: int, src: str, dst: str, cause: dict,
                     resumed_from: str = "") -> None:
        with self._lock:
            self.grows_done += 1
            t = self._targets.setdefault(vid, {"added": [], "cycles": 0})
            if dst not in t["added"]:
                t["added"].append(dst)
            t["grown_at"] = time.time()
            self._cold_since.pop(vid, None)
            self.recent.appendleft({
                "at": round(time.time(), 3), "vid": vid,
                "action": "replica_grow", "src": src, "dst": dst,
                "resumed": bool(resumed_from), **cause})
        from ..observability import events as _events

        # the journaled replica-add carries WHY: the firing heat alert
        # id and the exemplar trace of the flash crowd that caused it
        _events.emit("replica_grow", server=self.server or None,
                     trace_id=cause.get("cause_trace") or None,
                     vid=vid, src=src, dst=dst,
                     resumed=bool(resumed_from), **cause)
        self._record("grow_done", vid, cause, dst=dst, src=src,
                     resumed_from=resumed_from)

    # --- hot path: recall -------------------------------------------------
    def _run_recalls(self, vols, shares, head) -> int:
        with self._lock:
            tiered = {vid: dict(t) for vid, t in self._tiered.items()}
        recalled = 0
        for vid, t in tiered.items():
            if shares.get(vid, 0.0) < self.grow_share and vid not in head:
                continue
            info = vols.get(vid)
            server = t.get("server") or (
                next(iter(info["holders"]), "") if info else "")
            if not server:
                continue
            cause = self._cause(vid)
            try:
                with _deadline.scope(self.actuation_deadline_s):
                    self.executor.admin_post(
                        server, "/admin/tier_download",
                        {"volume_id": vid})
            except Exception as e:
                with self._lock:
                    self.failures += 1
                    self.recent.appendleft({
                        "at": round(time.time(), 3), "vid": vid,
                        "action": "recall_failed", "server": server,
                        "error": f"{type(e).__name__}: {e}"[:200]})
                from ..observability import events as _events

                _events.emit("autoscale_failed",
                             server=self.server or None, vid=vid,
                             action="recall",
                             error=f"{type(e).__name__}: {e}"[:200],
                             **cause)
                continue
            with self._lock:
                self.recalls_done += 1
                self._tiered.pop(vid, None)
                self._cold_since.pop(vid, None)
                self.recent.appendleft({
                    "at": round(time.time(), 3), "vid": vid,
                    "action": "tier_recall", "server": server, **cause})
            from ..observability import events as _events

            _events.emit("tier_recall", server=self.server or None,
                         trace_id=cause.get("cause_trace") or None,
                         vid=vid, volume_server=server, **cause)
            self._record("recall_done", vid, cause, server=server)
            recalled += 1
        return recalled

    # --- cold path: shrink ------------------------------------------------
    def _run_shrinks(self, vols, shares, now) -> int:
        """Drop ONE added replica per sufficiently-cold volume per
        cycle, only after the hold-down has run uninterrupted — the
        hysteresis half of the thrash guard."""
        due = []
        with self._lock:
            for vid, t in self._targets.items():
                if not t.get("added"):
                    continue
                if shares.get(vid, 0.0) > self.cold_share:
                    self._cold_since.pop(vid, None)
                    continue
                since = self._cold_since.setdefault(vid, now)
                if now - since >= self.hold_down_s:
                    due.append(vid)
        shrunk = 0
        for vid in due:
            if not self._take_move_token():
                break
            info = vols.get(vid)
            cause = self._cause(vid)
            with self._lock:
                t = self._targets.get(vid) or {}
                added = list(t.get("added") or ())
            # drop the most recent add still actually holding a copy
            dst = next((u for u in reversed(added)
                        if info is None or u in info["holders"]), None)
            if dst is None:
                continue
            try:
                with _deadline.scope(self.actuation_deadline_s):
                    self.executor.admin_post(dst, "/admin/delete_volume",
                                             {"volume_id": vid})
                    self.executor.refresh_heartbeats([dst])
            except Exception as e:
                with self._lock:
                    self.failures += 1
                    self.recent.appendleft({
                        "at": round(time.time(), 3), "vid": vid,
                        "action": "shrink_failed", "dst": dst,
                        "error": f"{type(e).__name__}: {e}"[:200]})
                from ..observability import events as _events

                _events.emit("autoscale_failed",
                             server=self.server or None, vid=vid,
                             action="shrink", dst=dst,
                             error=f"{type(e).__name__}: {e}"[:200],
                             **cause)
                continue
            with self._lock:
                self.shrinks_done += 1
                t = self._targets.get(vid)
                if t is not None and dst in t.get("added", []):
                    t["added"].remove(dst)
                    t["shrunk_at"] = now
                    t["cycles"] = int(t.get("cycles") or 0) + 1
                self._cold_since.pop(vid, None)
                self._causes.pop(vid, None)
                self.recent.appendleft({
                    "at": round(time.time(), 3), "vid": vid,
                    "action": "replica_shrink", "dst": dst, **cause})
            from ..observability import events as _events

            _events.emit("replica_shrink", server=self.server or None,
                         vid=vid, dst=dst,
                         hold_down_s=self.hold_down_s, **cause)
            self._record("shrink_done", vid, cause, dst=dst)
            shrunk += 1
        return shrunk

    # --- cold path: tier --------------------------------------------------
    def _tier_eligible(self, vid: int, info: dict, shares: dict,
                       now: float) -> bool:
        if not self.tier_backend:
            return False
        if len(info["holders"]) != 1:
            return False  # only single-replica volumes tier
        with self._lock:
            if vid in self._tiered or vid in self._replicated:
                return False
            if (self._targets.get(vid) or {}).get("added"):
                return False
        full = info["size"] >= self.tier_full_frac * \
            self.volume_size_limit or info["read_only"]
        if not full:
            return False
        if shares.get(vid, 0.0) > self.cold_share:
            with self._lock:
                self._cold_since.pop(vid, None)
            return False
        with self._lock:
            since = self._cold_since.setdefault(vid, now)
        return now - since >= self.tier_after_s

    def _run_tiers(self, vols, shares, now) -> int:
        tiered = 0
        for vid, info in sorted(vols.items()):
            if not self._tier_eligible(vid, info, shares, now):
                continue
            server = info["holders"][0]
            cause = self._cause(vid)
            # two-phase: (1) upload + verify on the volume server —
            # local .dat retained, manifest `pending`
            try:
                with _deadline.scope(self.actuation_deadline_s):
                    r = self.executor.admin_post(
                        server, "/admin/tier_upload",
                        {"volume_id": vid,
                         "backend": self.tier_backend,
                         "two_phase": True})
            except Exception as e:
                with self._lock:
                    self.failures += 1
                    self.recent.appendleft({
                        "at": round(time.time(), 3), "vid": vid,
                        "action": "tier_failed", "server": server,
                        "error": f"{type(e).__name__}: {e}"[:200]})
                from ..observability import events as _events

                _events.emit("autoscale_failed",
                             server=self.server or None, vid=vid,
                             action="tier",
                             error=f"{type(e).__name__}: {e}"[:200],
                             **cause)
                continue
            manifest = (r or {}).get("manifest") or {}
            # (2) the tier_committed decision rides the raft log BEFORE
            # the local delete: this record IS the commit point — a
            # leader (or volume server) crash after it resumes the
            # commit, a crash before it garbage-collects the upload
            self._record("tier_pending", vid, cause, server=server,
                         backend=self.tier_backend,
                         key=manifest.get("key", ""),
                         file_size=manifest.get("file_size", 0),
                         crc32=manifest.get("crc32"))
            if self._commit_tier(vid, server, {
                    "backend": self.tier_backend,
                    "key": manifest.get("key", "")}, cause):
                tiered += 1
        return tiered

    def _commit_tier(self, vid: int, server: str, rec: dict,
                     cause: dict) -> bool:
        """(3) the idempotent commit leg: the volume server persists
        `committed` and drops its local `.dat`.  Safe to re-issue after
        a failover — a volume server that crashed uncommitted GC'd the
        upload, which surfaces here as tier_failed (re-planned cold)."""
        try:
            with _deadline.scope(self.actuation_deadline_s):
                self.executor.admin_post(server, "/admin/tier_commit",
                                         {"volume_id": vid})
        except Exception as e:
            with self._lock:
                self.failures += 1
                self.recent.appendleft({
                    "at": round(time.time(), 3), "vid": vid,
                    "action": "tier_failed", "server": server,
                    "error": f"{type(e).__name__}: {e}"[:200]})
            from ..observability import events as _events

            _events.emit("autoscale_failed", server=self.server or None,
                         vid=vid, action="tier_commit",
                         error=f"{type(e).__name__}: {e}"[:200],
                         **cause)
            self._record("tier_failed", vid, cause, server=server,
                         error=f"{type(e).__name__}: {e}"[:200])
            return False
        with self._lock:
            self.tiers_done += 1
            self._cold_since.pop(vid, None)
            self.recent.appendleft({
                "at": round(time.time(), 3), "vid": vid,
                "action": "tier_committed", "server": server,
                "backend": rec.get("backend", ""),
                "key": rec.get("key", ""), **cause})
        from ..observability import events as _events

        _events.emit("tier_committed", server=self.server or None,
                     vid=vid, volume_server=server,
                     backend=rec.get("backend", ""),
                     key=rec.get("key", ""), **cause)
        self._record("tier_done", vid, cause, server=server,
                     backend=rec.get("backend", ""),
                     key=rec.get("key", ""))
        return True

    # --- manual actuation (shell volume.tier) -----------------------------
    def tier_volume(self, vid: int, backend: str = "",
                    recall: bool = False) -> dict:
        """Operator-driven tier/recall (shell `volume.tier`): the SAME
        two-phase legs the autonomous cold path runs — upload+verify,
        raft-logged tier_pending commit point, idempotent commit — so
        a manually tiered volume lands in the replicated tiered
        registry and auto-recalls when heat returns.  Raises ValueError
        on operator mistakes (unknown volume, replicated volume, no
        backend), RuntimeError when an actuation leg fails."""
        vols = self._volume_map()
        if recall:
            with self._lock:
                t = dict(self._tiered.get(vid) or {})
            info = vols.get(vid)
            server = t.get("server") or (
                next(iter(info["holders"]), "") if info else "")
            if not server:
                raise ValueError(f"volume {vid} is not tiered")
            cause = self._cause(vid)
            with _deadline.scope(self.actuation_deadline_s):
                self.executor.admin_post(server, "/admin/tier_download",
                                         {"volume_id": vid})
            with self._lock:
                self.recalls_done += 1
                self._tiered.pop(vid, None)
                self._cold_since.pop(vid, None)
                self.recent.appendleft({
                    "at": round(time.time(), 3), "vid": vid,
                    "action": "tier_recall", "server": server,
                    "manual": True, **cause})
            from ..observability import events as _events

            _events.emit("tier_recall", server=self.server or None,
                         vid=vid, volume_server=server, manual=True,
                         **cause)
            self._record("recall_done", vid, cause, server=server)
            return {"recalled": vid, "server": server}
        info = vols.get(vid)
        if info is None:
            raise ValueError(f"volume {vid} not found")
        if len(info["holders"]) != 1:
            raise ValueError(
                f"volume {vid} has {len(info['holders'])} replicas; "
                "only single-replica volumes tier")
        backend = backend or self.tier_backend
        if not backend:
            raise ValueError("no tier backend: pass -backend or start "
                             "the master with -autoscale.tierBackend")
        server = info["holders"][0]
        cause = self._cause(vid)
        with _deadline.scope(self.actuation_deadline_s):
            r = self.executor.admin_post(
                server, "/admin/tier_upload",
                {"volume_id": vid, "backend": backend,
                 "two_phase": True})
        manifest = (r or {}).get("manifest") or {}
        self._record("tier_pending", vid, cause, server=server,
                     backend=backend, key=manifest.get("key", ""),
                     file_size=manifest.get("file_size", 0),
                     crc32=manifest.get("crc32"))
        if not self._commit_tier(vid, server, {
                "backend": backend,
                "key": manifest.get("key", "")}, cause):
            raise RuntimeError(
                f"tier commit failed for volume {vid}; the verified "
                "upload was rolled back (see autoscale.status)")
        return {"tiered": vid, "server": server, "backend": backend,
                "key": manifest.get("key", "")}

    # --- views ------------------------------------------------------------
    def health_contribution(self) -> dict:
        """Master-local addition to /cluster/health totals: failed
        actuations (grow/shrink/tier/recall legs that errored) — the
        autoscale_failures health key."""
        with self._lock:
            return {"autoscale_failures": int(self.failures)}

    def status(self) -> dict:
        admin_locked = False
        try:
            admin_locked = bool(self.admin_locked_fn())
        except Exception:
            pass
        with self._lock:
            return {
                "enabled": self.enabled,
                "paused": self.paused or admin_locked,
                "pause_reason": self.pause_reason or (
                    "admin_lock" if admin_locked else ""),
                "interval_s": self.interval_s,
                "cycles": self.cycles,
                "last_cycle_at": round(self.last_cycle_at, 3),
                "last_error": self.last_error,
                "knobs": {"grow_share": self.grow_share,
                          "max_replicas": self.max_replicas,
                          "cold_share": self.cold_share,
                          "hold_down_s": self.hold_down_s,
                          "regrow_cooldown_s": self.regrow_cooldown_s,
                          "max_cycles_per_volume":
                              self.max_cycles_per_volume,
                          "tier_backend": self.tier_backend,
                          "tier_after_s": self.tier_after_s},
                "targets": {str(vid): dict(t)
                            for vid, t in self._targets.items()},
                "tiered": {str(vid): dict(t)
                           for vid, t in self._tiered.items()},
                "grows": self.grows_done,
                "shrinks": self.shrinks_done,
                "tiers": self.tiers_done,
                "recalls": self.recalls_done,
                "failures": self.failures,
                "move_budget": {"rate_per_s": self.move_rate,
                                "burst": self.move_burst,
                                "tokens": round(self._tokens, 2)},
                "recent": list(self.recent),
                # the raft-replicated actuation records: identical on
                # the leader and a caught-up follower (the state-hash
                # equality surface the failover tests compare)
                "replicated": {
                    "pending": {str(v): dict(r)
                                for v, r in self._replicated.items()},
                    "log": [dict(r) for r in self._replog.values()]},
            }
