"""Autonomous EC rebuild & rebalance coordinator (master-side).

PAPER.md's L4 control plane was reproduced as passive bookkeeping: the
master knows every shard location (topology.py `ec_shard_locations`),
the signal plane reports every degraded moment (/cluster/events,
/cluster/alerts, /cluster/health), and nothing ACTS on any of it — a
rack dying at 3am pages a human who then types `ec.rebuild` by hand.
This module closes that loop with three separable layers:

  ClusterView + planner   pure functions over a neutral topology
                          snapshot: clean-shard deficits, a rack/DC-
                          aware placement scorer (volume_growth.py's
                          same-rack / other-rack / other-DC semantics
                          turned into a ranking), rebuild-host choice,
                          and dedupe/rack-diversity/skew rebalance
                          plans.  No HTTP, no locks — unit-testable and
                          shared verbatim by `weed shell` ec.rebuild /
                          ec.balance, so manual and autonomous moves
                          agree by construction.
  PlanExecutor            the HTTP legs (/admin/ec/copy -> mount ->
                          delete, /admin/ec/rebuild), transport
                          injected so the shell drives it through
                          CommandEnv and tests through fakes.  Every
                          step passes the `coord.exec` fault point.
  EcCoordinator           the master-side loop: subscribes to the
                          cluster event journal (shard_corrupt,
                          scrub_unrepairable, peer_stale, alert_fired)
                          instead of re-deriving state, keeps a
                          priority queue of degraded EC volumes keyed
                          by clean-shard deficit (below k+1 first,
                          below k critical), runs bounded-concurrency
                          repairs and a token-bucket-budgeted rebalance
                          pass on membership change.  Every action is
                          journaled with the alert id and trace id that
                          caused it, under a force-sampled trace root
                          of its own (the repair's cross-server hops
                          stitch at GET /cluster/traces/<id>).

The coordinator pauses itself while the shell's admin lock is held (no
dueling migrations) and via POST /cluster/coordinator/pause.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..ec.layout import DATA_SHARDS_COUNT, TOTAL_SHARDS_COUNT
from ..utils import deadline as _deadline
from ..utils import faultinject
from ..utils.backoff import retry_allowed


# --------------------------------------------------------------------------
# cluster view: one neutral snapshot both the master loop and the shell
# commands plan against
# --------------------------------------------------------------------------

@dataclass
class NodeView:
    url: str
    rack: str = "DefaultRack"
    dc: str = "DefaultDataCenter"
    free: float = 0.0
    ec_shards: int = 0
    alive: bool = True

    @property
    def rack_key(self) -> tuple[str, str]:
        # racks are only unique within a DC (two DCs may both have
        # a "rack1"): placement diversity keys on the (dc, rack) pair
        return (self.dc, self.rack)


@dataclass
class ClusterView:
    """vid -> shard id -> holder urls, plus per-node rack/DC/load."""
    nodes: dict[str, NodeView] = field(default_factory=dict)
    shards: dict[int, dict[int, list[str]]] = field(default_factory=dict)
    collections: dict[int, str] = field(default_factory=dict)

    def alive_holders(self, vid: int, sid: int) -> list[str]:
        return [u for u in self.shards.get(vid, {}).get(sid, [])
                if self.nodes.get(u) and self.nodes[u].alive]

    def present_shards(self, vid: int) -> set[int]:
        """Shard ids with at least one ALIVE holder — the clean-shard
        set the deficit math runs on (a shard only reachable on a
        stale peer cannot serve reads or feed a rebuild)."""
        return {sid for sid in self.shards.get(vid, {})
                if self.alive_holders(vid, sid)}

    def rack_counts(self, vid: int) -> dict[tuple, int]:
        """(dc, rack) -> how many of this volume's shards it holds."""
        out: dict[tuple, int] = {}
        for sid in self.shards.get(vid, {}):
            for url in self.alive_holders(vid, sid):
                key = self.nodes[url].rack_key
                out[key] = out.get(key, 0) + 1
        return out

    def racks(self) -> set[tuple]:
        return {n.rack_key for n in self.nodes.values() if n.alive}


def view_from_status(topology_doc: dict,
                     stale: tuple = ()) -> ClusterView:
    """Build a view from the master's /dir/status Topology document —
    the shell-side constructor (EcVolumes + EcCollections ride it)."""
    view = ClusterView()
    for dc in topology_doc.get("DataCenters", []):
        for rack in dc.get("Racks", []):
            for n in rack.get("DataNodes", []):
                view.nodes[n["Url"]] = NodeView(
                    url=n["Url"], rack=rack.get("Id", "DefaultRack"),
                    dc=dc.get("Id", "DefaultDataCenter"),
                    free=float(n.get("Free", 0)),
                    ec_shards=int(n.get("EcShards", 0)),
                    alive=n["Url"] not in stale)
    for vid_str, shard_map in topology_doc.get("EcVolumes", {}).items():
        view.shards[int(vid_str)] = {
            int(sid): list(urls) for sid, urls in shard_map.items()}
    for vid_str, coll in topology_doc.get("EcCollections", {}).items():
        view.collections[int(vid_str)] = coll
    return view


def clone_view(view: ClusterView) -> ClusterView:
    """Deep-enough copy for planning: plan_rebalance simulates moves
    forward on the view it plans over, so execution needs the original
    (pre-plan) holder state to drive the real mount/unmount decisions."""
    return ClusterView(
        nodes={u: NodeView(**vars(n)) for u, n in view.nodes.items()},
        shards={vid: {s: list(us) for s, us in m.items()}
                for vid, m in view.shards.items()},
        collections=dict(view.collections))


def view_from_topology(topo, stale: tuple = ()) -> ClusterView:
    """Build a view straight off the master's live Topology (under its
    lock, no HTTP) — the coordinator-side constructor.  `stale` names
    peers the aggregator could not scrape: registered but unreachable,
    so they must not count as clean-shard holders or repair targets."""
    with topo.lock:
        return view_from_status(topo.to_map(), stale=stale)


# --------------------------------------------------------------------------
# planner: deficits, placement scoring, rebuild-host choice, rebalance
# --------------------------------------------------------------------------

@dataclass
class Move:
    """One planned shard movement.  kind: 'move' relocates src -> dst,
    'dedupe' drops a duplicate copy from src (dst empty)."""
    vid: int
    sid: int
    src: str
    dst: str = ""
    kind: str = "move"
    reason: str = "skew"


def clean_deficits(view: ClusterView,
                   total: int = TOTAL_SHARDS_COUNT,
                   k: int = DATA_SHARDS_COUNT) -> dict[int, dict]:
    """Per-volume repair need: {vid: {clean, deficit, critical,
    under_replicated}}.  A volume is under-replicated below k+1 clean
    shards (one more loss starts costing data), critical below k
    (reads already need every survivor); any volume short of `total`
    distinct shards carries a deficit worth repairing."""
    out: dict[int, dict] = {}
    for vid in view.shards:
        clean = len(view.present_shards(vid))
        if clean >= total:
            continue
        out[vid] = {"clean": clean, "deficit": total - clean,
                    "critical": clean < k,
                    "under_replicated": clean < k + 1}
    return out


def placement_rank(view: ClusterView, vid: int, sid: int,
                   exclude: tuple = ()) -> list[str]:
    """Candidate targets for one shard, best first.  The scorer reuses
    volume_growth.py's placement semantics as a ranking: a rack not yet
    holding this volume's shards beats one that does (the 'other rack'
    pool), a fresh DC breaks ties (the 'other DC' pool), then fewest EC
    shards and most free slots — so spreads converge toward the same
    layout find_empty_slots would have chosen for replicas."""
    holders = set(view.shards.get(vid, {}).get(sid, []))
    rack_counts = view.rack_counts(vid)
    dc_counts: dict[str, int] = {}
    for key, c in rack_counts.items():
        dc_counts[key[0]] = dc_counts.get(key[0], 0) + c
    cands = [n for n in view.nodes.values()
             if n.alive and n.url not in holders
             and n.url not in exclude]
    cands.sort(key=lambda n: (
        rack_counts.get(n.rack_key, 0),    # rack diversity first
        dc_counts.get(n.dc, 0),            # then DC diversity
        n.free <= 0,                       # full nodes last
        n.ec_shards,                       # then least-loaded
        -n.free,
        n.url))                            # deterministic tie-break
    return [n.url for n in cands]


def choose_rebuild_host(view: ClusterView, vid: int) -> Optional[str]:
    """The server to rebuild on: already holds the most clean shards of
    this volume (fewest survivor copies over the wire), then most free
    slots, then least loaded.  None when no alive node exists."""
    local_counts: dict[str, int] = {}
    for sid in view.shards.get(vid, {}):
        for url in view.alive_holders(vid, sid):
            local_counts[url] = local_counts.get(url, 0) + 1
    cands = [n for n in view.nodes.values() if n.alive]
    if not cands:
        return None
    cands.sort(key=lambda n: (-local_counts.get(n.url, 0),
                              -n.free, n.ec_shards, n.url))
    return cands[0].url


def rack_ceiling(view: ClusterView,
                 total: int = TOTAL_SHARDS_COUNT,
                 k: int = DATA_SHARDS_COUNT) -> int:
    """Max shards of one volume a single rack may hold: losing any one
    rack must leave >= k shards, so the target is total - k — relaxed
    to an even split when the cluster has too few racks to afford it."""
    n_racks = max(1, len(view.racks()))
    return max(total - k, -(-total // n_racks))


def plan_rebalance(view: ClusterView, max_moves: int = 0,
                   total: int = TOTAL_SHARDS_COUNT,
                   k: int = DATA_SHARDS_COUNT) -> list[Move]:
    """Dedupe duplicate shard copies, fix rack-diversity violations,
    then tighten server shard-count skew.  Pure planning over the view
    (node counters are simulated forward as moves are planned);
    max_moves > 0 bounds the move/dedupe total (the coordinator's
    token-bucket budget caps the EXECUTION anyway, but a bounded plan
    keeps the status doc honest about what this cycle will attempt)."""
    moves: list[Move] = []
    counts = {u: n.ec_shards for u, n in view.nodes.items()}

    def budget_left() -> bool:
        return not max_moves or len(moves) < max_moves

    # per-node ceiling for one volume's shards: rack-diversity moves may
    # concentrate a few shards per node (unavoidable when shards
    # outnumber nodes) but never more than an even split's share
    alive_n = max(1, sum(1 for n in view.nodes.values() if n.alive))
    node_vid_ceiling = -(-total // alive_n)

    def _vid_held(vid: int, url: str) -> int:
        return sum(1 for us in view.shards.get(vid, {}).values()
                   if url in us)

    # 1. dedupe: keep the copy on the least-loaded holder
    for vid in sorted(view.shards):
        for sid, holders in sorted(view.shards[vid].items()):
            alive = [u for u in holders
                     if view.nodes.get(u) and view.nodes[u].alive]
            if len(alive) <= 1:
                continue
            keep = min(alive, key=lambda u: (counts.get(u, 0), u))
            for url in alive:
                if url == keep or not budget_left():
                    continue
                moves.append(Move(vid, sid, url, kind="dedupe",
                                  reason="dedupe"))
                counts[url] = counts.get(url, 1) - 1
                view.nodes[url].ec_shards = max(
                    0, view.nodes[url].ec_shards - 1)
                view.shards[vid][sid] = [keep]

    # 2. rack diversity: drain racks holding more of a volume than the
    #    ceiling allows toward the least-represented racks
    ceiling = rack_ceiling(view, total, k)
    for vid in sorted(view.shards):
        rack_counts = view.rack_counts(vid)
        for key in sorted(rack_counts, key=lambda kk: -rack_counts[kk]):
            while rack_counts[key] > ceiling and budget_left():
                src_sid, src_url = _pick_rack_excess(view, vid, key)
                if src_sid is None:
                    break
                dst = next(
                    (u for u in placement_rank(view, vid, src_sid)
                     if view.nodes[u].rack_key != key
                     and rack_counts.get(view.nodes[u].rack_key, 0)
                     < ceiling
                     and _vid_held(vid, u) < node_vid_ceiling), None)
                if dst is None:
                    break
                moves.append(Move(vid, src_sid, src_url, dst,
                                  reason="rack"))
                _apply_move(view, counts, rack_counts, vid, src_sid,
                            src_url, dst)

    # 3. skew: move shards off servers holding more than their share.
    #    Targets must hold NOTHING of the moved volume (concentrating a
    #    volume to fix server skew would trade durability for tidiness)
    #    and must not push their rack over the diversity ceiling.
    urls = sorted(u for u, n in view.nodes.items() if n.alive)
    if not urls:
        return moves
    avg = -(-sum(counts.get(u, 0) for u in urls) // len(urls))
    for src in sorted(urls, key=lambda u: -counts.get(u, 0)):
        while counts.get(src, 0) > avg and budget_left():
            picked = _pick_any_shard(view, src)
            if picked is None:
                break
            vid, sid = picked
            rack_counts = view.rack_counts(vid)
            src_rack = view.nodes[src].rack_key
            dst = next(
                (u for u in placement_rank(view, vid, sid)
                 if counts.get(u, 0) < avg
                 and _vid_held(vid, u) == 0
                 # same-rack moves leave the rack count unchanged;
                 # cross-rack ones must not push dst over the ceiling
                 and (view.nodes[u].rack_key == src_rack
                      or rack_counts.get(view.nodes[u].rack_key, 0)
                      < ceiling)), None)
            if dst is None:
                break
            moves.append(Move(vid, sid, src, dst, reason="skew"))
            _apply_move(view, counts, rack_counts, vid, sid, src, dst)
    return moves


def _pick_rack_excess(view: ClusterView, vid: int, rack_key):
    """A (sid, url) of this volume held in the over-full rack, taken
    from the rack's most-loaded holder first."""
    best = None
    for sid in sorted(view.shards.get(vid, {})):
        for url in view.alive_holders(vid, sid):
            if view.nodes[url].rack_key != rack_key:
                continue
            load = view.nodes[url].ec_shards
            if best is None or load > best[2]:
                best = (sid, url, load)
    return (best[0], best[1]) if best else (None, None)


def _pick_any_shard(view: ClusterView, src: str):
    """Any (vid, sid) held by src whose volume is most concentrated on
    it (moving those improves per-volume spread too)."""
    best = None
    for vid in sorted(view.shards):
        held = [sid for sid in sorted(view.shards[vid])
                if src in view.shards[vid][sid]]
        if held and (best is None or len(held) > best[0]):
            best = (len(held), vid, held[0])
    return (best[1], best[2]) if best else None


def _apply_move(view: ClusterView, counts: dict, rack_counts: dict,
                vid: int, sid: int, src: str, dst: str) -> None:
    """Simulate one move forward so later planning sees it."""
    holders = view.shards[vid][sid]
    if src in holders:
        holders.remove(src)
    holders.append(dst)
    counts[src] = counts.get(src, 1) - 1
    counts[dst] = counts.get(dst, 0) + 1
    view.nodes[src].ec_shards = max(0, view.nodes[src].ec_shards - 1)
    view.nodes[dst].ec_shards += 1
    src_key = view.nodes[src].rack_key
    rack_counts[src_key] = max(0, rack_counts.get(src_key, 1) - 1)
    dst_key = view.nodes[dst].rack_key
    rack_counts[dst_key] = rack_counts.get(dst_key, 0) + 1


# --------------------------------------------------------------------------
# executor: the HTTP legs, transport-injected
# --------------------------------------------------------------------------

class UnrepairableError(RuntimeError):
    """Fewer than k clean shards reachable: no rebuild host can be
    given enough survivors — repair is impossible until a holder
    returns or an operator restores shards."""


def _default_post(server: str, path: str, payload: dict,
                  timeout: float = 600.0) -> dict:
    from ..utils.httpd import http_json

    return http_json("POST", f"http://{server}{path}", payload,
                     timeout=timeout)


class PlanExecutor:
    """Execute repair and move plans over HTTP.  `post_fn(server, path,
    payload, timeout)` is the transport — CommandEnv.volume_post for the
    shell, the pooled http_json for the coordinator, fakes for tests —
    so the manual and autonomous paths share one implementation.  Every
    admin call passes the `coord.exec` fault point first (chaos drills
    fail any step deterministically).  Stateless: safe to share across
    concurrent repairs."""

    def __init__(self, post_fn: Optional[Callable] = None,
                 timeout: float = 600.0):
        self._post_fn = post_fn or _default_post
        self.timeout = timeout

    def _post(self, server: str, path: str, payload: dict,
              timeout: Optional[float] = None) -> dict:
        faultinject.hit("coord.exec")
        return self._post_fn(server, path, payload,
                             timeout or self.timeout)

    def admin_post(self, server: str, path: str, payload: dict,
                   timeout: Optional[float] = None) -> dict:
        """Generic admin leg for the OTHER master control loops (the
        heat autoscaler's replica copy / tier / recall calls): same
        injected transport, same explicit timeout, same `coord.exec`
        fault point — so chaos drills fail autoscaler actuations with
        the exact lever they fail repairs with."""
        return self._post(server, path, payload, timeout)

    def refresh_heartbeats(self, servers) -> None:
        """Nudge touched servers to re-heartbeat so the master registry
        converges now instead of on the next pulse (best-effort)."""
        for url in sorted(set(servers)):
            try:
                self._post_fn(url, "/admin/heartbeat_now", {}, 30.0)
            except Exception:
                pass

    def rescrub(self, view: ClusterView, vid: int) -> list[str]:
        """Post-repair targeted re-scrub: every holder of the healed
        volume re-verifies it against its sidecar NOW, so a stale
        `unrepairable` scrub verdict (recorded while < k clean shards
        were reachable) clears immediately instead of waiting for the
        next full pass.  Runs inside the repair's trace/deadline scope
        — each holder's targeted pass adopts the request's trace
        context, so the verdict flip journals under the repair.
        Best-effort: a holder mid-scan converges on its own schedule."""
        holders = sorted({
            u for us in view.shards.get(vid, {}).values() for u in us
            if view.nodes.get(u) and view.nodes[u].alive})
        started: list[str] = []
        for url in holders:
            try:
                # NO knob overrides in the payload: start() persists
                # any rate/interval it receives onto the LIVE scrubber,
                # so a re-scrub passing rate_mb_s=0 would silently
                # unthrottle the operator's configured IO cap forever
                self._post(url, "/ec/scrub/start",
                           {"volume_id": vid}, 30.0)
                started.append(url)
            except Exception:
                pass
        return started

    # --- moves ------------------------------------------------------------
    def execute_move(self, view: ClusterView, mv: Move) -> None:
        """One planned move against the real cluster; view holder lists
        are updated to match (plan_rebalance already simulated them for
        planning — execute on a FRESH view)."""
        collection = view.collections.get(mv.vid, "")
        if mv.kind == "dedupe":
            self._drop_shard(view, mv.vid, collection, mv.sid, mv.src)
            return
        self._post(mv.dst, "/admin/ec/copy", {
            "volume_id": mv.vid, "collection": collection,
            "shard_ids": [mv.sid], "source_data_node": mv.src})
        try:
            self._post(mv.dst, "/admin/ec/mount",
                       {"volume_id": mv.vid, "collection": collection})
        except Exception:
            # a copied-but-never-mounted shard file would be invisible
            # to heartbeats AND to the scrubber (which only scans
            # mounted shards) — an orphan forever; drop it before
            # surfacing the failure
            try:
                self._post(mv.dst, "/admin/ec/delete",
                           {"volume_id": mv.vid,
                            "collection": collection,
                            "shard_ids": [mv.sid]})
            except Exception:
                pass
            raise
        self._drop_shard(view, mv.vid, collection, mv.sid, mv.src)
        view.shards.setdefault(mv.vid, {}).setdefault(
            mv.sid, []).append(mv.dst)

    def _drop_shard(self, view: ClusterView, vid: int, collection: str,
                    sid: int, url: str) -> None:
        """Delete one shard copy, keeping the holder mounted iff it
        still holds other shards of the volume (deleting the last one
        also removes the .ecx/.ecj/.eci set)."""
        self._post(url, "/admin/ec/delete",
                   {"volume_id": vid, "collection": collection,
                    "shard_ids": [sid]})
        holders = view.shards.get(vid, {}).get(sid, [])
        if url in holders:
            holders.remove(url)
        still_holds = any(url in us
                          for s2, us in view.shards.get(vid, {}).items()
                          if s2 != sid)
        if still_holds:
            self._post(url, "/admin/ec/mount",
                       {"volume_id": vid, "collection": collection})
        else:
            self._post(url, "/admin/ec/unmount", {"volume_id": vid})

    # --- repair -----------------------------------------------------------
    def execute_repair(self, view: ClusterView, vid: int,
                       engine: Optional[str] = None,
                       spread: bool = True,
                       total: int = TOTAL_SHARDS_COUNT,
                       k: int = DATA_SHARDS_COUNT) -> dict:
        """Rebuild a volume's missing shards on the best host, then
        spread the rebuilt shards rack/zone-aware.  Returns {host,
        rebuilt, moves, copied}.  On a mid-plan failure the temp
        survivor copies are best-effort cleaned off the host (no orphan
        shards) and the error re-raised — the coordinator re-plans on a
        fresh view next cycle."""
        shard_map = view.shards.get(vid, {})
        collection = view.collections.get(vid, "")
        present = view.present_shards(vid)
        missing = sorted(set(range(total)) - present)
        if not missing:
            return {"host": "", "rebuilt": [], "moves": [], "copied": []}
        if len(present) < k:
            raise UnrepairableError(
                f"volume {vid}: only {len(present)} clean shards "
                f"reachable, need {k}")
        host = choose_rebuild_host(view, vid)
        if host is None:
            raise UnrepairableError(f"volume {vid}: no alive servers")
        # copy every survivor the host lacks — the rebuild regenerates
        # ALL locally-missing shards, so any survivor not copied first
        # would be regenerated into a duplicate of a remote copy.  A
        # copy the receiver REJECTS on .eci sidecar verification (rot
        # at the source / mangled wire) retries the next holder; with
        # every holder bad the shard is skipped and REGENERATED instead
        # — detection upgrades the plan, it never bricks it — and the
        # rotted source copies are dropped once the rebuild lands.
        copied: list[int] = []
        local = sum(1 for sid in present
                    if host in view.alive_holders(vid, sid))
        bad_sources: list[tuple[int, str]] = []
        last_err: Optional[Exception] = None
        try:
            for sid in sorted(present):
                holders = view.alive_holders(vid, sid)
                if host in holders:
                    continue
                for source in holders:
                    try:
                        self._post(host, "/admin/ec/copy", {
                            "volume_id": vid, "collection": collection,
                            "shard_ids": [sid],
                            "source_data_node": source,
                            "copy_ecx_file": True,
                            "copy_ecj_file": True})
                        copied.append(sid)
                        break
                    except Exception as e:
                        last_err = e
                        if "sidecar verification" in str(e):
                            bad_sources.append((sid, source))
                # a shard whose every holder failed is simply not
                # copied: the rebuild regenerates it below, provided
                # enough clean survivors did land
            if local + len(copied) < k:
                raise last_err or UnrepairableError(
                    f"volume {vid}: only {local + len(copied)} clean "
                    f"survivors reached {host}, need {k}")
            r = self._post(host, "/admin/ec/rebuild",
                           {"volume_id": vid, "collection": collection,
                            "engine": engine or "cpu"})
            rebuilt = [int(s) for s in r.get("rebuilt_shard_ids", [])]
            if copied:
                self._post(host, "/admin/ec/delete",
                           {"volume_id": vid, "collection": collection,
                            "shard_ids": copied})
            self._post(host, "/admin/ec/mount",
                       {"volume_id": vid, "collection": collection})
        except Exception:
            # leave no orphan survivor copies behind the failed attempt
            if copied:
                try:
                    self._post(host, "/admin/ec/delete",
                               {"volume_id": vid,
                                "collection": collection,
                                "shard_ids": copied})
                    self._post(host, "/admin/ec/mount",
                               {"volume_id": vid,
                                "collection": collection})
                except Exception:
                    pass
            raise
        view.nodes[host].ec_shards += len(rebuilt)
        # sources whose copy failed sidecar verification still hold the
        # rotted bytes; a clean replacement now exists — regenerated by
        # the rebuild OR copied from an alternate holder — so drop the
        # bad copies, else a later dedupe may keep the rotted one and
        # delete the clean one
        for sid, url in bad_sources:
            if sid not in rebuilt and sid not in copied:
                continue
            try:
                self._drop_shard(view, vid, collection, sid, url)
            except Exception:
                pass  # the scrubber will quarantine it eventually
        # spread: each rebuilt shard goes where the scorer says; the
        # host keeps those it is itself the best placement for.  A
        # failed spread move is NON-fatal: the rebuild already landed
        # (the data is safe and registered), and placement left
        # imperfect here converges through the rebalance pass — failing
        # the whole repair over it would journal a healed volume as
        # repair_failed and strand its cause attribution.
        moves: list[tuple[int, str]] = []
        move_errors: list[str] = []
        for sid in rebuilt:
            # rank BEFORE registering the host as this shard's holder,
            # so the host competes like any other candidate and keeps
            # the shards it is itself the best placement for
            target = next(iter(placement_rank(view, vid, sid)), None) \
                if spread else None
            shard_map.setdefault(sid, []).append(host)
            if target is None or target == host:
                continue
            try:
                self.execute_move(
                    view, Move(vid, sid, host, target,
                               reason="spread"))
            except Exception as e:
                move_errors.append(
                    f"{sid}->{target}: "
                    f"{type(e).__name__}: {e}"[:160])
                continue
            moves.append((sid, target))
        self.refresh_heartbeats([host] + [t for _s, t in moves])
        return {"host": host, "rebuilt": rebuilt, "moves": moves,
                "copied": copied, "move_errors": move_errors}


# --------------------------------------------------------------------------
# the coordinator loop
# --------------------------------------------------------------------------

# journal event types that wake the planner immediately (everything else
# rides the periodic safety-net scan)
_WAKE_EVENT_TYPES = ("shard_corrupt", "scrub_unrepairable",
                     "scrub_repair_failed", "peer_stale", "alert_fired",
                     "degraded_bind")
# alert rule name -> the event type whose moments it watches; used to
# attach the FIRING alert id to the repairs it caused
_ALERT_FOR_TYPE = {
    "shard_corrupt": "corrupt_shards_increase",
    "scrub_unrepairable": "scrub_unrepairable",
    "scrub_repair_failed": "scrub_unrepairable",
    "ec_under_replicated": "ec_under_replicated_increase",
}


class EcCoordinator:  # weedlint: concurrent-class
    """Master-side repair/rebalance loop.  Reached concurrently: its
    own cycle thread, the repair pool threads, HTTP router threads
    (status/pause/resume), and whatever thread ships events into the
    cluster journal (on_events).  All mutable state rides _lock; the
    HTTP legs run strictly outside it."""

    def __init__(self, topo, server: str = "",
                 stale_peers_fn: Optional[Callable[[], list]] = None,
                 is_leader_fn: Optional[Callable[[], bool]] = None,
                 admin_locked_fn: Optional[Callable[[], bool]] = None,
                 interval_s: float = 15.0, max_concurrent: int = 2,
                 move_rate: float = 1.0, move_burst: float = 8.0,
                 max_moves_per_cycle: int = 16,
                 max_repairs_per_cycle: int = 4,
                 post_fn: Optional[Callable] = None,
                 engine: Optional[str] = None,
                 repair_deadline_s: float = 900.0,
                 replicate_fn: Optional[Callable[[dict], None]] = None):
        self.topo = topo
        self.server = server
        self.stale_peers_fn = stale_peers_fn or (lambda: [])
        self.is_leader_fn = is_leader_fn or (lambda: True)
        self.admin_locked_fn = admin_locked_fn or (lambda: False)
        self.interval_s = float(interval_s)
        self.max_concurrent = max(1, int(max_concurrent))
        self.move_rate = float(move_rate)
        self.move_burst = float(move_burst)
        self.max_moves_per_cycle = int(max_moves_per_cycle)
        self.max_repairs_per_cycle = int(max_repairs_per_cycle)
        self.engine = engine
        # per-repair wall budget: every HTTP leg of one repair draws
        # from ONE propagated deadline (utils/deadline.py), so a
        # wedged peer cannot pin a repair slot for the sum of every
        # leg's individual timeout
        self.repair_deadline_s = float(repair_deadline_s)
        self.executor = PlanExecutor(post_fn=post_fn)
        from ..stats import coordinator_metrics

        self.metrics = coordinator_metrics()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # repair queue: vid -> {deficit, critical, attempts, cause...}
        self._queue: dict[int, dict] = {}  # guarded-by: _lock
        # degraded-event causes by vid, + currently-firing alert names
        self._causes: dict[int, dict] = {}  # guarded-by: _lock
        self._alerts: dict[str, dict] = {}  # guarded-by: _lock
        # volumes already journaled as under-replicated (one event per
        # transition, not one per scan)
        self._under_notified: set[int] = set()  # guarded-by: _lock
        self.paused = False  # guarded-by: _lock
        self.pause_reason = ""  # guarded-by: _lock
        self.cycles = 0  # guarded-by: _lock
        self.last_cycle_at = 0.0  # guarded-by: _lock
        self.last_error = ""  # guarded-by: _lock
        self.repairs_done = 0  # guarded-by: _lock
        self.repairs_failed = 0  # guarded-by: _lock
        self.moves_done = 0  # guarded-by: _lock
        self.recent: deque = deque(maxlen=64)  # guarded-by: _lock
        # token-bucket move budget
        self._tokens = float(move_burst)  # guarded-by: _lock
        self._tokens_at = time.monotonic()  # guarded-by: _lock
        # --- replicated repair records (master HA) -----------------
        # plan/done/failed records replicate through the raft log
        # (replicate_fn -> leader append; followers land in
        # apply_replicated): a leader killed mid-repair leaves its
        # planned record on a quorum, and resume_replicated() on the
        # NEW leader re-arms the orphaned repair with the ORIGINAL
        # alert/trace cause attribution.
        self.replicate_fn = replicate_fn
        # vid -> latest unfinished record (planned / failed)
        self._replicated: dict[int, dict] = {}  # guarded-by: _lock
        # ordered record history, dedup'd by record id
        self._replog: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "EcCoordinator":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ec-coordinator")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    @property
    def enabled(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def pause(self, reason: str = "api") -> None:
        with self._lock:
            self.paused = True
            self.pause_reason = reason

    def resume(self) -> None:
        with self._lock:
            self.paused = False
            self.pause_reason = ""
        self._wake.set()

    # --- event subscription ----------------------------------------------
    def on_events(self, events: list[dict]) -> None:  # thread-entry
        """Cluster-journal ingest hook: record causes (which alert /
        event / trace made each volume urgent) and wake the planner.
        Called on whatever thread shipped the batch — cheap, lock-only,
        never HTTP."""
        wake = False
        with self._lock:
            for e in events:
                etype = e.get("type") or ""
                det = e.get("details") or {}
                if etype == "alert_fired":
                    self._alerts[str(det.get("alert") or "")] = {
                        "event": e.get("id", ""),
                        "trace": det.get("exemplar_trace")
                        or e.get("trace") or ""}
                    wake = True
                elif etype == "alert_resolved":
                    self._alerts.pop(str(det.get("alert") or ""), None)
                elif etype in _WAKE_EVENT_TYPES:
                    vid = _vid_from_event(det)
                    if vid is not None:
                        self._causes[vid] = {
                            "event": e.get("id", ""), "type": etype,
                            "trace": e.get("trace") or "",
                            "alert": _ALERT_FOR_TYPE.get(etype, "")}
                    wake = True
        if wake:
            self._wake.set()

    # --- replicated repair records (master HA) ----------------------------
    def _record(self, op: str, vid: int, entry: dict,  # leader-only
                **extra) -> None:
        """Journal one repair lifecycle record: apply it to the local
        replicated view, then hand it to replicate_fn (the master's
        synchronous raft append) so it survives this leader.  Called
        OUTSIDE _lock — replication does quorum HTTP."""
        at = round(time.time(), 3)
        rec = {"id": f"{vid}:{op}:{at:.3f}", "op": op, "vid": vid,
               "at": at, "alert": entry.get("alert", ""),
               "cause_trace": entry.get("cause_trace", ""),
               "cause_event": entry.get("cause_event", ""), **extra}
        self.apply_replicated(rec)
        if self.replicate_fn is not None:
            try:
                self.replicate_fn(rec)
            except Exception:
                pass  # replication loss must never fail the repair

    def apply_replicated(self, rec: dict) -> None:  # raft-apply, thread-entry
        """Land one plan/done/failed record (leader's local write or a
        follower's apply-loop).  Idempotent: records dedup by id and
        the pending map is last-write-wins per volume."""
        try:
            vid = int(rec.get("vid"))
        except (TypeError, ValueError):
            return
        op = str(rec.get("op") or "")
        with self._lock:
            rid = str(rec.get("id") or f"{vid}:{op}:{rec.get('at')}")
            self._replog[rid] = dict(rec)
            while len(self._replog) > 256:
                self._replog.popitem(last=False)
            if op == "done":
                self._replicated.pop(vid, None)
            elif op in ("planned", "failed"):
                self._replicated[vid] = dict(rec)

    def export_replicated(self) -> dict:
        """The replicable repair-record state (raft snapshot leg)."""
        with self._lock:
            return {"pending": {str(vid): dict(r)
                                for vid, r in self._replicated.items()},
                    "log": [dict(r) for r in self._replog.values()]}

    def import_replicated(self, doc: dict) -> None:  # raft-apply
        """Install a snapshot of the repair-record state (idempotent:
        replays merge by record id / volume id)."""
        for rec in (doc or {}).get("log") or []:
            self.apply_replicated(rec)
        with self._lock:
            for vid_s, rec in ((doc or {}).get("pending") or {}).items():
                try:
                    self._replicated[int(vid_s)] = dict(rec)
                except (TypeError, ValueError):
                    continue

    def resume_replicated(self) -> None:
        """Promotion hook: re-arm every planned-but-unfinished repair
        from the replicated records — the orphaned repair's cause
        attribution (alert + trace + event) survives the election, so
        the new leader's repair_planned/repair_done events carry the
        ORIGINAL why, not a blank one.  The deficits themselves
        re-derive from volume-server heartbeats; this seeds the cause
        map and wakes the planner early."""
        with self._lock:
            for vid, rec in self._replicated.items():
                self._causes.setdefault(vid, {
                    "event": rec.get("cause_event", ""),
                    "type": "replicated_plan",
                    "trace": rec.get("cause_trace", ""),
                    "alert": rec.get("alert", "")})
        self._wake.set()

    # --- the loop ---------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                break
            if not self.is_leader_fn():
                continue
            with self._lock:
                paused, reason = self.paused, self.pause_reason
            if paused:
                continue
            if self.admin_locked_fn():
                # an operator holds the shell's exclusive admin lock:
                # their migrations must not duel with ours
                continue
            try:
                self.run_cycle()
                with self._lock:
                    self.last_error = ""
            except Exception as e:  # keep the loop alive; surface it
                self.metrics.cycles.inc("error")
                with self._lock:
                    self.last_error = f"{type(e).__name__}: {e}"[:300]

    def run_cycle(self) -> dict:
        """One planning+execution round (synchronous — tests and the
        bench drill call it directly)."""
        faultinject.hit("coord.plan")
        view = self._snapshot_view()
        deficits = clean_deficits(view)
        self._update_queue(view, deficits)
        repaired = self._run_repairs()
        moved = self._run_rebalance()
        with self._lock:
            self.cycles += 1
            self.last_cycle_at = time.time()
        self.metrics.cycles.inc("ok")
        return {"deficits": len(deficits), "repaired": repaired,
                "moved": moved}

    def _snapshot_view(self) -> ClusterView:
        try:
            stale = tuple(self.stale_peers_fn() or ())
        except Exception:
            stale = ()
        return view_from_topology(self.topo, stale=stale)

    def _update_queue(self, view: ClusterView,
                      deficits: dict[int, dict]) -> None:
        """Refresh the priority queue + the under-replication gauge
        from this cycle's deficits, and journal newly-under-replicated
        volumes (the ec_under_replicated health signal)."""
        newly_under: list[tuple[int, int]] = []
        with self._lock:
            for vid in list(self._queue):
                if vid not in deficits:
                    self._queue.pop(vid)  # healed (by us or otherwise)
                    self._causes.pop(vid, None)
                    self._under_notified.discard(vid)
            for vid, d in deficits.items():
                entry = self._queue.setdefault(
                    vid, {"attempts": 0, "queued_at": time.time()})
                entry.update(d)
                cause = self._causes.get(vid, {})
                entry["cause_trace"] = cause.get("trace", "")
                entry["cause_event"] = cause.get("event", "")
                entry["alert"] = self._cause_alert_locked(vid)
                if d["under_replicated"] and \
                        vid not in self._under_notified:
                    self._under_notified.add(vid)
                    newly_under.append((vid, d["clean"]))
            under = sum(1 for d in deficits.values()
                        if d["under_replicated"])
            self.metrics.under_replicated.set(float(under))
            self.metrics.queue_depth.set(float(len(self._queue)))
        from ..observability import events as _events

        for vid, clean in newly_under:
            _events.emit("ec_under_replicated", server=self.server
                         or None, vid=vid, clean_shards=clean,
                         threshold=DATA_SHARDS_COUNT + 1)

    def _cause_alert_locked(self, vid: int) -> str:  # holds: _lock
        """The firing alert id this volume's repair answers: the
        cause event's mapped rule when that alert is firing, else any
        relevant firing alert, else the cause's static mapping."""
        cause = self._causes.get(vid, {})
        mapped = cause.get("alert", "")
        if mapped and mapped in self._alerts:
            return mapped
        for name in self._alerts:
            if name in _ALERT_FOR_TYPE.values() or name == "peer_down":
                return name
        return mapped

    # --- repairs ----------------------------------------------------------
    def _run_repairs(self) -> int:
        now = time.time()
        with self._lock:
            snapshot = [(vid, dict(e)) for vid, e in self._queue.items()]
        ready = []
        for vid, e in snapshot:
            attempts = e.get("attempts", 0)
            if attempts:
                # exponential backoff per volume: a persistently
                # failing repair re-copies up to k survivor shards
                # per attempt — retrying every cycle would saturate
                # the wire and spam the journal
                hold = min(self.interval_s * (2 ** attempts), 600.0)
                if now - e.get("last_attempt_at", 0.0) < hold:
                    continue
                # a re-attempt is a RETRY and draws from the
                # per-destination retry budget (utils/backoff.py): a
                # repair that keeps failing degrades to one attempt
                # per budget refill — belt on top of the exponential
                # hold, and the denial is counted + journaled
                # (retry_budget_exhausted) so a repair storm that
                # DIDN'T happen still shows up on the record
                if not retry_allowed(f"repair:{vid}", "coordinator"):
                    continue
            ready.append((vid, e))
        with self._lock:
            batch = sorted(
                ready,
                key=lambda kv: (not kv[1].get("critical", False),
                                -kv[1].get("deficit", 0), kv[0]))
            batch = batch[:self.max_repairs_per_cycle]
            for vid, _e in batch:
                q = self._queue.get(vid)
                if q is not None:
                    q["last_attempt_at"] = now
        if not batch:
            return 0
        import concurrent.futures

        done = 0
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=self.max_concurrent,
                thread_name_prefix="coord-repair") as pool:
            for ok in pool.map(lambda kv: self._run_repair(*kv), batch):
                done += 1 if ok else 0
        return done

    def _run_repair(self, vid: int, entry: dict) -> bool:
        """One repair under its own force-sampled trace root: the
        copy/rebuild/mount hops stitch into a cluster trace, and every
        journaled event carries BOTH this trace (what we did) and the
        causing alert/trace (why)."""
        from ..observability import context as _trace_context
        from ..observability import events as _events
        from ..observability import get_tracer

        tr = get_tracer()
        ctx = prev = None
        if tr.enabled and _trace_context.current() is None:
            ctx = _trace_context.TraceContext(
                _trace_context.new_trace_id())
            prev = _trace_context.activate(ctx)
        prev_srv = _trace_context.swap_server(self.server or None)
        cause = {"alert": entry.get("alert", ""),
                 "cause_trace": entry.get("cause_trace", ""),
                 "cause_event": entry.get("cause_event", "")}
        try:
            with tr.span("coord.repair", vid=vid,
                         deficit=entry.get("deficit", 0),
                         alert=cause["alert"]):
                view = self._snapshot_view()
                if len(view.present_shards(vid)) >= TOTAL_SHARDS_COUNT:
                    # healed between queueing and execution (another
                    # repair, a returning holder): drop the entry
                    # without journaling a repair that never ran
                    with self._lock:
                        self._queue.pop(vid, None)
                        self._causes.pop(vid, None)
                        self._under_notified.discard(vid)
                        stale_plan = vid in self._replicated
                    if stale_plan:
                        # an inherited planned record for a volume that
                        # healed: close it out so followers stop
                        # carrying it as pending
                        self._record("done", vid, entry, host="",
                                     rebuilt=[], healed_elsewhere=True)
                    return True
                _events.emit("repair_planned", server=self.server
                             or None, vid=vid,
                             deficit=entry.get("deficit", 0),
                             critical=entry.get("critical", False),
                             **cause)
                # quorum-replicate the plan BEFORE executing: a leader
                # killed mid-repair leaves this record for its
                # successor to re-plan from (with the cause intact)
                self._record("planned", vid, entry,
                             deficit=entry.get("deficit", 0),
                             critical=entry.get("critical", False))
                try:
                    # ONE deadline for the whole repair: every leg
                    # (copies, rebuild, mounts, spread, re-scrub)
                    # draws from the same propagated budget, so a
                    # wedged peer fails the repair at the budget
                    # instead of pinning a repair slot for the sum of
                    # every leg's timeout
                    with _deadline.scope(self.repair_deadline_s):
                        res = self.executor.execute_repair(
                            view, vid, engine=self.engine)
                except Exception as e:
                    self.metrics.repairs.inc("failed")
                    self.metrics.repair_failures.inc(
                        type(e).__name__[:40])
                    with self._lock:
                        self.repairs_failed += 1
                        q = self._queue.get(vid)
                        if q is not None:
                            q["attempts"] = q.get("attempts", 0) + 1
                        self.recent.appendleft({
                            "at": round(time.time(), 3), "vid": vid,
                            "action": "repair_failed",
                            "error": f"{type(e).__name__}: {e}"[:200],
                            **cause})
                    _events.emit("repair_failed", server=self.server
                                 or None, vid=vid,
                                 error=f"{type(e).__name__}: {e}"[:200],
                                 **cause)
                    self._record("failed", vid, entry,
                                 error=f"{type(e).__name__}: {e}"[:200])
                    return False
                if not res["host"] and not res["rebuilt"]:
                    # healed between queueing and execution (another
                    # repair, a returning holder): not OUR repair —
                    # drop the queue entry without claiming credit
                    with self._lock:
                        self._queue.pop(vid, None)
                        self._causes.pop(vid, None)
                        self._under_notified.discard(vid)
                    self._record("done", vid, entry, host="",
                                 rebuilt=[], healed_elsewhere=True)
                    return True
                # post-repair targeted re-scrub (best-effort, its own
                # slice of the repair deadline): holders re-verify the
                # healed volume NOW so stale unrepairable verdicts
                # clear immediately — journaled under this repair's
                # trace via the scrub route's context adoption
                rescrubbed: list[str] = []
                try:
                    with _deadline.scope(min(60.0,
                                             self.repair_deadline_s)):
                        rescrubbed = self.executor.rescrub(view, vid)
                except Exception:
                    pass
                self.metrics.repairs.inc("done")
                with self._lock:
                    self.repairs_done += 1
                    self._queue.pop(vid, None)
                    self._causes.pop(vid, None)
                    self._under_notified.discard(vid)
                    self.recent.appendleft({
                        "at": round(time.time(), 3), "vid": vid,
                        "action": "repair_done", "host": res["host"],
                        "rebuilt": res["rebuilt"],
                        "spread": [list(m) for m in res["moves"]],
                        "rescrubbed": rescrubbed,
                        **cause})
                _events.emit("repair_done", server=self.server or None,
                             vid=vid, host=res["host"],
                             rebuilt=res["rebuilt"],
                             moves=len(res["moves"]),
                             move_errors=res.get("move_errors") or [],
                             rescrubbed=rescrubbed,
                             **cause)
                self._record("done", vid, entry, host=res["host"],
                             rebuilt=res["rebuilt"])
                return True
        finally:
            _trace_context.swap_server(prev_srv)
            if ctx is not None:
                _trace_context.activate(prev)

    # --- rebalance --------------------------------------------------------
    def _take_move_token(self) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.move_burst,
                self._tokens + (now - self._tokens_at) * self.move_rate)
            self._tokens_at = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def _run_rebalance(self) -> int:
        """Token-budgeted continuous rebalance: dedupe + rack-diversity
        + skew moves, run every cycle (plans are cheap; execution is
        what the bucket bounds).  A membership change (join/leave)
        needs no edge detection — it simply yields a non-empty plan
        the next time this runs."""
        view = self._snapshot_view()
        plan = plan_rebalance(clone_view(view),
                              max_moves=self.max_moves_per_cycle)
        if not plan:
            return 0
        from ..observability import context as _trace_context
        from ..observability import events as _events
        from ..observability import get_tracer

        tr = get_tracer()
        ctx = prev = None
        if tr.enabled and _trace_context.current() is None:
            ctx = _trace_context.TraceContext(
                _trace_context.new_trace_id())
            prev = _trace_context.activate(ctx)
        prev_srv = _trace_context.swap_server(self.server or None)
        executed = 0
        touched: set[str] = set()
        try:
            with tr.span("coord.rebalance", planned=len(plan)):
                for mv in plan:
                    if self._stop.is_set():
                        break
                    if not self._take_move_token():
                        break  # budget spent; the rest keeps next cycle
                    try:
                        self.executor.execute_move(view, mv)
                    except Exception as e:
                        with self._lock:
                            self.recent.appendleft({
                                "at": round(time.time(), 3),
                                "vid": mv.vid, "sid": mv.sid,
                                "action": "move_failed",
                                "error":
                                    f"{type(e).__name__}: {e}"[:200]})
                        continue
                    executed += 1
                    touched.update((mv.src, mv.dst) if mv.dst
                                   else (mv.src,))
                    self.metrics.moves.inc(mv.reason)
                    with self._lock:
                        self.moves_done += 1
                        self.recent.appendleft({
                            "at": round(time.time(), 3),
                            "vid": mv.vid, "sid": mv.sid,
                            "action": mv.kind, "reason": mv.reason,
                            "src": mv.src, "dst": mv.dst})
                    _events.emit("rebalance_move", server=self.server
                                 or None, vid=mv.vid, sid=mv.sid,
                                 src=mv.src, dst=mv.dst,
                                 reason=mv.reason)
                if touched:
                    self.executor.refresh_heartbeats(touched)
        finally:
            _trace_context.swap_server(prev_srv)
            if ctx is not None:
                _trace_context.activate(prev)
        return executed

    # --- views ------------------------------------------------------------
    def health_contribution(self) -> dict:
        """Master-local additions to /cluster/health totals: the
        under-replication gauge and the repair-failure counter live on
        the master (volume servers cannot know cluster-wide shard
        counts), so the aggregator folds them in through this hook."""
        m = self.metrics
        return {
            "ec_under_replicated":
                int(m.under_replicated.value()),
            "coordinator_repair_failures":
                int(sum(m.repair_failures.snapshot().values())),
        }

    def status(self) -> dict:
        admin_locked = False
        try:
            admin_locked = bool(self.admin_locked_fn())
        except Exception:
            pass
        with self._lock:
            queue = [
                {"vid": vid, **{k: v for k, v in e.items()}}
                for vid, e in sorted(
                    self._queue.items(),
                    key=lambda kv: (not kv[1].get("critical", False),
                                    -kv[1].get("deficit", 0), kv[0]))]
            doc = {
                "enabled": self.enabled,
                "paused": self.paused or admin_locked,
                "pause_reason": self.pause_reason or (
                    "admin_lock" if admin_locked else ""),
                "interval_s": self.interval_s,
                "cycles": self.cycles,
                "last_cycle_at": round(self.last_cycle_at, 3),
                "last_error": self.last_error,
                "queue": queue,
                "under_replicated":
                    int(self.metrics.under_replicated.value()),
                "repairs": {"done": self.repairs_done,
                            "failed": self.repairs_failed},
                "moves": self.moves_done,
                "move_budget": {"rate_per_s": self.move_rate,
                                "burst": self.move_burst,
                                "tokens": round(self._tokens, 2)},
                "recent": list(self.recent),
                # the raft-replicated repair records: identical on the
                # leader and a caught-up follower (the state-hash
                # equality surface tests compare)
                "replicated": {
                    "pending": {str(v): dict(r)
                                for v, r in self._replicated.items()},
                    "log": [dict(r) for r in self._replog.values()]},
            }
        return doc


def _vid_from_event(details: dict) -> Optional[int]:
    """Volume id out of a journal event's details: explicit `vid`
    (scrub verdict events), else parsed from the shard base `path`
    (shard_corrupt events carry the file prefix `.../[coll_]vid`)."""
    if "vid" in details:
        try:
            return int(details["vid"])
        except (TypeError, ValueError):
            return None
    path = str(details.get("path") or "")
    if not path:
        return None
    name = path.replace("\\", "/").rsplit("/", 1)[-1]
    tail = name.rsplit("_", 1)[-1]
    try:
        return int(tail)
    except ValueError:
        return None
