"""GF(2^8) matrix multiply on TPU via bit-plane decomposition.

The RS hot loop (reference: reedsolomon.Encode at ec_encoder.go:179 and
ReconstructData at store_ec.go:331, AVX2 PSHUFB assembly on CPU) is
``out[R,B] = M[R,K] . data[K,B]`` over GF(2^8).  TPUs have no byte-LUT
instruction, but GF(2^8) multiplication by a constant is linear over GF(2):
byte x maps to M_c . bits(x) for an 8x8 0/1 matrix M_c.  Expanding every
entry of the GF matrix into its bit-matrix turns the whole operation into a
single 0/1 matmul

    out_bits[8R, B] = (A[8R, 8K] @ data_bits[8K, B]) mod 2

which the MXU eats directly: 0/1 values are exact in bfloat16, accumulation
is exact in float32 (sums <= 8K << 2^24), and mod 2 of the popcount equals
the XOR fold.  Column layout of A is bit-plane-major: column j*K + k is input
bit j of data shard k, so data_bits is built by stacking the 8 shifted bit
planes of the byte matrix — no byte-granular shuffles on chip.

Two implementations, byte-identical to each other and to the numpy CPU
engine (differential-tested):
  - `gf_matmul_xla`: pure jnp, XLA fuses unpack+matmul+pack
  - `gf_matmul_pallas`: fused Pallas kernel tiled over B
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ec.gf256 import expand_matrix_to_bits

LANE = 128
# tile sweep on v5e (fori-loop sustained, 640MB resident): 8192=47.1GB/s,
# 16384=54.4, 32768=55.9, 65536=57.1 (best), 131072=55.8
DEFAULT_TILE_B = 65536
# the Pallas interpreter (CPU tests/dryrun) grinds on 64K tiles; use the
# small tile there — correctness paths only, never a perf surface
INTERPRET_TILE_B = 1024


def expand_matrix_bitplanes(gmat: np.ndarray) -> np.ndarray:
    """[R, K] GF matrix -> [8R, 8K] 0/1 matrix in bit-plane-major layout on
    BOTH axes: column j*K + k is input bit j of shard k, row i*R + r is
    output bit i of shard r.  This layout makes on-chip unpack (stack of 8
    shifted planes) and repack (8 contiguous row-slices) free of strided or
    3D operations."""
    r, k = gmat.shape
    abits = expand_matrix_to_bits(gmat)  # [8R, 8K], (r-major,i-minor)x(k-major,j-minor)
    a = abits.reshape(r, 8, k, 8)  # [r, i, k, j]
    return np.ascontiguousarray(a.transpose(1, 0, 3, 2).reshape(8 * r, 8 * k))


def _unpack_bitplanes(data: jnp.ndarray) -> jnp.ndarray:
    """[K, B] u8/i32 -> [8K, B] 0/1 i32, rows bit-plane-major to match the A
    layout.  Static concat of 2D shifts — no 3D intermediates (Mosaic-safe)."""
    d = data.astype(jnp.int32)
    return jnp.concatenate([(d >> j) & 1 for j in range(8)], axis=0)


def _pack_bits(bits: jnp.ndarray, r: int) -> jnp.ndarray:
    """[8R, B] 0/1 i32 (rows bit-major: row i*R + r) -> [R, B] u8.
    Contiguous static row-slices only — no 3D or strided ops (Mosaic-safe)."""
    out = bits[0:r]
    for i in range(1, 8):
        out = out | (bits[i * r : (i + 1) * r] << i)
    return out.astype(jnp.uint8)


@functools.partial(jax.jit, static_argnames=())
def gf_matmul_xla(a_planes: jnp.ndarray, data: jnp.ndarray) -> jnp.ndarray:
    """a_planes [8R, 8K] u8 (from expand_matrix_bitplanes), data [K, B] u8
    -> [R, B] u8."""
    r8 = a_planes.shape[0]
    bits = _unpack_bitplanes(data).astype(jnp.int8)
    acc = jnp.dot(a_planes.astype(jnp.int8), bits,
                  preferred_element_type=jnp.int32)
    return _pack_bits(acc & 1, r8 // 8)


def _gf_kernel(a_ref, d_ref, o_ref):
    # v5e MXU does native int8 x int8 -> int32; unpack must go through i32
    # (Mosaic has no packed u8 shifts), the dot runs in i8
    bits = _unpack_bitplanes(d_ref[:])  # [8K, TB] i32
    acc = jnp.dot(a_ref[:].astype(jnp.int8), bits.astype(jnp.int8),
                  preferred_element_type=jnp.int32)  # [8R, TB]
    o_ref[:] = _pack_bits(acc & 1, o_ref.shape[0])


@functools.partial(jax.jit, static_argnames=("tile_b", "interpret"))
def gf_matmul_pallas(a_planes: jnp.ndarray, data: jnp.ndarray,
                     tile_b: int = DEFAULT_TILE_B,
                     interpret: bool = False) -> jnp.ndarray:
    """Fused Pallas kernel: grid over B tiles; A resident in VMEM; unpack,
    one MXU matmul, mod-2, repack — no 8x bit expansion ever hits HBM."""
    r8, k8 = a_planes.shape
    k, b = data.shape
    assert k8 == 8 * k and b % tile_b == 0
    grid = (b // tile_b,)
    return pl.pallas_call(
        _gf_kernel,
        out_shape=jax.ShapeDtypeStruct((r8 // 8, b), jnp.uint8),
        grid=grid,
        in_specs=[
            pl.BlockSpec((r8, k8), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, tile_b), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((r8 // 8, tile_b), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(a_planes, data)


def _pack_u32_lanes(p: jnp.ndarray) -> jnp.ndarray:
    """[R, B] u8 -> [R, B//4] u32: 4 consecutive lane bytes per word,
    little-endian, so a host-side ``.view(uint8)`` restores the exact byte
    stream.  Fetching over a remote-TPU link costs per *element*, not per
    byte (measured 6x faster than fetching u8 directly), so the streaming
    pipeline always pulls parity through this packing.  Strided lane slices
    (not a [R, B/4, 4] reshape+bitcast) on purpose: the 3-D intermediate
    picks up a T(8,128) tiled layout with 32x padding and OOMs HBM."""
    w = p.astype(jnp.uint32)
    return (w[:, 0::4] | (w[:, 1::4] << 8) | (w[:, 2::4] << 16)
            | (w[:, 3::4] << 24))


@functools.partial(jax.jit, static_argnames=("tile_b", "interpret"))
def gf_matmul_pallas_packed(a_planes: jnp.ndarray, data: jnp.ndarray,
                            tile_b: int = DEFAULT_TILE_B,
                            interpret: bool = False) -> jnp.ndarray:
    """gf_matmul_pallas fused with the u32 transfer packing; B % 4 == 0."""
    return _pack_u32_lanes(
        gf_matmul_pallas(a_planes, data, tile_b=tile_b, interpret=interpret))


@jax.jit
def gf_matmul_xla_packed(a_planes: jnp.ndarray,
                         data: jnp.ndarray) -> jnp.ndarray:
    """gf_matmul_xla fused with the u32 transfer packing; B % 4 == 0."""
    return _pack_u32_lanes(gf_matmul_xla(a_planes, data))


def unpack_u32_host(words: np.ndarray, width: int) -> np.ndarray:
    """Host-side inverse of _pack_u32_lanes: [R, width//4] u32 -> [R, width]
    u8 (no copy beyond the fetch buffer when already little-endian)."""
    arr = np.ascontiguousarray(words)
    if arr.dtype.byteorder == ">":  # pragma: no cover - TPU hosts are LE
        arr = arr.astype("<u4")
    return arr.view(np.uint8).reshape(arr.shape[0], width)


class TpuEngine:
    """GfMatmulEngine backed by the bit-plane kernels.

    Plugs into seaweedfs_tpu.ec.codec.ReedSolomon; byte-identical to
    CpuEngine.  `mode` is "xla" | "pallas" | "auto" (pallas on real TPU,
    xla elsewhere — pallas-on-CPU uses the interpreter, which is only for
    tests)."""

    def __init__(self, mode: str = "auto", tile_b: int = 0):
        backend = jax.default_backend()
        self.on_tpu = backend not in ("cpu", "gpu")
        self.tile_b = tile_b or (DEFAULT_TILE_B if self.on_tpu
                                 else INTERPRET_TILE_B)
        if mode == "auto":
            mode = "pallas" if self.on_tpu else "xla"
        self.mode = mode
        self.name = f"tpu-{mode}"
        self._plane_cache: dict[bytes, jnp.ndarray] = {}

    def _planes(self, m: np.ndarray) -> jnp.ndarray:
        key = m.tobytes() + bytes([m.shape[0]])
        p = self._plane_cache.get(key)
        if p is None:
            p = jnp.asarray(expand_matrix_bitplanes(m))
            self._plane_cache[key] = p
        return p

    def matmul(self, m: np.ndarray, shards: np.ndarray) -> np.ndarray:
        a = self._planes(np.asarray(m, dtype=np.uint8))
        b = shards.shape[1]
        if self.mode == "pallas":
            pad = (-b) % self.tile_b
            padded = np.pad(shards, ((0, 0), (0, pad))) if pad else shards
            out = gf_matmul_pallas(a, jnp.asarray(padded), tile_b=self.tile_b,
                                   interpret=not self.on_tpu)
        else:
            pad = (-b) % LANE
            padded = np.pad(shards, ((0, 0), (0, pad))) if pad else shards
            out = gf_matmul_xla(a, jnp.asarray(padded))
        if pad:
            # device-side slice BEFORE the fetch: only the b valid
            # parity columns cross the (possibly tunneled, ~MB/s-class)
            # D2H link — the tile padding never leaves the device
            out = jax.lax.slice(out, (0, 0), (out.shape[0], b))
        return np.asarray(jax.device_get(out))
