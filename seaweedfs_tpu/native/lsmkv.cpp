// Native LSM KV engine — the C++ LevelDB-class store SURVEY §2.9 maps the
// reference's goleveldb/rocksdb dependency onto.
//
// BYTE-FORMAT COMPATIBLE with the Python engine (filer/lsm_store.py): the
// same WAL record framing (>II klen vlen | key | value), the same SSTable
// layout ([values][index: >IQI klen voff vlen + key][footer: >Q index_off]),
// the same 8-digit sequence filenames and tombstone sentinel — so a store
// directory written by either engine opens under the other, and the two are
// differential-tested against each other on identical directories.
//
// C ABI (ctypes consumer: seaweedfs_tpu/native/__init__.py):
//   lsm_open/lsm_close, lsm_put/lsm_get/lsm_delete, lsm_scan*, lsm_flush
// All operations are serialized by one mutex per DB; get/scan copy out.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <dirent.h>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <sys/stat.h>
#include <vector>

namespace {

const std::string kTombstone = std::string("\x00__tombstone__", 14);

uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | p[3];
}
uint64_t be64(const uint8_t* p) {
  return (uint64_t(be32(p)) << 32) | be32(p + 4);
}
void put32(std::string& out, uint32_t v) {
  out.push_back(char(v >> 24)); out.push_back(char(v >> 16));
  out.push_back(char(v >> 8)); out.push_back(char(v));
}
void put64(std::string& out, uint64_t v) {
  put32(out, uint32_t(v >> 32)); put32(out, uint32_t(v));
}

struct SSTable {
  std::string path;
  FILE* f = nullptr;
  std::vector<std::string> keys;
  std::vector<std::pair<uint64_t, uint32_t>> offs;  // (value_off, value_len)

  bool load(const std::string& p) {
    path = p;
    f = fopen(p.c_str(), "rb");
    if (!f) return false;
    fseek(f, 0, SEEK_END);
    long size = ftell(f);
    if (size < 8) return false;
    fseek(f, size - 8, SEEK_SET);
    uint8_t foot[8];
    if (fread(foot, 1, 8, f) != 8) return false;
    uint64_t index_off = be64(foot);
    long index_len = size - 8 - long(index_off);
    if (index_len < 0) return false;
    std::vector<uint8_t> blob(index_len);
    fseek(f, long(index_off), SEEK_SET);
    if (index_len && fread(blob.data(), 1, index_len, f) != size_t(index_len))
      return false;
    size_t pos = 0;
    while (pos + 16 <= blob.size()) {
      uint32_t klen = be32(&blob[pos]);
      uint64_t voff = be64(&blob[pos + 4]);
      uint32_t vlen = be32(&blob[pos + 12]);
      pos += 16;
      if (pos + klen > blob.size()) break;
      keys.emplace_back(reinterpret_cast<char*>(&blob[pos]), klen);
      offs.emplace_back(voff, vlen);
      pos += klen;
    }
    return true;
  }

  bool get(const std::string& key, std::string* out) const {
    auto it = std::lower_bound(keys.begin(), keys.end(), key);
    if (it == keys.end() || *it != key) return false;
    size_t i = it - keys.begin();
    out->resize(offs[i].second);
    fseek(f, long(offs[i].first), SEEK_SET);
    if (offs[i].second &&
        fread(&(*out)[0], 1, offs[i].second, f) != offs[i].second)
      return false;
    return true;
  }

  void items(std::map<std::string, std::string>* into) const {
    for (size_t i = 0; i < keys.size(); i++) {
      std::string v;
      get(keys[i], &v);
      (*into)[keys[i]] = v;
    }
  }

  ~SSTable() { if (f) fclose(f); }
};

struct DB {
  std::mutex mu;
  std::string dir;
  int memtable_limit = 8192;
  int compact_trigger = 8;
  std::map<std::string, std::string> mem;
  std::vector<std::unique_ptr<SSTable>> tables;  // oldest..newest
  long seq = 0;
  FILE* wal = nullptr;

  std::string wal_path() const { return dir + "/wal.log"; }

  void replay_wal() {
    FILE* f = fopen(wal_path().c_str(), "rb");
    if (!f) return;
    for (;;) {
      uint8_t hdr[8];
      if (fread(hdr, 1, 8, f) != 8) break;
      uint32_t klen = be32(hdr), vlen = be32(hdr + 4);
      std::string k(klen, '\0'), v(vlen, '\0');
      if (klen && fread(&k[0], 1, klen, f) != klen) break;  // torn tail
      if (vlen && fread(&v[0], 1, vlen, f) != vlen) break;
      mem[k] = v;
    }
    fclose(f);
  }

  bool open(const char* d, int mlimit, int ctrigger) {
    dir = d;
    memtable_limit = mlimit;
    compact_trigger = ctrigger;
    mkdir(d, 0755);
    std::vector<std::string> names;
    if (DIR* dp = opendir(d)) {
      while (dirent* e = readdir(dp)) {
        std::string n = e->d_name;
        if (n.size() > 4 && n.substr(n.size() - 4) == ".sst")
          names.push_back(n);
      }
      closedir(dp);
    }
    std::sort(names.begin(), names.end());
    for (auto& n : names) {
      auto t = std::make_unique<SSTable>();
      if (t->load(dir + "/" + n)) {
        long s = atol(n.substr(0, n.size() - 4).c_str());
        if (s + 1 > seq) seq = s + 1;
        tables.push_back(std::move(t));
      }
    }
    replay_wal();
    wal = fopen(wal_path().c_str(), "ab");
    return wal != nullptr;
  }

  void wal_append(const std::string& k, const std::string& v) {
    std::string rec;
    put32(rec, uint32_t(k.size()));
    put32(rec, uint32_t(v.size()));
    rec += k;
    rec += v;
    fwrite(rec.data(), 1, rec.size(), wal);
    fflush(wal);
  }

  void write_sst(const std::map<std::string, std::string>& items,
                 const std::string& path) {
    std::string tmp = path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    std::string index;
    uint64_t off = 0;
    for (auto& kv : items) {
      fwrite(kv.second.data(), 1, kv.second.size(), f);
      put32(index, uint32_t(kv.first.size()));
      put64(index, off);
      put32(index, uint32_t(kv.second.size()));
      index += kv.first;
      off += kv.second.size();
    }
    std::string foot;
    put64(foot, off);
    fwrite(index.data(), 1, index.size(), f);
    fwrite(foot.data(), 1, foot.size(), f);
    fflush(f);
    fclose(f);
    rename(tmp.c_str(), path.c_str());
  }

  std::string next_sst_path() {
    char buf[32];
    snprintf(buf, sizeof buf, "%08ld.sst", seq++);
    return dir + "/" + buf;
  }

  void flush_memtable() {  // caller holds mu
    if (mem.empty()) return;
    std::string path = next_sst_path();
    write_sst(mem, path);
    auto t = std::make_unique<SSTable>();
    t->load(path);
    tables.push_back(std::move(t));
    mem.clear();
    fclose(wal);
    wal = fopen(wal_path().c_str(), "wb");  // truncate
    if (int(tables.size()) >= compact_trigger) compact();
  }

  void compact() {  // caller holds mu
    std::map<std::string, std::string> merged;
    for (auto& t : tables) t->items(&merged);  // oldest..newest: later wins
    for (auto it = merged.begin(); it != merged.end();)
      it = (it->second == kTombstone) ? merged.erase(it) : std::next(it);
    std::string path = next_sst_path();
    write_sst(merged, path);
    for (auto& t : tables) {
      std::string old = t->path;
      t.reset();
      remove(old.c_str());
    }
    tables.clear();
    auto nt = std::make_unique<SSTable>();
    nt->load(path);
    tables.push_back(std::move(nt));
  }

  void put(const std::string& k, const std::string& v) {
    std::lock_guard<std::mutex> g(mu);
    wal_append(k, v);
    mem[k] = v;
    if (int(mem.size()) >= memtable_limit) flush_memtable();
  }

  bool get(const std::string& k, std::string* out) {
    std::lock_guard<std::mutex> g(mu);
    auto it = mem.find(k);
    if (it != mem.end()) {
      if (it->second == kTombstone) return false;
      *out = it->second;
      return true;
    }
    for (auto t = tables.rbegin(); t != tables.rend(); ++t) {  // newest first
      if ((*t)->get(k, out)) return *out != kTombstone;
    }
    return false;
  }

  void scan(const std::string& prefix,
            std::vector<std::pair<std::string, std::string>>* out) {
    std::lock_guard<std::mutex> g(mu);
    std::map<std::string, std::string> merged;
    for (auto& t : tables) {
      auto it = std::lower_bound(t->keys.begin(), t->keys.end(), prefix);
      for (size_t i = it - t->keys.begin(); i < t->keys.size(); i++) {
        if (t->keys[i].compare(0, prefix.size(), prefix) != 0) break;
        std::string v;
        t->get(t->keys[i], &v);
        merged[t->keys[i]] = v;
      }
    }
    for (auto it = mem.lower_bound(prefix); it != mem.end(); ++it) {
      if (it->first.compare(0, prefix.size(), prefix) != 0) break;
      merged[it->first] = it->second;
    }
    for (auto& kv : merged)
      if (kv.second != kTombstone) out->push_back(kv);
  }

  void close() {
    std::lock_guard<std::mutex> g(mu);
    flush_memtable();
    if (wal) { fclose(wal); wal = nullptr; }
    tables.clear();
  }
};

struct ScanIter {
  std::vector<std::pair<std::string, std::string>> items;
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* lsm_open(const char* dir, int memtable_limit, int compact_trigger) {
  auto* db = new DB();
  if (!db->open(dir, memtable_limit, compact_trigger)) {
    delete db;
    return nullptr;
  }
  return db;
}

void lsm_close(void* h) {
  auto* db = static_cast<DB*>(h);
  db->close();
  delete db;
}

void lsm_put(void* h, const uint8_t* k, int klen, const uint8_t* v,
             long vlen) {
  static_cast<DB*>(h)->put(
      std::string(reinterpret_cast<const char*>(k), klen),
      std::string(reinterpret_cast<const char*>(v), vlen));
}

void lsm_delete(void* h, const uint8_t* k, int klen) {
  static_cast<DB*>(h)->put(
      std::string(reinterpret_cast<const char*>(k), klen), kTombstone);
}

// returns value length, or -1 when absent; *out is malloc'd (lsm_free)
long lsm_get(void* h, const uint8_t* k, int klen, uint8_t** out) {
  std::string v;
  if (!static_cast<DB*>(h)->get(
          std::string(reinterpret_cast<const char*>(k), klen), &v))
    return -1;
  *out = static_cast<uint8_t*>(malloc(v.size() ? v.size() : 1));
  memcpy(*out, v.data(), v.size());
  return long(v.size());
}

void lsm_free(uint8_t* p) { free(p); }

void lsm_flush(void* h) {
  auto* db = static_cast<DB*>(h);
  std::lock_guard<std::mutex> g(db->mu);
  db->flush_memtable();
}

void* lsm_scan(void* h, const uint8_t* prefix, int plen) {
  auto* it = new ScanIter();
  static_cast<DB*>(h)->scan(
      std::string(reinterpret_cast<const char*>(prefix), plen), &it->items);
  return it;
}

int lsm_scan_next(void* hi, const uint8_t** k, int* klen, const uint8_t** v,
                  long* vlen) {
  auto* it = static_cast<ScanIter*>(hi);
  if (it->pos >= it->items.size()) return 0;
  auto& kv = it->items[it->pos++];
  *k = reinterpret_cast<const uint8_t*>(kv.first.data());
  *klen = int(kv.first.size());
  *v = reinterpret_cast<const uint8_t*>(kv.second.data());
  *vlen = long(kv.second.size());
  return 1;
}

void lsm_scan_close(void* hi) { delete static_cast<ScanIter*>(hi); }

}  // extern "C"
