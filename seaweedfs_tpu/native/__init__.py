"""ctypes loader for the native GF(2^8) SIMD library.

Builds lazily with make on first import (cached as libgf256.so); callers
fall back to the numpy engine when no C++ toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libgf256.so")
_lib = None


def _build() -> bool:
    try:
        subprocess.run(["make", "-s", "-C", _DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def load():
    """Returns the ctypes lib or None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    src = os.path.join(_DIR, "gf256_simd.cpp")
    if not os.path.exists(_LIB_PATH) or (
        os.path.exists(src) and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
    ):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.gf_init.argtypes = [ctypes.c_char_p]
    lib.gf_matmul.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_long,
    ]
    lib.gf_matmul_ptrs.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_long,
    ]
    lib.gf_has_avx2.restype = ctypes.c_int
    lib.gf_has_gfni.restype = ctypes.c_int

    from ..ec.gf256 import MUL_TABLE

    lib.gf_init(MUL_TABLE.tobytes())
    _lib = lib
    return lib


def has_avx2() -> bool:
    lib = load()
    return bool(lib and lib.gf_has_avx2())


def has_gfni() -> bool:
    lib = load()
    return bool(lib and lib.gf_has_gfni())


# --- native LSM KV (lsmkv.cpp) ----------------------------------------------

_LSM_LIB_PATH = os.path.join(_DIR, "liblsmkv.so")
_lsm_lib = None


def load_lsm():
    """Returns the lsmkv ctypes lib or None if unavailable."""
    global _lsm_lib
    if _lsm_lib is not None:
        return _lsm_lib
    src = os.path.join(_DIR, "lsmkv.cpp")
    if not os.path.exists(_LSM_LIB_PATH) or (
        os.path.exists(src)
        and os.path.getmtime(src) > os.path.getmtime(_LSM_LIB_PATH)
    ):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LSM_LIB_PATH)
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    # inputs are c_char_p: Python bytes pass by pointer with NO copy
    # (length travels separately, so embedded NULs are fine)
    lib.lsm_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.lsm_open.restype = ctypes.c_void_p
    lib.lsm_close.argtypes = [ctypes.c_void_p]
    lib.lsm_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                            ctypes.c_char_p, ctypes.c_long]
    lib.lsm_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.lsm_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                            ctypes.POINTER(u8p)]
    lib.lsm_get.restype = ctypes.c_long
    lib.lsm_free.argtypes = [u8p]
    lib.lsm_flush.argtypes = [ctypes.c_void_p]
    lib.lsm_scan.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.lsm_scan.restype = ctypes.c_void_p
    lib.lsm_scan_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_long)]
    lib.lsm_scan_next.restype = ctypes.c_int
    lib.lsm_scan_close.argtypes = [ctypes.c_void_p]
    _lsm_lib = lib
    return lib


class NativeKv:
    """Thin pythonic handle over the C++ LSM (byte-format compatible with
    filer/lsm_store.py — the two engines open each other's directories)."""

    def __init__(self, directory: str, memtable_limit: int = 8192,
                 compact_trigger: int = 8):
        lib = load_lsm()
        if lib is None:
            raise RuntimeError("native lsmkv library unavailable")
        self._lib = lib
        self._db = lib.lsm_open(directory.encode(), memtable_limit,
                                compact_trigger)
        if not self._db:
            raise OSError(f"lsm_open failed for {directory!r}")

    def put(self, key: bytes, value: bytes) -> None:
        self._lib.lsm_put(self._db, key, len(key), value, len(value))

    def get(self, key: bytes):
        out = ctypes.POINTER(ctypes.c_uint8)()
        n = self._lib.lsm_get(self._db, key, len(key), ctypes.byref(out))
        if n < 0:
            return None
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.lsm_free(out)

    def delete(self, key: bytes) -> None:
        self._lib.lsm_delete(self._db, key, len(key))

    def scan(self, prefix: bytes):
        it = self._lib.lsm_scan(self._db, prefix, len(prefix))
        try:
            kp = ctypes.POINTER(ctypes.c_uint8)()
            vp = ctypes.POINTER(ctypes.c_uint8)()
            klen = ctypes.c_int()
            vlen = ctypes.c_long()
            while self._lib.lsm_scan_next(it, ctypes.byref(kp),
                                          ctypes.byref(klen),
                                          ctypes.byref(vp),
                                          ctypes.byref(vlen)):
                yield (ctypes.string_at(kp, klen.value),
                       ctypes.string_at(vp, vlen.value))
        finally:
            self._lib.lsm_scan_close(it)

    def flush(self) -> None:
        self._lib.lsm_flush(self._db)

    def close(self) -> None:
        if self._db:
            self._lib.lsm_close(self._db)
            self._db = None


def gf_matmul_ptrs(mat: np.ndarray, in_addrs, out_addrs, n: int) -> None:
    """Row-pointer matmul: in_addrs/out_addrs are raw addresses (ints) of
    K input and R output rows of n bytes each — typically straight into
    mmap'd files, making the matmul itself the only data movement."""
    lib = load()
    if lib is None:
        raise RuntimeError("native gf256 library unavailable")
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    r, k = mat.shape
    ins = (ctypes.c_void_p * k)(*in_addrs)
    outs = (ctypes.c_void_p * r)(*out_addrs)
    lib.gf_matmul_ptrs(mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                       r, k, ins, outs, ctypes.c_long(n))


def gf_matmul(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[R, B] = mat[R, K] . data[K, B] over GF(2^8) via the native lib."""
    lib = load()
    if lib is None:
        raise RuntimeError("native gf256 library unavailable")
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    r, k = mat.shape
    n = data.shape[1]
    out = np.empty((r, n), dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.gf_matmul(mat.ctypes.data_as(u8p), r, k,
                  data.ctypes.data_as(u8p), out.ctypes.data_as(u8p),
                  ctypes.c_long(n))
    return out
