"""ctypes loader for the native GF(2^8) SIMD library.

Builds lazily with make on first import (cached as libgf256.so); callers
fall back to the numpy engine when no C++ toolchain is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libgf256.so")
_lib = None


def _build() -> bool:
    try:
        subprocess.run(["make", "-s", "-C", _DIR], check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def load():
    """Returns the ctypes lib or None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    src = os.path.join(_DIR, "gf256_simd.cpp")
    if not os.path.exists(_LIB_PATH) or (
        os.path.exists(src) and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
    ):
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.gf_init.argtypes = [ctypes.c_char_p]
    lib.gf_matmul.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_long,
    ]
    lib.gf_has_avx2.restype = ctypes.c_int

    from ..ec.gf256 import MUL_TABLE

    lib.gf_init(MUL_TABLE.tobytes())
    _lib = lib
    return lib


def has_avx2() -> bool:
    lib = load()
    return bool(lib and lib.gf_has_avx2())


def gf_matmul(mat: np.ndarray, data: np.ndarray) -> np.ndarray:
    """out[R, B] = mat[R, K] . data[K, B] over GF(2^8) via the native lib."""
    lib = load()
    if lib is None:
        raise RuntimeError("native gf256 library unavailable")
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    data = np.ascontiguousarray(data, dtype=np.uint8)
    r, k = mat.shape
    n = data.shape[1]
    out = np.empty((r, n), dtype=np.uint8)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.gf_matmul(mat.ctypes.data_as(u8p), r, k,
                  data.ctypes.data_as(u8p), out.ctypes.data_as(u8p),
                  ctypes.c_long(n))
    return out
