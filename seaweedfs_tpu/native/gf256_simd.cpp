// GF(2^8) region multiply-accumulate — the CPU default engine.
//
// The TPU-native rebuild still needs a first-class CPU path (the reference's
// default is klauspost/reedsolomon's AVX2 assembly, weed/storage/
// erasure_coding/ec_encoder.go:198).  This is the same technique: split each
// byte into nibbles and use two 16-entry PSHUFB lookup tables per constant,
// processing 32 bytes per instruction on AVX2, with a plain table fallback.
// Tables are injected from Python (seaweedfs_tpu.ec.gf256) so field/matrix
// construction lives in exactly one place.
//
// Build: see Makefile (g++ -O3, per-function target attributes; no global
// -mavx2 so the scalar path stays runnable on any x86_64).

#include <cstdint>
#include <cstring>
#include <vector>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#define HAVE_X86 1
#endif

static uint8_t MUL_LO[256][16]; // MUL_LO[c][x]  = c * x        (low nibble)
static uint8_t MUL_HI[256][16]; // MUL_HI[c][x]  = c * (x<<4)   (high nibble)
static uint8_t MUL[256][256];   // full table for the scalar path
static uint64_t AFF[256];       // GF2P8AFFINEQB matrix per constant

extern "C" void gf_init(const uint8_t *mul_table /* [256][256] */) {
    std::memcpy(MUL, mul_table, 256 * 256);
    for (int c = 0; c < 256; c++) {
        for (int x = 0; x < 16; x++) {
            MUL_LO[c][x] = mul_table[c * 256 + x];
            MUL_HI[c][x] = mul_table[c * 256 + (x << 4)];
        }
        // multiplication by c is GF(2)-linear, so it is expressible as
        // the 8x8 bit matrix GF2P8AFFINEQB applies — even though the
        // instruction's own field polynomial (0x11B) differs from this
        // field's (0x11D).  Layout (verified empirically + Intel SDM):
        // qword byte (7-r) holds the row for OUTPUT bit r; row bit j is
        // the coefficient of INPUT bit j, i.e. bit r of c*(1<<j).
        uint64_t m = 0;
        for (int r = 0; r < 8; r++) {
            uint8_t row = 0;
            for (int j = 0; j < 8; j++)
                if ((mul_table[c * 256 + (1 << j)] >> r) & 1)
                    row |= (uint8_t)(1 << j);
            m |= (uint64_t)row << (8 * (7 - r));
        }
        AFF[c] = m;
    }
}

static void mul_add_region_scalar(uint8_t c, const uint8_t *in, uint8_t *out,
                                  long n) {
    const uint8_t *row = MUL[c];
    for (long i = 0; i < n; i++)
        out[i] ^= row[in[i]];
}

#if HAVE_X86
__attribute__((target("avx2"))) static void
mul_add_region_avx2(uint8_t c, const uint8_t *in, uint8_t *out, long n) {
    const __m256i lo_tbl =
        _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)MUL_LO[c]));
    const __m256i hi_tbl =
        _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)MUL_HI[c]));
    const __m256i nib = _mm256_set1_epi8(0x0f);
    long i = 0;
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i *)(in + i));
        __m256i lo = _mm256_and_si256(v, nib);
        __m256i hi = _mm256_and_si256(_mm256_srli_epi16(v, 4), nib);
        __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(lo_tbl, lo),
                                     _mm256_shuffle_epi8(hi_tbl, hi));
        __m256i o = _mm256_loadu_si256((const __m256i *)(out + i));
        _mm256_storeu_si256((__m256i *)(out + i), _mm256_xor_si256(o, r));
    }
    if (i < n)
        mul_add_region_scalar(c, in + i, out + i, n - i);
}
#endif

#if HAVE_X86
// GFNI path: one VGF2P8AFFINEQB computes c*x for 64 bytes — ~4x the AVX2
// PSHUFB nibble-table throughput (klauspost/reedsolomon's GFNI path uses
// the same per-constant affine-matrix technique).
__attribute__((target("gfni,avx512f,avx512bw"))) static void
mul_add_region_gfni(uint8_t c, const uint8_t *in, uint8_t *out, long n) {
    const __m512i A = _mm512_set1_epi64((long long)AFF[c]);
    long i = 0;
    for (; i + 64 <= n; i += 64) {
        __m512i v = _mm512_loadu_si512((const void *)(in + i));
        __m512i r = _mm512_gf2p8affine_epi64_epi8(v, A, 0);
        __m512i o = _mm512_loadu_si512((const void *)(out + i));
        _mm512_storeu_si512((void *)(out + i), _mm512_xor_si512(o, r));
    }
    if (i < n)
        mul_add_region_scalar(c, in + i, out + i, n - i);
}
#endif

static bool has_avx2() {
#if HAVE_X86
    return __builtin_cpu_supports("avx2");
#else
    return false;
#endif
}

static bool has_gfni512() {
#if HAVE_X86
    return __builtin_cpu_supports("gfni") &&
           __builtin_cpu_supports("avx512f") &&
           __builtin_cpu_supports("avx512bw");
#else
    return false;
#endif
}

static void mul_add_region(uint8_t c, const uint8_t *in, uint8_t *out, long n) {
#if HAVE_X86
    static const bool gfni = has_gfni512();
    if (gfni) {
        mul_add_region_gfni(c, in, out, n);
        return;
    }
    static const bool avx2 = has_avx2();
    if (avx2) {
        mul_add_region_avx2(c, in, out, n);
        return;
    }
#endif
    mul_add_region_scalar(c, in, out, n);
}

static void xor_region(const uint8_t *in, uint8_t *out, long n) {
    long i = 0;
    for (; i + 8 <= n; i += 8)
        *(uint64_t *)(out + i) ^= *(const uint64_t *)(in + i);
    for (; i < n; i++)
        out[i] ^= in[i];
}

#if HAVE_X86
// Column-major GFNI kernel: one pass over the input with R zmm
// accumulators, so every input byte is LOADED ONCE and every output byte
// is STORED ONCE (never read) — versus the row-major path's R re-streams
// and read-modify-writes.  This is the shape of klauspost/reedsolomon's
// generated mulGFNI_10x4_64 kernels.  AFF matrices for the R*K constants
// are 8-byte broadcast loads, L1-hot.
template <int R>
__attribute__((target("gfni,avx512f,avx512bw"))) static void
matmul_cols_gfni(const uint64_t *aff /* [R*K] */, int k,
                 const uint8_t *const *in_rows, uint8_t *const *out_rows,
                 long n) {
    long i = 0;
    for (; i + 64 <= n; i += 64) {
        __m512i acc[R];
        for (int r = 0; r < R; r++)
            acc[r] = _mm512_setzero_si512();
        for (int j = 0; j < k; j++) {
            __m512i v = _mm512_loadu_si512((const void *)(in_rows[j] + i));
            for (int r = 0; r < R; r++)
                acc[r] = _mm512_xor_si512(
                    acc[r], _mm512_gf2p8affine_epi64_epi8(
                                v,
                                _mm512_set1_epi64((long long)aff[r * k + j]),
                                0));
        }
        for (int r = 0; r < R; r++)
            _mm512_storeu_si512((void *)(out_rows[r] + i), acc[r]);
    }
    // caller guarantees n % 64 == 0 (the scalar tail runs in matmul_core)
}
#endif

// Row-major fallback (AVX2 PSHUFB / scalar): tiled over n so a K-row input
// block stays cache-resident across all R output rows.
static void matmul_rows_tiled(const uint8_t *mat, int rows, int k,
                              const uint8_t *const *in_rows,
                              uint8_t *const *out_rows, long n) {
    const long TILE = 1 << 14; // 16KB: R out tiles stay L1-resident
    for (long off = 0; off < n; off += TILE) {
        long len = (n - off < TILE) ? (n - off) : TILE;
        for (int r = 0; r < rows; r++) {
            uint8_t *orow = out_rows[r] + off;
            std::memset(orow, 0, len);
            for (int j = 0; j < k; j++) {
                uint8_t c = mat[r * k + j];
                const uint8_t *irow = in_rows[j] + off;
                if (c == 0)
                    continue;
                if (c == 1)
                    xor_region(irow, orow, len);
                else
                    mul_add_region(c, irow, orow, len);
            }
        }
    }
}

// Shared core on row pointers; picks the GFNI column-major kernel when the
// CPU has it and R <= 8 (register budget), else the row-tiled path.
static void matmul_core(const uint8_t *mat, int rows, int k,
                        const uint8_t *const *in_rows,
                        uint8_t *const *out_rows, long n) {
#if HAVE_X86
    static const bool gfni = has_gfni512();
    if (gfni && rows >= 1 && rows <= 8 && k <= 32) {
        uint64_t aff[8 * 32];
        for (int r = 0; r < rows; r++)
            for (int j = 0; j < k; j++)
                aff[r * k + j] = AFF[mat[r * k + j]];
        long main_n = n & ~63L; // 64B-aligned body
        if (main_n) {
            switch (rows) {
            case 1: matmul_cols_gfni<1>(aff, k, in_rows, out_rows, main_n); break;
            case 2: matmul_cols_gfni<2>(aff, k, in_rows, out_rows, main_n); break;
            case 3: matmul_cols_gfni<3>(aff, k, in_rows, out_rows, main_n); break;
            case 4: matmul_cols_gfni<4>(aff, k, in_rows, out_rows, main_n); break;
            case 5: matmul_cols_gfni<5>(aff, k, in_rows, out_rows, main_n); break;
            case 6: matmul_cols_gfni<6>(aff, k, in_rows, out_rows, main_n); break;
            case 7: matmul_cols_gfni<7>(aff, k, in_rows, out_rows, main_n); break;
            case 8: matmul_cols_gfni<8>(aff, k, in_rows, out_rows, main_n); break;
            }
        }
        if (main_n < n) { // scalar tail
            for (int r = 0; r < rows; r++) {
                uint8_t *orow = out_rows[r] + main_n;
                std::memset(orow, 0, n - main_n);
                for (int j = 0; j < k; j++) {
                    uint8_t c = mat[r * k + j];
                    if (c)
                        mul_add_region_scalar(c, in_rows[j] + main_n, orow,
                                              n - main_n);
                }
            }
        }
        return;
    }
#endif
    matmul_rows_tiled(mat, rows, k, in_rows, out_rows, n);
}

// out[R, n] = mat[R, K] . data[K, n] over GF(2^8).
// data rows are contiguous [K][n]; out rows [R][n] are overwritten.
extern "C" void gf_matmul(const uint8_t *mat, int rows, int k,
                          const uint8_t *data, uint8_t *out, long n) {
    std::vector<const uint8_t *> in_rows(k);
    std::vector<uint8_t *> out_rows(rows);
    for (int j = 0; j < k; j++)
        in_rows[j] = data + (long)j * n;
    for (int r = 0; r < rows; r++)
        out_rows[r] = out + (long)r * n;
    matmul_core(mat, rows, k, in_rows.data(), out_rows.data(), n);
}

// out_rows[r][0..n) = mat[R, K] . in_rows[K][0..n) over GF(2^8), with every
// row an independent pointer.  This is the zero-copy entry point: callers
// hand pointers straight into mmap'd shard/volume files, so the matmul IS
// the read and the write — no staging buffers, no user-space copies.
// Dispatches like gf_matmul: single-pass GFNI column-major kernel when the
// CPU has it (R<=8), else the 16KB row-tiled AVX2/scalar fallback.
extern "C" void gf_matmul_ptrs(const uint8_t *mat, int rows, int k,
                               const uint8_t *const *in_rows,
                               uint8_t *const *out_rows, long n) {
    matmul_core(mat, rows, k, in_rows, out_rows, n);
}

extern "C" int gf_has_avx2() { return has_avx2() ? 1 : 0; }

extern "C" int gf_has_gfni() { return has_gfni512() ? 1 : 0; }
