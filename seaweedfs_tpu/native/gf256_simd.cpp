// GF(2^8) region multiply-accumulate — the CPU default engine.
//
// The TPU-native rebuild still needs a first-class CPU path (the reference's
// default is klauspost/reedsolomon's AVX2 assembly, weed/storage/
// erasure_coding/ec_encoder.go:198).  This is the same technique: split each
// byte into nibbles and use two 16-entry PSHUFB lookup tables per constant,
// processing 32 bytes per instruction on AVX2, with a plain table fallback.
// Tables are injected from Python (seaweedfs_tpu.ec.gf256) so field/matrix
// construction lives in exactly one place.
//
// Build: see Makefile (g++ -O3, per-function target attributes; no global
// -mavx2 so the scalar path stays runnable on any x86_64).

#include <cstdint>
#include <cstring>

#if defined(__x86_64__) || defined(_M_X64)
#include <immintrin.h>
#define HAVE_X86 1
#endif

static uint8_t MUL_LO[256][16]; // MUL_LO[c][x]  = c * x        (low nibble)
static uint8_t MUL_HI[256][16]; // MUL_HI[c][x]  = c * (x<<4)   (high nibble)
static uint8_t MUL[256][256];   // full table for the scalar path

extern "C" void gf_init(const uint8_t *mul_table /* [256][256] */) {
    std::memcpy(MUL, mul_table, 256 * 256);
    for (int c = 0; c < 256; c++) {
        for (int x = 0; x < 16; x++) {
            MUL_LO[c][x] = mul_table[c * 256 + x];
            MUL_HI[c][x] = mul_table[c * 256 + (x << 4)];
        }
    }
}

static void mul_add_region_scalar(uint8_t c, const uint8_t *in, uint8_t *out,
                                  long n) {
    const uint8_t *row = MUL[c];
    for (long i = 0; i < n; i++)
        out[i] ^= row[in[i]];
}

#if HAVE_X86
__attribute__((target("avx2"))) static void
mul_add_region_avx2(uint8_t c, const uint8_t *in, uint8_t *out, long n) {
    const __m256i lo_tbl =
        _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)MUL_LO[c]));
    const __m256i hi_tbl =
        _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i *)MUL_HI[c]));
    const __m256i nib = _mm256_set1_epi8(0x0f);
    long i = 0;
    for (; i + 32 <= n; i += 32) {
        __m256i v = _mm256_loadu_si256((const __m256i *)(in + i));
        __m256i lo = _mm256_and_si256(v, nib);
        __m256i hi = _mm256_and_si256(_mm256_srli_epi16(v, 4), nib);
        __m256i r = _mm256_xor_si256(_mm256_shuffle_epi8(lo_tbl, lo),
                                     _mm256_shuffle_epi8(hi_tbl, hi));
        __m256i o = _mm256_loadu_si256((const __m256i *)(out + i));
        _mm256_storeu_si256((__m256i *)(out + i), _mm256_xor_si256(o, r));
    }
    if (i < n)
        mul_add_region_scalar(c, in + i, out + i, n - i);
}
#endif

static bool has_avx2() {
#if HAVE_X86
    return __builtin_cpu_supports("avx2");
#else
    return false;
#endif
}

static void mul_add_region(uint8_t c, const uint8_t *in, uint8_t *out, long n) {
#if HAVE_X86
    static const bool avx2 = has_avx2();
    if (avx2) {
        mul_add_region_avx2(c, in, out, n);
        return;
    }
#endif
    mul_add_region_scalar(c, in, out, n);
}

static void xor_region(const uint8_t *in, uint8_t *out, long n) {
    long i = 0;
    for (; i + 8 <= n; i += 8)
        *(uint64_t *)(out + i) ^= *(const uint64_t *)(in + i);
    for (; i < n; i++)
        out[i] ^= in[i];
}

// out[R, n] = mat[R, K] . data[K, n] over GF(2^8).
// data rows are contiguous [K][n]; out rows [R][n] are overwritten.
// Tiled over n so a K-row input block stays L2-resident across all R output
// rows instead of re-streaming from DRAM per row.
extern "C" void gf_matmul(const uint8_t *mat, int rows, int k,
                          const uint8_t *data, uint8_t *out, long n) {
    const long TILE = 1 << 16; // 64KB per row-chunk; K*TILE fits in L2
    for (long off = 0; off < n; off += TILE) {
        long len = (n - off < TILE) ? (n - off) : TILE;
        for (int r = 0; r < rows; r++) {
            uint8_t *orow = out + (long)r * n + off;
            std::memset(orow, 0, len);
            for (int j = 0; j < k; j++) {
                uint8_t c = mat[r * k + j];
                const uint8_t *irow = data + (long)j * n + off;
                if (c == 0)
                    continue;
                if (c == 1)
                    xor_region(irow, orow, len);
                else
                    mul_add_region(c, irow, orow, len);
            }
        }
    }
}

extern "C" int gf_has_avx2() { return has_avx2() ? 1 : 0; }
