// Native volume-server data plane: GIL-free framed-TCP needle IO.
//
// The hot loop of the rebuild's volume server (the analog of the
// reference's volume_server_tcp_handlers_write.go experiment, made the
// production fast path).  A thread-per-connection TCP server speaks the
// framing of utils/framing.py:
//
//   request:  op(1) | key_len(u16 BE) | key utf8 | body_len(u32 BE) | body
//   response: status(1, 0=ok)         | payload_len(u32 BE) | payload
//
// Ops: 'W' append needle (key=fid, body=data) -> u32 stored size
//      'R' read needle   (key=fid)            -> needle data
//      'D' delete        (key=fid)            -> u32 freed size
//
// Byte formats are IDENTICAL to the Python engine (and the reference):
//   needle v3 record (needle_read_write.go):
//     cookie u32 BE | id u64 BE | size i32 BE
//     [data_size u32 BE | data | flags u8]           when data_size > 0
//     masked_crc32c(data) u32 BE | append_at_ns u64 BE
//     padding 1..8: (size BE4 ++ zeros)[0:pad]
//   idx entry (idx/walk.go): key u64 BE | offset/8 u32 BE | size i32 BE
//
// Coherence contract with the Python Store: while a volume is registered
// here, this plane is the ONLY writer/reader of its needles (the Python
// HTTP handlers route through dp_write/dp_read/dp_delete via ctypes);
// maintenance (vacuum, EC, copy) first dp_remove_volume()s it, works on
// quiesced files, and re-registers, which rebuilds the map from the idx.
//
// Build: g++ -O3 -fPIC -shared -std=c++17 (links -lz for gzip'd blobs).

#include <algorithm>
#include <arpa/inet.h>
#include <memory>
#include <cerrno>
#include <chrono>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <fcntl.h>
#include <mutex>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/socket.h>
#include <sys/stat.h>
#include <atomic>
#include <condition_variable>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <unordered_set>
#include <vector>
#include <zlib.h>

namespace {

// ---------------------------------------------------------------- crc32c
#if defined(__x86_64__)
#include <cpuid.h>
static bool has_sse42() {
    unsigned a, b, c, d;
    if (!__get_cpuid(1, &a, &b, &c, &d)) return false;
    return (c & (1u << 20)) != 0;  // SSE4.2
}
__attribute__((target("sse4.2")))
static uint32_t crc32c_hw(uint32_t crc, const uint8_t* p, size_t n) {
    crc = ~crc;
    while (n >= 8) {
        uint64_t v;
        memcpy(&v, p, 8);
        crc = (uint32_t)__builtin_ia32_crc32di(crc, v);
        p += 8; n -= 8;
    }
    while (n--) crc = __builtin_ia32_crc32qi(crc, *p++);
    return ~crc;
}
#endif

static uint32_t crc_table[256];
static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
        crc_table[i] = c;
    }
}
static uint32_t crc32c_sw(uint32_t crc, const uint8_t* p, size_t n) {
    crc = ~crc;
    while (n--) crc = crc_table[(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    return ~crc;
}

static bool g_hw_crc = false;
static uint32_t crc32c(const uint8_t* p, size_t n) {
#if defined(__x86_64__)
    if (g_hw_crc) return crc32c_hw(0, p, n);
#endif
    return crc32c_sw(0, p, n);
}

static uint32_t masked_crc(uint32_t c) {
    // crc.go:24-26: rotr15(c) + 0xa282ead8
    uint32_t rot = (c >> 15) | (c << 17);
    return rot + 0xA282EAD8u;
}

// ------------------------------------------------------------- BE helpers
static void put_u32(uint8_t* p, uint32_t v) {
    p[0] = v >> 24; p[1] = v >> 16; p[2] = v >> 8; p[3] = v;
}
static void put_u64(uint8_t* p, uint64_t v) {
    for (int i = 7; i >= 0; i--) { p[i] = (uint8_t)v; v >>= 8; }
}
static uint16_t get_u16(const uint8_t* p) {
    return ((uint16_t)p[0] << 8) | p[1];
}
static uint32_t get_u32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | p[3];
}
static uint64_t get_u64(const uint8_t* p) {
    uint64_t v = 0;
    for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
    return v;
}

// error codes surfaced to Python / the wire
enum {
    DP_OK = 0, DP_NOT_FOUND = -2, DP_COOKIE = -3, DP_DELETED = -4,
    DP_READONLY = -5, DP_NO_VOLUME = -6, DP_IO = -7, DP_CRC = -8,
    DP_BAD_REQ = -9, DP_FULL = -10, DP_TCP_FORBIDDEN = -11,
};

// ------------------------------------------------------------- volume
struct NeedleVal { uint64_t offset; int32_t size; };

struct Volume {
    int dat_fd = -1;
    int idx_fd = -1;
    uint64_t dat_size = 0;   // append offset
    uint64_t idx_size = 0;   // idx append offset (rollback anchor)
    uint64_t max_key = 0;    // highest needle id seen (heartbeat reseed)
    uint64_t deleted_bytes = 0;  // stored sizes of dead needles (vacuum)
    bool read_only = false;
    // W/D frames arriving over TCP are rejected unless this is set: the
    // TCP plane has no IP-whitelist slot and no replication fan-out, so
    // the Python side only enables it for replication-000 volumes on
    // servers with no whitelist configured.  Local C-API calls
    // (dp_write/dp_append/dp_delete, the HTTP plane's funnel) are never
    // gated by it — the HTTP layer already enforced whitelist+fan-out.
    bool tcp_writable = true;
    bool retired = false;    // set under write_mu by dp_remove_volume
    std::unordered_map<uint64_t, NeedleVal> map;
    std::mutex write_mu;     // serializes append (.dat + .idx + map)
    std::mutex map_mu;       // guards map for lock-free-ish readers
    // group-commit fsync (volume_write.go's batch worker analog): many
    // concurrent durable writers share one fsync pass
    std::mutex sync_mu;
    std::condition_variable sync_cv;
    uint64_t sync_pending = 0;   // highest requested generation
    uint64_t sync_done = 0;      // highest completed generation
    uint64_t sync_passes = 0;    // actual fsync() pairs performed
    bool sync_failed = false;    // PERMANENTLY sticky: a failed fsync
                                 // drops dirty pages (appends from ANY
                                 // generation) and clears the kernel
                                 // error, so no later pass can prove
                                 // durability — every durable write on
                                 // this registration fails until the
                                 // operator re-registers the volume
    bool sync_running = false;

    ~Volume() {
        if (dat_fd >= 0) close(dat_fd);
        if (idx_fd >= 0) close(idx_fd);
    }
};

using VolumeRef = std::shared_ptr<Volume>;

constexpr int32_t TOMBSTONE = -1;
constexpr uint8_t FLAG_IS_COMPRESSED = 0x01;

static const char* dp_strerror(int code) {
    switch (code) {
        case DP_NOT_FOUND: return "not found";
        case DP_COOKIE:    return "cookie mismatch";
        case DP_DELETED:   return "already deleted";
        case DP_READONLY:  return "volume is read only";
        case DP_NO_VOLUME: return "volume not on native plane";
        case DP_IO:        return "io error";
        case DP_CRC:       return "crc mismatch";
        case DP_FULL:      return "volume size limit exceeded";
        case DP_TCP_FORBIDDEN:
            return "tcp writes not allowed for this volume";
        default:           return "bad request";
    }
}

struct Server {
    int listen_fd = -1;
    int port = 0;
    std::thread accept_thread;
    std::mutex vol_mu;
    std::unordered_map<uint32_t, VolumeRef> volumes;
    struct ConnThread {
        std::thread t;
        std::shared_ptr<std::atomic<bool>> done;
    };
    std::mutex conn_mu;
    std::unordered_set<int> conns;
    std::vector<ConnThread> conn_threads;  // reaped on accept + dp_stop
    volatile bool stopping = false;
};

// Returns an owning reference: in-flight ops keep the Volume (and its
// fds) alive across a concurrent dp_remove_volume; writers additionally
// observe `retired` under write_mu so a quiesced volume takes no more
// appends after the remove returns.
static VolumeRef find_volume(Server* s, uint32_t vid) {
    std::lock_guard<std::mutex> g(s->vol_mu);
    auto it = s->volumes.find(vid);
    return it == s->volumes.end() ? nullptr : it->second;
}

// Group-commit durable sync: every caller whose appends happened before
// its generation is covered by ONE fsync pass; appends are NOT blocked
// while the pass runs (fsync happens outside write_mu).
static int vol_group_sync(Volume* v) {
    std::unique_lock<std::mutex> lk(v->sync_mu);
    if (v->sync_failed) return DP_IO;
    uint64_t my_gen = ++v->sync_pending;
    for (;;) {
        if (v->sync_failed) return DP_IO;
        if (v->sync_done >= my_gen) return DP_OK;
        if (!v->sync_running) {
            v->sync_running = true;
            uint64_t target = v->sync_pending;
            lk.unlock();
            int rc = DP_OK;
            if (fsync(v->dat_fd) != 0 || fsync(v->idx_fd) != 0)
                rc = DP_IO;
            lk.lock();
            v->sync_running = false;
            v->sync_done = target;
            if (rc != DP_OK)
                v->sync_failed = true;
            v->sync_passes++;
            v->sync_cv.notify_all();
            continue;  // loop observes sync_done / sync_failed
        }
        v->sync_cv.wait(lk);
    }
}

// needle record size on disk for a stored `size` (types.go GetActualSize)
static uint64_t actual_size(int32_t size) {
    uint64_t used = 16 + (uint64_t)size + 4 + 8;      // header+body+crc+ts
    uint64_t pad = 8 - (used % 8);                    // 1..8, never 0
    return used + pad;
}

// Append one 16-byte idx entry (caller holds write_mu).  On a failed or
// short write (ENOSPC) roll BOTH files back: a torn, 16-misaligned idx
// tail would misparse every later entry on the next replay, and the .dat
// record at `dat_off` would have no idx entry and resurface as a torn
// tail — mirror the .dat rollback the write paths already do.
static int idx_append(Volume* v, const uint8_t* ie, uint64_t dat_off) {
    if (write(v->idx_fd, ie, 16) != 16) {
        (void)!ftruncate(v->idx_fd, (off_t)v->idx_size);
        (void)!ftruncate(v->dat_fd, (off_t)dat_off);
        return DP_IO;
    }
    v->idx_size += 16;
    return DP_OK;
}

// ------------------------------------------------------------- ops
constexpr uint64_t MAX_VOLUME_BYTES = 8ull * 0xFFFFFFFFull;  // u32 off/8

static int vol_write(Volume* v, uint64_t id, uint32_t cookie,
                     const uint8_t* data, uint32_t len, uint32_t* out_size) {
    if (v->read_only) return DP_READONLY;
    std::lock_guard<std::mutex> g(v->write_mu);
    if (v->retired) return DP_NO_VOLUME;
    // cookie check against an existing live needle (volume_write.go)
    {
        std::lock_guard<std::mutex> m(v->map_mu);
        auto it = v->map.find(id);
        if (it != v->map.end() && it->second.size >= 0) {
            uint8_t hdr[4];
            if (pread(v->dat_fd, hdr, 4, it->second.offset) == 4 &&
                get_u32(hdr) != cookie)
                return DP_COOKIE;
        }
    }
    int32_t size = len > 0 ? (int32_t)(4 + len + 1) : 0;
    uint64_t rec_len = actual_size(size);
    if (v->dat_size + rec_len > MAX_VOLUME_BYTES)
        return DP_FULL;  // idx offsets are u32 of off/8 (offset_4bytes.go)
    std::vector<uint8_t> rec(rec_len);
    uint8_t* p = rec.data();
    put_u32(p, cookie); put_u64(p + 4, id); put_u32(p + 12, (uint32_t)size);
    size_t i = 16;
    if (len > 0) {
        put_u32(p + i, len); i += 4;
        memcpy(p + i, data, len); i += len;
        p[i++] = 0;  // flags
    }
    uint32_t crc = masked_crc(crc32c(data, len));
    put_u32(p + i, crc); i += 4;
    uint64_t now_ns = (uint64_t)std::chrono::duration_cast<
        std::chrono::nanoseconds>(std::chrono::system_clock::now()
                                      .time_since_epoch()).count();
    put_u64(p + i, now_ns); i += 8;
    // padding quirk (needle_read_write.go): size BE4 then zeros
    uint8_t padsrc[12] = {0};
    put_u32(padsrc, (uint32_t)size);
    size_t pad = rec_len - i;
    memcpy(p + i, padsrc, pad);

    uint64_t off = v->dat_size;
    if (pwrite(v->dat_fd, rec.data(), rec_len, off) != (ssize_t)rec_len) {
        (void)!ftruncate(v->dat_fd, off);
        return DP_IO;
    }
    uint8_t ie[16];
    put_u64(ie, id); put_u32(ie + 8, (uint32_t)(off / 8));
    put_u32(ie + 12, (uint32_t)size);
    if (idx_append(v, ie, off) != DP_OK) return DP_IO;
    v->dat_size = off + rec_len;
    if (id > v->max_key) v->max_key = id;
    {
        std::lock_guard<std::mutex> m(v->map_mu);
        auto it = v->map.find(id);
        if (it != v->map.end() && it->second.size >= 0)
            v->deleted_bytes += (uint64_t)it->second.size;  // overwritten
        v->map[id] = NeedleVal{off, size};
    }
    *out_size = (uint32_t)size;
    return DP_OK;
}

static int vol_delete(Volume* v, uint64_t id, uint32_t cookie,
                      uint32_t* out_size) {
    if (v->read_only) return DP_READONLY;
    std::lock_guard<std::mutex> g(v->write_mu);
    if (v->retired) return DP_NO_VOLUME;
    NeedleVal nv;
    {
        std::lock_guard<std::mutex> m(v->map_mu);
        auto it = v->map.find(id);
        if (it == v->map.end() || it->second.size < 0) {
            *out_size = 0;
            return DP_OK;  // double delete returns 0 (volume_write.go)
        }
        nv = it->second;
    }
    // append a zero-data tombstone needle, then log (key, off, -1)
    uint64_t rec_len = actual_size(0);
    std::vector<uint8_t> rec(rec_len);
    uint8_t* p = rec.data();
    put_u32(p, cookie); put_u64(p + 4, id); put_u32(p + 12, 0);
    uint32_t crc = masked_crc(crc32c(nullptr, 0));
    put_u32(p + 16, crc);
    uint64_t now_ns = (uint64_t)std::chrono::duration_cast<
        std::chrono::nanoseconds>(std::chrono::system_clock::now()
                                      .time_since_epoch()).count();
    put_u64(p + 20, now_ns);
    memset(p + 28, 0, rec_len - 28);  // pad: size(0) BE4 -> zeros
    uint64_t off = v->dat_size;
    if (pwrite(v->dat_fd, rec.data(), rec_len, off) != (ssize_t)rec_len) {
        (void)!ftruncate(v->dat_fd, off);
        return DP_IO;
    }
    uint8_t ie[16];
    put_u64(ie, id); put_u32(ie + 8, (uint32_t)(off / 8));
    put_u32(ie + 12, (uint32_t)TOMBSTONE);
    if (idx_append(v, ie, off) != DP_OK) return DP_IO;
    v->dat_size = off + rec_len;
    v->deleted_bytes += (uint64_t)nv.size;
    {
        std::lock_guard<std::mutex> m(v->map_mu);
        v->map.erase(id);
    }
    *out_size = (uint32_t)nv.size;
    return DP_OK;
}

// Parse a v3 record's data payload out of `rec` (without header), for a
// stored size and known data layout (needle.py _parse_body_v2 subset: we
// only need data + flags; name/mime/ttl ride behind and are skipped).
static int extract_data(const uint8_t* body, int32_t size,
                        std::vector<uint8_t>* out, uint8_t* flags) {
    if (size == 0) { out->clear(); *flags = 0; return DP_OK; }
    if (size < 5) return DP_IO;
    uint32_t dsize = get_u32(body);
    if ((int64_t)dsize + 5 > size) return DP_IO;
    out->assign(body + 4, body + 4 + dsize);
    *flags = body[4 + dsize];
    return DP_OK;
}

static int vol_read(Volume* v, uint64_t id, uint32_t cookie,
                    std::vector<uint8_t>* out) {
    NeedleVal nv;
    {
        std::lock_guard<std::mutex> m(v->map_mu);
        auto it = v->map.find(id);
        if (it == v->map.end()) return DP_NOT_FOUND;
        nv = it->second;
        if (nv.size < 0) return DP_DELETED;
    }
    uint64_t rec_len = actual_size(nv.size);
    std::vector<uint8_t> rec(rec_len);
    ssize_t got = pread(v->dat_fd, rec.data(), rec_len, nv.offset);
    if (got != (ssize_t)rec_len) return DP_IO;
    const uint8_t* p = rec.data();
    if (get_u32(p) != cookie) return DP_COOKIE;
    if (get_u64(p + 4) != id) return DP_IO;
    int32_t size = (int32_t)get_u32(p + 12);
    if (size != nv.size) return DP_IO;
    uint8_t flags = 0;
    std::vector<uint8_t> data;
    int rc = extract_data(p + 16, size, &data, &flags);
    if (rc != DP_OK) return rc;
    // integrity: stored masked crc must match recomputed (needle.py)
    uint32_t stored = get_u32(p + 16 + size);
    if (stored != masked_crc(crc32c(data.data(), data.size())))
        return DP_CRC;
    if (flags & FLAG_IS_COMPRESSED) {
        // HTTP-written compressible objects are stored gzipped; the frame
        // protocol has no encoding slot, so serve the original bytes
        std::vector<uint8_t> plain(data.size() * 4 + 64);
        z_stream zs{};
        if (inflateInit2(&zs, 16 + MAX_WBITS) != Z_OK) return DP_IO;
        zs.next_in = data.data();
        zs.avail_in = (uInt)data.size();
        size_t produced = 0;
        int zrc;
        do {
            if (produced == plain.size()) plain.resize(plain.size() * 2);
            zs.next_out = plain.data() + produced;
            zs.avail_out = (uInt)(plain.size() - produced);
            zrc = inflate(&zs, Z_NO_FLUSH);
            produced = plain.size() - zs.avail_out;
        } while (zrc == Z_OK);
        inflateEnd(&zs);
        if (zrc != Z_STREAM_END) return DP_IO;
        plain.resize(produced);
        *out = std::move(plain);
    } else {
        *out = std::move(data);
    }
    return DP_OK;
}

// ------------------------------------------------------------- fid parse
static bool parse_fid(const std::string& fid, uint32_t* vid, uint64_t* id,
                      uint32_t* cookie) {
    size_t comma = fid.find(',');
    if (comma == std::string::npos || comma == 0) return false;
    errno = 0;
    *vid = (uint32_t)strtoul(fid.c_str(), nullptr, 10);
    std::string hexs = fid.substr(comma + 1);
    if (hexs.size() <= 8) return false;
    if (hexs.size() % 2) hexs = "0" + hexs;
    size_t nb = hexs.size() / 2;
    if (nb > 12) return false;
    uint8_t raw[12] = {0};
    for (size_t i = 0; i < nb; i++) {
        auto nib = [](char c) -> int {
            if (c >= '0' && c <= '9') return c - '0';
            if (c >= 'a' && c <= 'f') return c - 'a' + 10;
            if (c >= 'A' && c <= 'F') return c - 'A' + 10;
            return -1;
        };
        int hi = nib(hexs[2 * i]), lo = nib(hexs[2 * i + 1]);
        if (hi < 0 || lo < 0) return false;
        raw[12 - nb + i] = (uint8_t)((hi << 4) | lo);
    }
    *id = get_u64(raw);
    *cookie = get_u32(raw + 8);
    return true;
}

// ------------------------------------------------------------- framing
static bool recv_exact(int fd, uint8_t* buf, size_t n) {
    while (n) {
        ssize_t got = recv(fd, buf, n, 0);
        if (got <= 0) return false;
        buf += got; n -= (size_t)got;
    }
    return true;
}

static bool send_all(int fd, const uint8_t* buf, size_t n) {
    while (n) {
        ssize_t put = send(fd, buf, n, MSG_NOSIGNAL);
        if (put <= 0) return false;
        buf += put; n -= (size_t)put;
    }
    return true;
}

static bool send_frame(int fd, uint8_t status, const uint8_t* payload,
                       uint32_t n) {
    std::vector<uint8_t> hdr(5);
    hdr[0] = status;
    put_u32(hdr.data() + 1, n);
    if (!send_all(fd, hdr.data(), 5)) return false;
    return n == 0 || send_all(fd, payload, n);
}

static void serve_conn(Server* s, int fd,
                       std::shared_ptr<std::atomic<bool>> done) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    std::vector<uint8_t> body;
    for (;;) {
        uint8_t op;
        if (!recv_exact(fd, &op, 1)) break;
        uint8_t klen_b[2];
        if (!recv_exact(fd, klen_b, 2)) break;
        uint16_t klen = get_u16(klen_b);
        std::string key(klen, '\0');
        if (klen && !recv_exact(fd, (uint8_t*)key.data(), klen)) break;
        uint8_t blen_b[4];
        if (!recv_exact(fd, blen_b, 4)) break;
        uint32_t blen = get_u32(blen_b);
        if (blen > (1u << 30)) break;  // 1GB sanity cap
        body.resize(blen);
        if (blen && !recv_exact(fd, body.data(), blen)) break;

        uint32_t vid, cookie; uint64_t id;
        int rc = DP_BAD_REQ;
        uint32_t out_size = 0;
        std::vector<uint8_t> out;
        if (parse_fid(key, &vid, &id, &cookie)) {
            VolumeRef v = find_volume(s, vid);
            if (v == nullptr) {
                rc = DP_NO_VOLUME;
            } else if (op == 'W') {
                rc = v->tcp_writable
                         ? vol_write(v.get(), id, cookie, body.data(), blen,
                                     &out_size)
                         : DP_TCP_FORBIDDEN;
            } else if (op == 'R') {
                rc = vol_read(v.get(), id, cookie, &out);
            } else if (op == 'D') {
                rc = v->tcp_writable
                         ? vol_delete(v.get(), id, cookie, &out_size)
                         : DP_TCP_FORBIDDEN;
            }
        }
        bool ok;
        if (rc == DP_OK && op == 'R') {
            ok = send_frame(fd, 0, out.data(), (uint32_t)out.size());
        } else if (rc == DP_OK) {
            uint8_t sz[4];
            put_u32(sz, out_size);
            ok = send_frame(fd, 0, sz, 4);
        } else {
            const char* msg = dp_strerror(rc);
            ok = send_frame(fd, 1, (const uint8_t*)msg,
                            (uint32_t)strlen(msg));
        }
        if (!ok) break;
    }
    close(fd);
    {
        std::lock_guard<std::mutex> g(s->conn_mu);
        s->conns.erase(fd);
    }
    done->store(true);
}

static void accept_loop(Server* s) {
    for (;;) {
        int fd = accept(s->listen_fd, nullptr, nullptr);
        if (fd < 0) {
            if (s->stopping) return;
            if (errno == EINTR) continue;
            return;
        }
        {
            std::lock_guard<std::mutex> g(s->conn_mu);
            // reap finished connection threads so the registry stays
            // bounded by the number of LIVE connections
            for (auto it = s->conn_threads.begin();
                 it != s->conn_threads.end();) {
                if (it->done->load()) {
                    it->t.join();
                    it = s->conn_threads.erase(it);
                } else {
                    ++it;
                }
            }
            s->conns.insert(fd);
            auto done = std::make_shared<std::atomic<bool>>(false);
            s->conn_threads.push_back(
                Server::ConnThread{std::thread(serve_conn, s, fd, done),
                                   done});
        }
    }
}

}  // namespace

// ================================================================ C API
extern "C" {

void* dp_start(const char* host, int port) {
    static std::once_flag once;
    std::call_once(once, [] {
        crc_init();
#if defined(__x86_64__)
        g_hw_crc = has_sse42();
#endif
    });
    if (port < 0) {
        // engine-only mode: no TCP listener at all (whitelist-guarded
        // servers — the plane has no whitelist slot, and the Python TCP
        // plane likewise refuses non-whitelisted connections outright,
        // reads included).  The C API keeps serving the HTTP funnel.
        Server* s = new Server();
        s->listen_fd = -1;
        s->port = 0;
        return s;
    }
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    addr.sin_addr.s_addr = host && *host ? inet_addr(host) : INADDR_ANY;
    if (bind(fd, (sockaddr*)&addr, sizeof addr) != 0 ||
        listen(fd, 128) != 0) {
        close(fd);
        return nullptr;
    }
    socklen_t alen = sizeof addr;
    getsockname(fd, (sockaddr*)&addr, &alen);
    Server* s = new Server();
    s->listen_fd = fd;
    s->port = ntohs(addr.sin_port);
    s->accept_thread = std::thread(accept_loop, s);
    return s;
}

int dp_port(void* h) { return ((Server*)h)->port; }

int dp_add_volume(void* h, unsigned vid, const char* dat_path,
                  const char* idx_path, int read_only, int tcp_writable) {
    Server* s = (Server*)h;
    auto v = std::make_shared<Volume>();
    v->read_only = read_only != 0;
    v->tcp_writable = tcp_writable != 0;
    v->dat_fd = open(dat_path, read_only ? O_RDONLY : O_RDWR);
    if (v->dat_fd < 0) return DP_IO;
    v->idx_fd = open(idx_path, O_RDWR | O_CREAT | O_APPEND, 0644);
    if (v->idx_fd < 0) return DP_IO;
    struct stat st;
    fstat(v->dat_fd, &st);
    v->dat_size = (uint64_t)st.st_size;
    // build the map from the idx (WalkIndexFile replay semantics)
    struct stat ist;
    fstat(v->idx_fd, &ist);
    uint64_t n = (uint64_t)ist.st_size / 16;
    // drop a torn (16-misaligned) tail before appending after it — the
    // Python open path truncates the same way
    if ((uint64_t)ist.st_size != n * 16)
        (void)!ftruncate(v->idx_fd, (off_t)(n * 16));
    v->idx_size = n * 16;
    std::vector<uint8_t> buf(1 << 20);
    uint64_t done = 0;
    while (done < n) {
        uint64_t batch = std::min<uint64_t>(buf.size() / 16, n - done);
        ssize_t got = pread(v->idx_fd, buf.data(), batch * 16, done * 16);
        if (got != (ssize_t)(batch * 16)) break;
        for (uint64_t i = 0; i < batch; i++) {
            const uint8_t* e = buf.data() + i * 16;
            uint64_t key = get_u64(e);
            uint64_t off = (uint64_t)get_u32(e + 8) * 8;
            int32_t size = (int32_t)get_u32(e + 12);
            if (key > v->max_key) v->max_key = key;
            auto old = v->map.find(key);
            if (old != v->map.end() && old->second.size >= 0)
                v->deleted_bytes += (uint64_t)old->second.size;
            if (off != 0 && size >= 0)
                v->map[key] = NeedleVal{off, size};
            else
                v->map.erase(key);
        }
        done += batch;
    }
    VolumeRef old;
    {
        std::lock_guard<std::mutex> g(s->vol_mu);
        auto it = s->volumes.find(vid);
        if (it != s->volumes.end()) old = it->second;
        s->volumes[vid] = v;
    }
    if (old) {  // drain + retire the replaced instance
        std::lock_guard<std::mutex> wg(old->write_mu);
        old->retired = true;
    }
    return DP_OK;
}

int dp_remove_volume(void* h, unsigned vid) {
    Server* s = (Server*)h;
    VolumeRef v;
    {
        std::lock_guard<std::mutex> g(s->vol_mu);
        auto it = s->volumes.find(vid);
        if (it == s->volumes.end()) return DP_NO_VOLUME;
        v = it->second;
        s->volumes.erase(it);
    }
    // drain the in-flight writer (if any) and fence later ones: once
    // retired is set under write_mu, no further append can touch the
    // files, so the Python side may reopen them safely.  In-flight
    // READERS hold a shared_ptr; the fds close when the last one drops.
    std::lock_guard<std::mutex> wg(v->write_mu);
    v->retired = true;
    return DP_OK;
}

int dp_write(void* h, unsigned vid, unsigned long long id, unsigned cookie,
             const unsigned char* data, unsigned len, unsigned* out_size) {
    VolumeRef v = find_volume((Server*)h, vid);
    if (!v) return DP_NO_VOLUME;
    return vol_write(v.get(), id, cookie, data, len, out_size);
}

// Append a record the caller serialized (rich needles from the HTTP
// plane: name/mime/flags/cipher ride inside `rec`).  The plane stays the
// single writer — same lock, same idx append, same map update.
int dp_append(void* h, unsigned vid, unsigned long long id, unsigned cookie,
              const unsigned char* rec, unsigned long long rec_len,
              int size) {
    VolumeRef v = find_volume((Server*)h, vid);
    if (!v) return DP_NO_VOLUME;
    if (v->read_only) return DP_READONLY;
    std::lock_guard<std::mutex> g(v->write_mu);
    if (v->retired) return DP_NO_VOLUME;
    if (v->dat_size + rec_len > MAX_VOLUME_BYTES) return DP_FULL;
    {
        std::lock_guard<std::mutex> m(v->map_mu);
        auto it = v->map.find(id);
        if (it != v->map.end() && it->second.size >= 0) {
            uint8_t hdr[4];
            if (pread(v->dat_fd, hdr, 4, it->second.offset) == 4 &&
                get_u32(hdr) != cookie)
                return DP_COOKIE;
        }
    }
    uint64_t off = v->dat_size;
    if (pwrite(v->dat_fd, rec, rec_len, off) != (ssize_t)rec_len) {
        (void)!ftruncate(v->dat_fd, off);
        return DP_IO;
    }
    uint8_t ie[16];
    put_u64(ie, id); put_u32(ie + 8, (uint32_t)(off / 8));
    put_u32(ie + 12, (uint32_t)size);
    if (idx_append(v.get(), ie, off) != DP_OK) return DP_IO;
    v->dat_size = off + rec_len;
    if (id > v->max_key) v->max_key = id;
    {
        std::lock_guard<std::mutex> m(v->map_mu);
        auto it = v->map.find(id);
        if (it != v->map.end() && it->second.size >= 0)
            v->deleted_bytes += (uint64_t)it->second.size;
        if (size >= 0)
            v->map[id] = NeedleVal{off, size};
        else
            v->map.erase(id);
    }
    return DP_OK;
}

// Whole stored record back to Python (HTTP reads need name/mime/flags);
// cookie is checked here (unless check_cookie=0, the Python
// read_needle(cookie=None) path) so a miss never ships the blob.
int dp_read_record(void* h, unsigned vid, unsigned long long id,
                   unsigned cookie, int check_cookie, unsigned char** out,
                   unsigned long long* out_len, int* out_size) {
    VolumeRef v = find_volume((Server*)h, vid);
    if (!v) return DP_NO_VOLUME;
    NeedleVal nv;
    {
        std::lock_guard<std::mutex> m(v->map_mu);
        auto it = v->map.find(id);
        if (it == v->map.end()) return DP_NOT_FOUND;
        nv = it->second;
        if (nv.size < 0) return DP_DELETED;
    }
    uint64_t rec_len = actual_size(nv.size);
    unsigned char* buf = (unsigned char*)malloc(rec_len);
    if (pread(v->dat_fd, buf, rec_len, nv.offset) != (ssize_t)rec_len) {
        free(buf);
        return DP_IO;
    }
    if (check_cookie && get_u32(buf) != cookie) {
        free(buf);
        return DP_COOKIE;
    }
    if (get_u64(buf + 4) != id) { free(buf); return DP_IO; }
    *out = buf;
    *out_len = rec_len;
    *out_size = nv.size;
    return DP_OK;
}

int dp_delete(void* h, unsigned vid, unsigned long long id, unsigned cookie,
              unsigned* out_size) {
    VolumeRef v = find_volume((Server*)h, vid);
    if (!v) return DP_NO_VOLUME;
    return vol_delete(v.get(), id, cookie, out_size);
}

// out buffer is malloc'd; caller frees with dp_free
int dp_read(void* h, unsigned vid, unsigned long long id, unsigned cookie,
            unsigned char** out, unsigned* out_len) {
    VolumeRef v = find_volume((Server*)h, vid);
    if (!v) return DP_NO_VOLUME;
    std::vector<uint8_t> data;
    int rc = vol_read(v.get(), id, cookie, &data);
    if (rc != DP_OK) return rc;
    *out = (unsigned char*)malloc(data.size() ? data.size() : 1);
    memcpy(*out, data.data(), data.size());
    *out_len = (unsigned)data.size();
    return DP_OK;
}

void dp_free(void* p) { free(p); }

int dp_stat(void* h, unsigned vid, unsigned long long* dat_size,
            unsigned long long* file_count,
            unsigned long long* max_file_key,
            unsigned long long* deleted_bytes,
            unsigned long long* sync_passes) {
    VolumeRef v = find_volume((Server*)h, vid);
    if (!v) return DP_NO_VOLUME;
    *dat_size = v->dat_size;
    *max_file_key = v->max_key;
    *deleted_bytes = v->deleted_bytes;
    {
        std::lock_guard<std::mutex> s(v->sync_mu);
        *sync_passes = v->sync_passes;
    }
    std::lock_guard<std::mutex> m(v->map_mu);
    *file_count = v->map.size();
    return DP_OK;
}

int dp_sync(void* h, unsigned vid) {
    VolumeRef v = find_volume((Server*)h, vid);
    if (!v) return DP_NO_VOLUME;
    // group commit: concurrent durable writers share one fsync pass, and
    // appends keep flowing while it runs (the VolumeRef keeps fds alive
    // across a concurrent retire)
    return vol_group_sync(v.get());
}

void dp_stop(void* h) {
    Server* s = (Server*)h;
    s->stopping = true;
    if (s->listen_fd >= 0) {
        shutdown(s->listen_fd, SHUT_RDWR);
        close(s->listen_fd);
    }
    {
        std::lock_guard<std::mutex> g(s->conn_mu);
        for (int fd : s->conns) shutdown(fd, SHUT_RDWR);
    }
    if (s->accept_thread.joinable()) s->accept_thread.join();
    // a connection accepted in the shutdown window is only registered
    // AFTER the first pass above; with the accept thread joined the
    // registry is final, so one more pass closes any straggler
    {
        std::lock_guard<std::mutex> g(s->conn_mu);
        for (int fd : s->conns) shutdown(fd, SHUT_RDWR);
    }
    std::vector<Server::ConnThread> threads;
    {
        std::lock_guard<std::mutex> g(s->conn_mu);
        threads.swap(s->conn_threads);
    }
    for (auto& ct : threads)
        if (ct.t.joinable()) ct.t.join();
    {
        std::lock_guard<std::mutex> g(s->vol_mu);
        s->volumes.clear();  // shared_ptr closes fds on release
    }
    // the Server shell itself is intentionally NOT freed: a Python
    // thread that raced stop() may still hold the handle, and dp_* on a
    // drained Server safely answers DP_NO_VOLUME (a few hundred bytes
    // leak once per plane, at process teardown in practice)
}

}  // extern "C"
