"""Compact JWS HS256 tokens for write/read authorization.

Equivalent of weed/security/jwt.go: the master signs a per-fid claim that the
volume server verifies before accepting a write (SeaweedFileIdClaims,
jwt.go:18-49); gateways sign a bare claim the filer verifies
(SeaweedFilerClaims, jwt.go:52-72). Implemented on stdlib hmac/hashlib —
the wire format is standard JWT so any client library interoperates.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Optional

EncodedJwt = str
SigningKey = bytes


class JwtError(Exception):
    pass


def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


_HEADER = _b64url(json.dumps(
    {"alg": "HS256", "typ": "JWT"}, separators=(",", ":")).encode())


def _sign(signing_key: SigningKey, payload: dict) -> EncodedJwt:
    body = _b64url(json.dumps(payload, separators=(",", ":")).encode())
    msg = f"{_HEADER}.{body}".encode()
    sig = _b64url(hmac.new(signing_key, msg, hashlib.sha256).digest())
    return f"{_HEADER}.{body}.{sig}"


def gen_jwt_for_volume_server(signing_key: SigningKey | str,
                              expires_after_sec: int,
                              file_id: str) -> EncodedJwt:
    """Master-side: restrict the token to a single fid (jwt.go:30-49)."""
    key = signing_key.encode() if isinstance(signing_key, str) else signing_key
    if not key:
        return ""
    claims: dict = {"fid": file_id}
    if expires_after_sec > 0:
        claims["exp"] = int(time.time()) + expires_after_sec
    return _sign(key, claims)


def gen_jwt_for_filer_server(signing_key: SigningKey | str,
                             expires_after_sec: int) -> EncodedJwt:
    """Gateway-side: authenticate to the filer API (jwt.go:52-72)."""
    key = signing_key.encode() if isinstance(signing_key, str) else signing_key
    if not key:
        return ""
    claims: dict = {}
    if expires_after_sec > 0:
        claims["exp"] = int(time.time()) + expires_after_sec
    return _sign(key, claims)


def decode_jwt(signing_key: SigningKey | str, token: EncodedJwt) -> dict:
    """Verify signature + exp, return the claims (jwt.go:91-99)."""
    key = signing_key.encode() if isinstance(signing_key, str) else signing_key
    parts = token.split(".")
    if len(parts) != 3:
        raise JwtError("malformed token")
    try:
        header = json.loads(_unb64url(parts[0]))
    except Exception:
        raise JwtError("malformed header") from None
    if header.get("alg") != "HS256":
        raise JwtError("unknown token method")
    msg = f"{parts[0]}.{parts[1]}".encode()
    want = hmac.new(key, msg, hashlib.sha256).digest()
    try:
        got = _unb64url(parts[2])
    except Exception:
        raise JwtError("malformed signature") from None
    if not hmac.compare_digest(want, got):
        raise JwtError("signature mismatch")
    try:
        claims = json.loads(_unb64url(parts[1]))
    except Exception:
        raise JwtError("malformed claims") from None
    exp = claims.get("exp")
    if exp is not None and time.time() > exp:
        raise JwtError("token expired")
    return claims


def get_jwt(headers, query: Optional[dict] = None) -> EncodedJwt:
    """Extract a token from ?jwt= or Authorization: Bearer (jwt.go:76-89)."""
    token = (query or {}).get("jwt", "")
    if not token:
        bearer = headers.get("Authorization", "") if headers else ""
        if len(bearer) > 7 and bearer[:6].upper() == "BEARER":
            token = bearer[7:]
    return token
