"""Cluster TLS/mTLS: ssl contexts from security.toml [tls] settings.

Equivalent of weed/security/tls.go (LoadServerTLS/LoadClientTLS): the
reference wraps every gRPC connection in mutual TLS when security.toml
carries ca/cert/key paths; here the same three files wrap every
inter-server HTTP socket.  One call to `enable_cluster_tls` flips the
whole process: servers listen with HTTPS (requiring client certs when a
CA is given) and every outgoing http:// URL is upgraded + verified.

    [tls]
    ca          = "/etc/seaweedfs/ca.crt"     # peer verification root
    cert        = "/etc/seaweedfs/node.crt"   # this node's certificate
    key         = "/etc/seaweedfs/node.key"
    verify_client = true                       # mTLS (default when ca set)
"""

from __future__ import annotations

import ssl
from dataclasses import dataclass
from typing import Optional

from ..utils.httpd import set_client_tls


@dataclass
class TlsConfig:
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    verify_client: bool = True

    @property
    def enabled(self) -> bool:
        return bool(self.cert_file and self.key_file)


def from_configuration(conf) -> TlsConfig:
    """security.toml [tls] section -> TlsConfig (absent section = off)."""
    return TlsConfig(
        ca_file=conf.get_string("tls.ca") or "",
        cert_file=conf.get_string("tls.cert") or "",
        key_file=conf.get_string("tls.key") or "",
        verify_client=bool(conf.get("tls.verify_client", True)),
    )


def server_context(cfg: TlsConfig) -> Optional[ssl.SSLContext]:
    """ssl context for `serve(..., tls_context=...)`; None when TLS off."""
    if not cfg.enabled:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    if cfg.ca_file:
        ctx.load_verify_locations(cfg.ca_file)
        if cfg.verify_client:
            ctx.verify_mode = ssl.CERT_REQUIRED  # mutual TLS
    return ctx


def client_context(cfg: TlsConfig) -> Optional[ssl.SSLContext]:
    """ssl context for outgoing requests: verifies the server against the
    CA and presents this node's cert (the mTLS client half)."""
    if not cfg.enabled:
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if cfg.ca_file:
        ctx.load_verify_locations(cfg.ca_file)
    else:  # pragma: no cover - cert without CA: trust it directly
        ctx.load_verify_locations(cfg.cert_file)
    # cluster certs are issued to node names, not necessarily the IPs
    # servers dial each other by — the CA signature is the trust anchor
    ctx.check_hostname = False
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    return ctx


def enable_cluster_tls(cfg: TlsConfig) -> Optional[ssl.SSLContext]:
    """Install the client side process-wide and return the server context
    for `serve`.  Returns None (and installs nothing) when cfg is off."""
    if not cfg.enabled:
        return None
    set_client_tls(client_context(cfg))
    return server_context(cfg)
