"""security.toml -> Guard construction.

Equivalent of the reference's security.toml scaffold
(weed/command/scaffold/security.toml) consumed by every server command:

    [jwt.signing]          key, expires_after_seconds   — volume write JWT
    [jwt.signing.read]     key, expires_after_seconds   — volume read JWT
    [jwt.filer_signing]    key, expires_after_seconds   — filer API JWT
    [guard]                white_list = ["ip", "cidr"]
"""

from __future__ import annotations

from ..utils.config import Configuration, load_configuration
from .guard import Guard


def load_security_configuration(search_dirs=None) -> Configuration:
    return load_configuration("security", search_dirs=search_dirs)


def volume_guard(conf: Configuration) -> Guard:
    return Guard(
        white_list=conf.get("guard.white_list", []) or [],
        signing_key=conf.get_string("jwt.signing.key"),
        expires_after_sec=conf.get_int("jwt.signing.expires_after_seconds", 10),
        read_signing_key=conf.get_string("jwt.signing.read.key"),
        read_expires_after_sec=conf.get_int(
            "jwt.signing.read.expires_after_seconds", 60),
    )


def master_guard(conf: Configuration) -> Guard:
    # master signs with the volume write key (it mints assign tokens)
    return volume_guard(conf)


def filer_guard(conf: Configuration) -> Guard:
    return Guard(
        white_list=conf.get("guard.white_list", []) or [],
        signing_key=conf.get_string("jwt.filer_signing.key"),
        expires_after_sec=conf.get_int(
            "jwt.filer_signing.expires_after_seconds", 10),
    )
