from .jwt import (EncodedJwt, SigningKey, decode_jwt, gen_jwt_for_filer_server,
                  gen_jwt_for_volume_server, get_jwt, JwtError)
from .guard import Guard

__all__ = [
    "EncodedJwt", "SigningKey", "decode_jwt", "gen_jwt_for_filer_server",
    "gen_jwt_for_volume_server", "get_jwt", "Guard", "JwtError",
]
