"""Request guard: IP whitelist + JWT enforcement middleware.

Equivalent of weed/security/guard.go:53-120 — a server wraps its mutating
handlers in `guard.white_list(...)` and its JWT-protected handlers in
`guard.secure(...)`. Inactive guards (no whitelist, no key) pass requests
through untouched, exactly like the reference's isWriteActive short-circuit.
"""

from __future__ import annotations

import ipaddress
from typing import Optional

from .jwt import JwtError, decode_jwt, get_jwt


class Guard:
    def __init__(self, white_list: Optional[list[str]] = None,
                 signing_key: str = "", expires_after_sec: int = 10,
                 read_signing_key: str = "", read_expires_after_sec: int = 60):
        self.white_list = [w for w in (white_list or []) if w]
        self.signing_key = signing_key
        self.expires_after_sec = expires_after_sec
        self.read_signing_key = read_signing_key
        self.read_expires_after_sec = read_expires_after_sec
        self.is_write_active = bool(self.white_list or self.signing_key)

    # --- whitelist (guard.go:65-130) --------------------------------------
    def check_white_list(self, remote_host: str) -> bool:
        if not self.white_list:
            return True
        for entry in self.white_list:
            if "/" in entry:
                try:
                    if (ipaddress.ip_address(remote_host)
                            in ipaddress.ip_network(entry, strict=False)):
                        return True
                except ValueError:
                    continue
            elif entry == remote_host:
                return True
        return False

    @staticmethod
    def actual_remote_host(req) -> str:
        """The TCP peer address. Divergence from guard.go:79-92 (which
        trusts X-Forwarded-For outright): a client-supplied header must not
        widen access, so the socket peer is authoritative. Proxied
        deployments whitelist the proxy address instead."""
        return req.handler.client_address[0]

    def white_list_ok(self, req) -> bool:
        if not self.is_write_active:
            return True
        return self.check_white_list(self.actual_remote_host(req))

    # --- jwt --------------------------------------------------------------
    def check_write_jwt(self, req, fid: str) -> Optional[str]:
        """Volume-server write check: returns an error string or None.
        The claim must carry the exact fid being written (the master signed
        it at assign time)."""
        if not self.signing_key:
            return None
        token = get_jwt(req.headers, req.query)
        if not token:
            return "missing jwt"
        try:
            claims = decode_jwt(self.signing_key, token)
        except JwtError as e:
            return str(e)
        if claims.get("fid") != fid:
            return f"jwt fid mismatch: {claims.get('fid')} != {fid}"
        return None

    def check_read_jwt(self, req, fid: str) -> Optional[str]:
        if not self.read_signing_key:
            return None
        token = get_jwt(req.headers, req.query)
        if not token:
            return "missing jwt"
        try:
            claims = decode_jwt(self.read_signing_key, token)
        except JwtError as e:
            return str(e)
        if claims.get("fid") not in (None, fid):
            return "jwt fid mismatch"
        return None

    def check_filer_jwt(self, req) -> Optional[str]:
        """Filer API check: any validly-signed token passes (bare claims)."""
        if not self.signing_key:
            return None
        token = get_jwt(req.headers, req.query)
        if not token:
            return "missing jwt"
        try:
            decode_jwt(self.signing_key, token)
        except JwtError as e:
            return str(e)
        return None

    def gen_read_token(self) -> str:
        """Mint a bare read token (no fid claim: valid for any read) with
        the read key — the master attaches this to /dir/lookup responses so
        secured reads are actually possible."""
        from .jwt import gen_jwt_for_filer_server

        return gen_jwt_for_filer_server(self.read_signing_key,
                                        self.read_expires_after_sec)
