"""Flight recorder: auto-captured diagnostic bundles that outlive rings.

The trace ring, the event journal, and /metrics all answer "what is
happening NOW" — but by the time an operator reads a 3 a.m. page, the
spans that would have explained it have rotated out of the bounded
ring.  The flight recorder freezes the evidence at the moment a rule
fires: when the master's alert engine transitions a rule to `firing`,
it asks the implicated server(s) (POST /debug/flightrecorder/capture)
to snapshot a bounded bundle of

    trace    — the process tracer's whole-ring to_dict() dump,
    profile  — a short collapsed-stack sampling profile,
    metrics  — the full Prometheus exposition,
    events   — the recent event journal tail,

persisted to a size-capped on-disk spool (oldest-bundle eviction) and
listed/fetched via GET /debug/flightrecorder[/<id>] and `weed shell
alerts.capture`.  Bundle ids land on the alert itself
(/cluster/alerts ... bundles=[...]), so the page links straight to the
evidence.

One recorder per process (like the tracer and journal): co-located
servers in one process share a spool, and every capture stamps the
requesting server's identity.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from typing import Optional

from . import context as _trace_context
from . import events as _events

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


class FlightRecorder:  # weedlint: concurrent-class
    """Size-capped on-disk spool of diagnostic bundles.  Reached
    concurrently: alert-engine capture fan-outs and HTTP threads
    serving /debug/flightrecorder."""

    def __init__(self, spool_dir: Optional[str] = None,
                 max_bytes: int = 64 << 20, max_bundles: int = 32):
        self.spool_dir = spool_dir  # guarded-by: _lock
        self.max_bytes = max_bytes  # guarded-by: _lock
        self.max_bundles = max_bundles  # guarded-by: _lock
        # RLock: _evict -> _scan -> _dir re-enters while holding it
        self._lock = threading.RLock()
        self._seq = 0  # guarded-by: _lock
        self.captures = 0  # guarded-by: _lock
        self.evicted = 0  # guarded-by: _lock

    def configure(self, spool_dir: Optional[str] = None,
                  max_bytes: Optional[int] = None,
                  max_bundles: Optional[int] = None) -> "FlightRecorder":
        """Servers point the shared recorder at their data directory at
        start; last configure wins (co-located servers share one spool,
        like they share one tracer)."""
        with self._lock:
            if spool_dir:
                self.spool_dir = spool_dir
            if max_bytes is not None:
                self.max_bytes = int(max_bytes)
            if max_bundles is not None:
                self.max_bundles = int(max_bundles)
        return self

    def _dir(self) -> str:
        with self._lock:  # two first-captures must agree on the spool
            d = self.spool_dir
            if not d:
                # unconfigured (bare tools, tests): a per-process
                # tempdir spool — bounded and disposable
                d = self.spool_dir = os.path.join(
                    tempfile.gettempdir(),
                    f"weed-flightrecorder-{os.getpid()}")
        os.makedirs(d, exist_ok=True)
        return d

    # --- capture ----------------------------------------------------------
    def capture(self, reason: str = "manual",
                alert: Optional[str] = None,
                server: Optional[str] = None,
                trace_id: Optional[str] = None,
                profile_s: float = 0.25, hz: float = 100.0,
                max_events: int = 256,
                events: Optional[list] = None) -> dict:
        """Snapshot this process into one bundle; returns its meta
        (id, sizes, …).  Bounded by construction: the trace ring and
        journal are already capped, the profile window is clamped, and
        the spool evicts oldest-first after the write."""
        from ..stats import REGISTRY
        from .profiler import profile_collapsed
        from .tracer import get_tracer

        if server is None:
            server = _trace_context.current_server()
        with self._lock:
            self._seq += 1
            seq = self._seq
        bundle_id = "fr-%s-%d-%s" % (
            time.strftime("%Y%m%d%H%M%S", time.gmtime()), seq,
            re.sub(r"[^A-Za-z0-9_-]", "_", alert or reason)[:40])
        captured_at = time.time()
        tracer = get_tracer()
        trace_doc = tracer.to_dict()
        profile = ""
        if profile_s > 0:
            try:
                # the profile must never be the reason a capture fails —
                # and never block the fan-out for long
                profile = profile_collapsed(min(profile_s, 5.0),
                                            hz=min(hz, 250.0))
            except Exception as e:
                profile = f"# profile failed: {type(e).__name__}: {e}\n"
        try:
            metrics = REGISTRY.expose()
        except Exception as e:
            metrics = f"# metrics failed: {type(e).__name__}: {e}\n"
        if events is None:
            events = _events.get_journal().query(limit=max_events)
        else:
            events = list(events)[-max_events:]
        doc = {
            "format": "seaweedfs-tpu-flightrecorder-v1",
            "meta": {
                "id": bundle_id,
                "reason": reason,
                "alert": alert or "",
                "server": server or "",
                "trace_id": trace_id or "",
                "captured_at": round(captured_at, 3),
                "span_count": len(trace_doc.get("spans") or []),
                "event_count": len(events),
            },
            "trace": trace_doc,
            "profile": profile,
            "metrics": metrics,
            "events": events,
        }
        d = self._dir()
        path = os.path.join(d, bundle_id + ".json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        with self._lock:  # capture fan-outs race on the counter
            self.captures += 1
        meta = dict(doc["meta"])
        meta["bytes"] = os.path.getsize(path)
        self._evict()
        _events.emit("flight_capture", server=server, id=bundle_id,
                     reason=reason, alert=alert or "",
                     bytes=meta["bytes"])
        return meta

    def _evict(self) -> None:
        """Oldest-bundle eviction past either cap — the spool can sit
        on a small disk forever."""
        with self._lock:
            try:
                entries = self._scan()
            except OSError:
                return
            max_bundles, max_bytes = self.max_bundles, self.max_bytes
            total = sum(e["bytes"] for e in entries)
            # entries is newest-first; trim from the tail
            while entries and (len(entries) > max_bundles
                               or total > max_bytes):
                victim = entries.pop()
                try:
                    os.remove(victim["path"])
                except OSError:
                    pass
                total -= victim["bytes"]
                self.evicted += 1

    def _scan(self) -> list[dict]:
        """Spool inventory, newest first (mtime; fs-only so restarts
        keep serving bundles captured by a previous process)."""
        d = self._dir()
        out = []
        for name in os.listdir(d):
            if not name.endswith(".json"):
                continue
            path = os.path.join(d, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append({"id": name[:-5], "bytes": st.st_size,
                        "mtime": st.st_mtime, "path": path})
        out.sort(key=lambda e: e["mtime"], reverse=True)
        return out

    # --- inspection -------------------------------------------------------
    def list(self) -> list[dict]:
        """Bundle index (id, size, age) newest first — the
        GET /debug/flightrecorder body."""
        now = time.time()
        return [{"id": e["id"], "bytes": e["bytes"],
                 "age_s": round(now - e["mtime"], 1)}
                for e in self._scan()]

    def get(self, bundle_id: str) -> Optional[dict]:
        """One full bundle document, or None (bad/unknown id — the id
        charset check also keeps path traversal out of the spool)."""
        if not _ID_RE.match(bundle_id or ""):
            return None
        path = os.path.join(self._dir(), bundle_id + ".json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def total_bytes(self) -> int:
        return sum(e["bytes"] for e in self._scan())


_GLOBAL = FlightRecorder()


def get_flightrecorder() -> FlightRecorder:
    return _GLOBAL
