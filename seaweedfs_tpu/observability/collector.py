"""Master-side trace collector + per-server span shipper.

The trace-context layer (context.py) makes every cross-server hop carry
one trace id, but the spans it produces still live in per-process rings:
answering "which hop bounded this EC rebuild?" would mean scraping every
server's /debug/traces and joining by hand.  This module closes the loop
the Dapper way:

  - TraceShipper (every server): hooks Tracer.on_record, buffers the
    spans of SAMPLED traces (only spans carrying a trace_id — local
    background work never ships), and batch-POSTs them to the master's
    /cluster/traces/ingest.  Bounded buffer; overflow and transport
    failures DROP (counted in SeaweedFS_trace_spans_dropped_total with
    reason ship_buffer/ship_error) rather than backpressure the serving
    path.  The ship POST itself runs under an explicit NOT_SAMPLED
    context so shipping can never recursively trace itself.

  - TraceCollector (the master): groups ingested spans by trace id,
    dedups by span id (multiple in-process shippers and re-ships are
    harmless), and serves the stitched trace at
    GET /cluster/traces/<trace_id>.  Spans carry their own `server`
    stamp from record time (context.swap_server at the Router
    chokepoint), so servers sharing one process tracer attribute
    correctly; the shipping server's URL is only a fallback for spans
    recorded outside any request.
    Bounded: oldest traces evict first, per-trace span counts cap, and
    both kinds of loss are visible on the trace document (`dropped`)
    so a truncated stitch cannot masquerade as a complete one.

Stitching needs no clock agreement beyond the tracer's wall-anchored
monotonic timestamps: parent/child edges come from span ids carried in
the Traceparent header, not from time ordering.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from . import context as _trace_context
from .tracer import Span, Tracer, _dropped_counter


class TraceShipper:
    """Ship sampled spans from this process's tracer to a collector.

    `master_url_fn` returns the CURRENT master url (volume servers
    follow the raft leader) or a comma-separated candidate list (the
    filer passes its configured masters): a flush that fails rotates
    to the next candidate, and ANY reachable master is a correct
    target because followers forward ingest POSTs to the raft leader.
    `local_collector` short-circuits HTTP for
    the master's own spans.  attach() CHAINS with any previously
    installed on_record hook, so several servers sharing one process
    (test fixtures, `weed server`) each get to ship — the collector's
    span-id dedup collapses the duplicates.
    """

    def __init__(self, tracer: Tracer, server: str,
                 master_url_fn: Optional[Callable[[], str]] = None,
                 local_collector: Optional["TraceCollector"] = None,
                 batch_size: int = 256, flush_interval: float = 0.5,
                 buffer_cap: int = 4096):
        self.tracer = tracer
        self.server = server
        self.master_url_fn = master_url_fn
        self.local_collector = local_collector
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.buffer_cap = buffer_cap
        self._buf: deque[Span] = deque()  # guarded-by: _lock
        # per-trace loss ledger: spans this shipper failed to deliver,
        # keyed by trace id, reported to the collector on the next
        # successful flush so a truncated stitched trace SAYS so
        # (at-least-once: a loss report that errors mid-POST may be
        # re-reported — dropped counts only ever over-warn, never
        # under-warn).  Bounded: past _LOST_CAP distinct traces only the
        # global counter keeps counting.
        self._lost: dict[str, int] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # hook-chain handoff: written by attach()/detach() on the
        # server's lifecycle thread before the flush thread starts /
        # after it stops; read lock-free on every recorded span
        self._prev_hook: Optional[Callable[[Span], None]] = None
        # shared leader-follow policy: candidate rotation + learned
        # leader hint (utils/leader.py) — internally locked
        from ..utils.leader import LeaderFollowingTransport
        self.transport = LeaderFollowingTransport(master_url_fn,
                                                  name=f"traces:{server}")
        self.shipped = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock

    _LOST_CAP = 1024

    # --- lifecycle --------------------------------------------------------
    def attach(self) -> "TraceShipper":
        self._prev_hook = self.tracer.on_record  # weedlint: disable=W502 lifecycle handoff: runs before the flush thread starts
        self.tracer.on_record = self._on_span
        self._thread = threading.Thread(target=self._flush_loop, daemon=True,
                                        name=f"trace-ship:{self.server}")
        self._thread.start()
        return self

    def detach(self) -> None:
        """Stop shipping: final flush, restore the previous hook."""
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self.tracer.on_record is self._on_span:
            self.tracer.on_record = self._prev_hook
        # whatever landed after the loop exited — with a sub-second
        # timeout: at cluster teardown the master is often already gone,
        # and server stop() must not hang the full transport timeout for
        # spans that would be dropped anyway (the loss is counted)
        self._flush(timeout=0.5)

    # --- hot path ---------------------------------------------------------
    def _on_span(self, sp: Span) -> None:  # thread-entry
        # called on whatever thread recorded the span;
        # a detached shipper may still sit mid-chain (another shipper
        # attached after it and holds the head of the hook chain): it
        # must degrade to a pure pass-through, not a buffer that fills
        # and drop-counts forever
        if not self._stop.is_set():
            # on_record already filtered to spans carrying a trace_id
            with self._lock:
                if len(self._buf) >= self.buffer_cap:
                    self.dropped += 1
                    _dropped_counter().inc("ship_buffer")
                    self._note_lost_locked(sp.trace_id)
                else:
                    self._buf.append(sp)
                    if len(self._buf) >= self.batch_size:
                        self._wake.set()
        prev = self._prev_hook
        if prev is not None:
            prev(sp)

    def _note_lost_locked(self, trace_id: Optional[str],
                          n: int = 1) -> None:
        if not trace_id:
            return
        if trace_id in self._lost or len(self._lost) < self._LOST_CAP:
            self._lost[trace_id] = self._lost.get(trace_id, 0) + n

    # --- shipping ---------------------------------------------------------
    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval)
            self._wake.clear()
            self._flush()

    def _flush(self, timeout: float = 5.0) -> None:
        with self._lock:
            if not self._buf and not self._lost:
                return
            batch = list(self._buf)
            self._buf.clear()
            lost = self._lost
            self._lost = {}
        docs = [sp.to_dict() for sp in batch]
        if self.local_collector is not None:
            self.local_collector.ingest(self.server, docs, lost=lost)
            with self._lock:
                self.shipped += len(docs)
            return
        try:
            # explicit negative decision: the ship POST must not be
            # sampled downstream (it would ship spans about shipping
            # spans, forever)
            with _trace_context.scope(_trace_context.NOT_SAMPLED):
                self.transport.post("/cluster/traces/ingest",
                                    {"server": self.server, "spans": docs,
                                     "lost": lost},
                                    timeout=timeout)
            with self._lock:
                self.shipped += len(docs)
        except Exception:
            # master down / not yet elected: the batch is LOST and
            # counted — and remembered per trace id, so when the master
            # is reachable again the affected stitched traces are marked
            # truncated instead of silently reading complete.  The
            # transport rotated to the next configured master (followers
            # forward to the leader, so any live one works) and learns
            # the leader address from ingest replies after an election.
            if docs:
                _dropped_counter().inc("ship_error", amount=len(docs))
            # counter updates ride _lock: the flush thread and the
            # detach()-time final flush race these read-modify-writes
            with self._lock:
                self.dropped += len(docs)
                for d in docs:
                    self._note_lost_locked(d.get("trace"))
                for tid, n in lost.items():
                    self._note_lost_locked(tid, n)


class _TraceEntry:
    __slots__ = ("spans", "span_ids", "servers", "updated_at", "dropped")

    def __init__(self):
        self.spans: list[dict] = []
        self.span_ids: set[str] = set()
        self.servers: set[str] = set()
        self.updated_at = time.time()
        self.dropped = 0


class TraceCollector:  # weedlint: concurrent-class
    """Bounded trace store keyed by trace id (the master's side).
    Reached concurrently from the threaded HTTP router (ingest POSTs +
    trace GETs)."""

    def __init__(self, max_traces: int = 512,
                 max_spans_per_trace: int = 8192, ttl_s: float = 900.0):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self.ttl_s = ttl_s
        self._traces: "OrderedDict[str, _TraceEntry]" = OrderedDict()  # guarded-by: _lock
        self._lock = threading.Lock()
        self.evicted_traces = 0  # guarded-by: _lock

    def ingest(self, server: str, spans: list[dict],
               lost: Optional[dict] = None) -> int:
        """Merge shipped span dicts; returns how many were accepted
        (dedup by span id; per-trace cap drops are counted on the
        trace so its doc says so).  `lost` maps trace id -> spans the
        SHIPPER already lost (buffer overflow, earlier failed POSTs):
        they land on the trace's dropped count so the stitched doc
        admits its truncation."""
        accepted = 0
        now = time.time()
        with self._lock:
            for tid, n in (lost or {}).items():
                try:
                    n = int(n)
                except (TypeError, ValueError):
                    continue
                if not tid or n <= 0:
                    continue
                entry = self._traces.get(tid)
                if entry is None:
                    entry = self._traces[tid] = _TraceEntry()
                entry.dropped += n
                entry.updated_at = now
                self._traces.move_to_end(tid)
            for sp in spans:
                tid = sp.get("trace")
                sid = sp.get("id")
                if not tid or not sid:
                    continue
                entry = self._traces.get(tid)
                if entry is None:
                    entry = self._traces[tid] = _TraceEntry()
                if sid in entry.span_ids:
                    continue  # duplicate ship (chained shippers, retry)
                if len(entry.spans) >= self.max_spans_per_trace:
                    entry.dropped += 1
                    _dropped_counter().inc("collector_cap")
                    continue
                sp = dict(sp)
                sp.setdefault("server", server)
                entry.spans.append(sp)
                entry.span_ids.add(sid)
                entry.servers.add(sp["server"])
                entry.updated_at = now
                self._traces.move_to_end(tid)
                accepted += 1
            self._evict(now)
        return accepted

    def _evict(self, now: float) -> None:  # holds: _lock
        while len(self._traces) > self.max_traces:
            self._traces.popitem(last=False)
            self.evicted_traces += 1
            _dropped_counter().inc("collector_evict")
        stale = [tid for tid, e in self._traces.items()
                 if now - e.updated_at > self.ttl_s]
        for tid in stale:
            del self._traces[tid]

    def get(self, trace_id: str) -> Optional[dict]:
        """The stitched trace document (analysis-ready: a `spans` list
        the analyzer's _normalize understands, plus identity fields)."""
        with self._lock:
            entry = self._traces.get(trace_id)
            if entry is None:
                return None
            spans = [dict(sp) for sp in entry.spans]
            servers = sorted(entry.servers)
            dropped = entry.dropped
        spans.sort(key=lambda s: s["t0"])
        return {"format": "seaweedfs-tpu-cluster-trace-v1",
                "trace_id": trace_id,
                "span_count": len(spans),
                "servers": servers,
                "dropped": dropped,
                "spans": spans}

    def summaries(self, limit: int = 64) -> list[dict]:
        """Most-recent-first index for GET /cluster/traces."""
        with self._lock:
            items = list(self._traces.items())[-limit:]
            out = []
            for tid, e in reversed(items):
                roots = [s for s in e.spans
                         if not s.get("parent")
                         or s["parent"] not in e.span_ids]
                root = min(roots, key=lambda s: s["t0"]) if roots else None
                t0 = min((s["t0"] for s in e.spans), default=0.0)
                t1 = max((s["t1"] for s in e.spans), default=0.0)
                out.append({"trace_id": tid,
                            "root": root["name"] if root else None,
                            "span_count": len(e.spans),
                            "servers": sorted(e.servers),
                            "wall_s": round(t1 - t0, 4),
                            "age_s": round(time.time() - e.updated_at, 1)})
        return out

    def chrome(self, trace_id: str) -> Optional[dict]:
        """Chrome trace-event rendering of one stitched trace (per-server
        process tracks come from each span's shipped namespace)."""
        doc = self.get(trace_id)
        if doc is None:
            return None
        tr = Tracer(capacity=max(len(doc["spans"]), 1))
        tr.ingest_log(doc["spans"])
        return tr.to_chrome()
