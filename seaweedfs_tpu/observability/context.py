"""Trace-context propagation + head-based sampling (the Dapper layer).

PR 1/4 gave every process a span ring and an analyzer, but a
cross-server operation (EC rebuild fetching a remote shard, a
replicated write fanning out, gateway -> filer -> volume) shatters into
disconnected per-process rings: nothing ties a volume server's request
span back to the caller's span.  This module closes that gap with a
`traceparent`-style context:

    Traceparent: 00-<32-hex trace id>-<parent span id>-<01|00>

The trace id is 128 bits of os.urandom; the parent span id is this
codebase's namespaced span id (e.g. ``p3f2a.1c``) rather than the W3C
16-hex form — the header is traceparent-STYLE, same shape and parsing
discipline, carried only between our own servers.  The flags octet is
the head-based sampling decision: 01 = record spans, 00 = the caller
already decided NOT to sample, so downstream must not re-decide (an
all-zero trace id means the same thing and is what unsampled requests
send).  A malformed header never errors — the ingress mints a fresh
context instead, so a bad client can't 500 a server.

Rules, in order, at every ingress (Router.dispatch, the framed-TCP
fronts, shell/client/bench entry points):

  1. valid incoming header  -> adopt its trace id + parent + decision;
  2. X-Force-Trace header   -> sample, fresh trace id;
  3. otherwise              -> sample with probability sample_rate().

The decision lives in a thread-local for the rest of the request;
every outbound hop (utils/httpd.py inject_trace_headers) re-emits it,
so one head decision governs the whole distributed operation and the
serving hot path pays one header parse + one random() at 1% sampling.
"""

from __future__ import annotations

import os
import random
import threading
from typing import Optional

TRACEPARENT_HEADER = "Traceparent"
FORCE_HEADER = "X-Force-Trace"

_ZERO_TRACE = "0" * 32
# what an unsampled request sends downstream: all-zero trace id + 00
# flags = "decided no" (distinct from an ABSENT header = "not decided")
NOT_SAMPLED_HEADER = "00-%s-%s-00" % (_ZERO_TRACE, "0" * 16)
_HEX = frozenset("0123456789abcdef")


class TraceContext:
    """An affirmative sampling decision: this request's spans record
    under `trace_id`, and the first local span parents under the
    caller's `span_id` (empty for a locally minted root)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str = ""):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id}, parent={self.span_id!r})"


class _NotSampled:
    """Shared marker for 'decided NOT to sample': propagated downstream
    (NOT_SAMPLED_HEADER) so one head decision rules the whole chain."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return "NOT_SAMPLED"


NOT_SAMPLED = _NotSampled()

_tls = threading.local()
# module-level so servers, shell, and clients in one process share the
# knob; default 1.0 keeps "enable tracing = record everything" behavior
_state = {"rate": 1.0}


def set_sample_rate(rate: float) -> None:
    _state["rate"] = min(max(float(rate), 0.0), 1.0)


def sample_rate() -> float:
    return _state["rate"]


def new_trace_id() -> str:
    return os.urandom(16).hex()


def format_traceparent(trace_id: str, span_id: str = "",
                       sampled: bool = True) -> str:
    return "00-%s-%s-%s" % (trace_id, span_id or "0" * 16,
                            "01" if sampled else "00")


def parse_traceparent(value: str):
    """Header -> TraceContext (sampled), NOT_SAMPLED (explicit negative
    decision), or None (absent/malformed: the caller mints fresh, never
    errors)."""
    if not value:
        return None
    parts = value.strip().split("-", 3)
    if len(parts) != 4:
        return None
    version, trace_id, parent, flags = parts
    if version != "00" or len(trace_id) != 32 or not _HEX.issuperset(trace_id):
        return None
    if not parent or any(c.isspace() for c in parent):
        return None
    if flags not in ("00", "01"):
        return None
    if trace_id == _ZERO_TRACE or flags == "00":
        return NOT_SAMPLED
    # an all-zero parent means "root": no remote span to re-root under
    return TraceContext(trace_id, "" if parent.strip("0") == "" else parent)


def ingress_context(headers):
    """The head-based sampling decision at a server ingress.  `headers`
    is any .get()-able (or None for headerless ingresses like the
    framed-TCP fronts and shell/bench entry points).  Always returns a
    decision: TraceContext or NOT_SAMPLED."""
    if headers is not None:
        parsed = parse_traceparent(headers.get(TRACEPARENT_HEADER) or "")
        if parsed is not None:
            return parsed
        force = (headers.get(FORCE_HEADER) or "").strip().lower()
        if force and force not in ("0", "false", "no", "off"):
            return TraceContext(new_trace_id())
    rate = _state["rate"]
    if rate >= 1.0 or (rate > 0.0 and random.random() < rate):
        return TraceContext(new_trace_id())
    return NOT_SAMPLED


def current():
    """The thread's active decision: TraceContext, NOT_SAMPLED, or None
    (no ingress ran on this thread)."""
    return getattr(_tls, "ctx", None)


def current_sampled() -> Optional[TraceContext]:
    ctx = getattr(_tls, "ctx", None)
    return ctx if type(ctx) is TraceContext else None


def is_not_sampled() -> bool:
    """True only for an explicit negative head decision on this thread —
    the tracer's one-attribute-read guard that keeps unsampled requests
    off the span ring.  Threads with NO decision (background pipelines,
    bench loops) still record."""
    return getattr(_tls, "ctx", None) is NOT_SAMPLED


def activate(ctx):
    """Install `ctx` on this thread; returns the previous value for
    symmetric restore (threads are pooled per connection — a leaked
    context would bleed into the next request)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    return prev


def swap_server(url):
    """Install this thread's owning-server identity (the advertised
    host:port) for the duration of a request; returns the previous
    value for symmetric restore.  Spans recorded while set are stamped
    with it (tracer._record), so several servers sharing one process
    tracer (`weed server`, in-process fixtures) still attribute each
    span to the server that actually did the work — the collector's
    ship-time fallback stamp is only used for spans recorded outside
    any request."""
    prev = getattr(_tls, "server", None)
    _tls.server = url or None
    return prev


def current_server():
    """The thread's owning-server identity, or None outside a request."""
    return getattr(_tls, "server", None)


def begin_request(headers):
    """Ingress helper: decide + activate in one step.  Returns
    (sampled_ctx_or_None, previous) — pass `previous` to end_request()
    in a finally block."""
    prev = getattr(_tls, "ctx", None)
    ctx = ingress_context(headers)
    _tls.ctx = ctx
    return (ctx if ctx is not NOT_SAMPLED else None), prev


def end_request(prev) -> None:
    _tls.ctx = prev


def fork_for_thread():
    """The calling thread's decision, with its INNERMOST OPEN span id
    folded in as the parent — the value to hand to `scope` on a helper
    thread so spans recorded there nest under the request span that
    spawned the work (a bare current() would re-root them, because the
    per-thread span stack does not travel)."""
    ctx = getattr(_tls, "ctx", None)
    if type(ctx) is not TraceContext:
        return ctx
    from .tracer import get_tracer

    span_id = get_tracer().current_span_id()
    return TraceContext(ctx.trace_id, span_id or ctx.span_id)


class scope:
    """``with scope(ctx):`` — carry a caller's decision onto another
    thread (the cluster aggregator's scrape pool, worker helpers).
    Pass fork_for_thread()'s result to keep the caller's open span as
    the parent."""

    __slots__ = ("ctx", "prev")

    def __init__(self, ctx):
        self.ctx = ctx

    def __enter__(self):
        self.prev = activate(self.ctx)
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self.prev
        return False


def inject_trace_headers(headers: dict) -> dict:
    """Stamp the active decision onto an outbound request's headers.
    Sampled: trace id + the CURRENT span id as the remote parent (the
    cross-server stitching edge).  Decided-unsampled: the static
    NOT_SAMPLED_HEADER so downstream doesn't re-decide.  No decision:
    untouched."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is None:
        return headers
    if type(ctx) is not TraceContext:
        headers.setdefault(TRACEPARENT_HEADER, NOT_SAMPLED_HEADER)
        return headers
    from .tracer import get_tracer

    span_id = get_tracer().current_span_id() or ctx.span_id
    headers.setdefault(TRACEPARENT_HEADER,
                       format_traceparent(ctx.trace_id, span_id, True))
    return headers
