"""Cluster heat telemetry: decayed per-volume / per-needle access heat.

ROADMAP's heat-based auto-replication item needs one missing piece
before placement can consume popularity: a SIGNAL.  The paper's
Haystack story (hot content in RAM/replicas, cold content in EC)
presumes the cluster *knows* what is hot — this module is that sensing
layer, built with the same rigor the repo already applies to traces,
events and workload records:

  DecayedCounter      exponentially-decayed mass with a configurable
                      half-life; rate() converts mass to events/s.
                      merge is associative (decay both to the same
                      instant, add) so per-server snapshots compose on
                      the master.
  SpaceSavingSketch   bounded top-K per-needle heat (Metwally's
                      space-saving, decayed): the Zipf head stays
                      identifiable without unbounded per-fid state.
  HeatAccumulator     per-SERVER accumulator fed at the existing
                      dataplane chokepoints — Router.dispatch (HTTP
                      plane, the reqlog route classes), the framed-TCP
                      plane, and needle-cache hit/admission callbacks.
                      Serves GET /debug/heat.
  HeatShipper         snapshots master-ward on the established shipper
                      transport contract: bounded pending buffer,
                      leader-follow rotation, loss counted, never
                      backpressure on the serving path.
  ClusterHeatJournal  the master's merged view: per-volume heat ranks,
                      a live Zipf fit over the merged needle sketch
                      (scenarios/replay.estimate_zipf_s), head-set
                      membership, rack/server imbalance gauges — and a
                      head-set SHIFT detector that compares the current
                      head against a trailing window and emits
                      heat_shift / flash_crowd events (with the hot
                      volume, its share, holders and an exemplar trace
                      id) that the default journal_event alert rules
                      turn into pages.

Cost discipline: accounting off is ONE attribute check at each
chokepoint (`router.heat is None`); accounting on is a compiled-regex
match plus a few dict/float ops under one small lock — the bench
`heat` section proves <1% read-rps against an accounting-off baseline
spawned back-to-back.
"""

from __future__ import annotations

import math
import re
import threading
import time
from collections import deque
from typing import Callable, Optional

from . import context as _trace_context
from . import events as _events

_LN2 = math.log(2.0)

# event types (events.EVENT_TYPES) that are raised by the shift
# detector and watched by a default `journal_event` alert rule — the
# W401 lint (tools/weedlint/rules_health_keys.py) walks this tuple
# against EVENT_TYPES and alerts.default_rules() both ways.
HEAT_EVENT_TYPES = ("heat_shift", "flash_crowd")

# metric families this plane owns (stats/metrics.py heat_metrics());
# W401 checks each is registered so a renamed gauge cannot silently
# detach the dashboards from the detector.
HEAT_METRIC_FAMILIES = ("SeaweedFS_volume_heat",
                        "SeaweedFS_heat_imbalance_ratio",
                        "SeaweedFS_heat_snapshots_dropped_total")

# object routes on the HTTP plane: /<vid>,<fid-rest> — same shape the
# router's fid parsing accepts; everything else (/status, /metrics,
# /batch/*, /debug/*) is control plane and carries no per-volume heat
_FID_PATH_RE = re.compile(r"^/(\d+),")


class DecayedCounter:
    """Exponentially-decayed event mass.  add(x, now) decays the mass
    to `now` then adds x; value(now) reads without mutating.  Under a
    CONSTANT input rate r the mass converges to r*half_life/ln2, so
    rate(now) = value(now)*ln2/half_life recovers events/s.  Merging
    decays both sides to one instant and adds — associative and
    commutative, the property the master-side journal leans on.

    Not internally locked: the owning accumulator/journal serializes
    access (instances are plain [mass, ts] state, like the sketch)."""

    __slots__ = ("half_life", "mass", "ts")

    def __init__(self, half_life: float = 30.0, mass: float = 0.0,
                 ts: float = 0.0):
        self.half_life = max(float(half_life), 1e-3)
        self.mass = float(mass)
        self.ts = float(ts)

    def _decay_to(self, now: float) -> None:
        if now > self.ts:
            self.mass *= 2.0 ** (-(now - self.ts) / self.half_life)
            self.ts = now

    def add(self, amount: float, now: float) -> None:
        self._decay_to(now)
        self.mass += amount

    def value(self, now: float) -> float:
        if now <= self.ts:
            return self.mass
        return self.mass * 2.0 ** (-(now - self.ts) / self.half_life)

    def rate(self, now: float) -> float:
        """Decayed events-per-second estimate."""
        return self.value(now) * _LN2 / self.half_life

    def merged(self, other: "DecayedCounter") -> "DecayedCounter":
        """A new counter holding both masses decayed to the later
        timestamp.  (a ⊕ b) ⊕ c == a ⊕ (b ⊕ c): decay is
        multiplicative in elapsed time, so the order of pairwise
        decays cannot change the final mass."""
        ts = max(self.ts, other.ts)
        return DecayedCounter(self.half_life,
                             self.value(ts) + other.value(ts), ts)

    def retune(self, half_life: float, now: float) -> None:
        """Change the half-life in place (drills shrink it so shares
        move on sub-second scales); mass is decayed under the OLD
        constant first so history is not re-interpreted."""
        self._decay_to(now)
        self.half_life = max(float(half_life), 1e-3)


class SpaceSavingSketch:
    """Bounded decayed top-K: Metwally space-saving over EWMA masses.

    A known key updates in O(1).  When the table is full, a new key
    replaces an approximately-coldest resident and INHERITS its decayed
    mass as `err` (the space-saving overestimate bound).  "Approximately
    coldest" is amortized: one O(K log K) harvest collects the coldest
    ~K/8 keys into a pool that subsequent replacements consume, so the
    steady-state tail-miss cost is O(log K) amortized, not O(K).

    Not internally locked — the owning accumulator/journal serializes
    access (same contract as DecayedCounter)."""

    __slots__ = ("capacity", "half_life", "_e", "_pool")

    def __init__(self, capacity: int = 512, half_life: float = 30.0):
        self.capacity = max(int(capacity), 8)
        self.half_life = max(float(half_life), 1e-3)
        # key -> [mass, ts, err]
        self._e: dict[str, list] = {}
        # (key, mass_at_harvest) coldest-first pool, consumed from the end
        self._pool: list[tuple[str, float]] = []

    def __len__(self) -> int:
        return len(self._e)

    def _decayed(self, ent: list, now: float) -> float:
        if now <= ent[1]:
            return ent[0]
        return ent[0] * 2.0 ** (-(now - ent[1]) / self.half_life)

    def _harvest(self, now: float) -> None:
        n = max(self.capacity // 8, 1)
        cold = sorted(((self._decayed(ent, now), k)
                       for k, ent in self._e.items()))[:n]
        # coldest LAST so .pop() consumes coldest-first
        self._pool = [(k, m) for m, k in reversed(cold)]

    def touch(self, key: str, now: float, amount: float = 1.0) -> None:
        ent = self._e.get(key)
        if ent is not None:
            ent[0] = self._decayed(ent, now) + amount
            ent[1] = now
            return
        if len(self._e) < self.capacity:
            self._e[key] = [amount, now, 0.0]
            return
        victim_mass = 0.0
        victim = None
        while self._pool:
            k, harvest_mass = self._pool.pop()
            ent = self._e.get(k)
            if ent is None:
                continue  # already evicted by an earlier replacement
            m = self._decayed(ent, now)
            if m > 2.0 * harvest_mass + 1.0:
                continue  # got hot since the harvest: not a victim
            victim, victim_mass = k, m
            break
        if victim is None:
            self._harvest(now)
            if self._pool:
                victim, victim_mass = self._pool.pop()
                victim_mass = self._decayed(self._e[victim], now)
        if victim is None:  # capacity >= 8 keys, all vanished: degenerate
            self._e[key] = [amount, now, 0.0]
            return
        del self._e[victim]
        # space-saving inheritance: the newcomer may BE the victim's
        # successor in disguise — carry the evicted mass as both count
        # floor and error bound
        self._e[key] = [victim_mass + amount, now, victim_mass]

    def top(self, now: float, k: int = 0) -> list[dict]:
        """Hottest-first [{key, mass, err}]; k=0 returns all."""
        rows = [{"key": key, "mass": self._decayed(ent, now),
                 "err": ent[2]}
                for key, ent in self._e.items()]
        rows.sort(key=lambda r: -r["mass"])
        return rows[:k] if k else rows

    def retune(self, half_life: float, now: float) -> None:
        for ent in self._e.values():
            ent[0] = self._decayed(ent, now)
            ent[1] = now
        self.half_life = max(float(half_life), 1e-3)


class _VolumeHeat:
    """Per-volume decayed signals (all guarded by the accumulator's
    lock).  error share = errors / (reads + errors) over the decay
    window — a volume serving 500s gets hot in the WRONG way and the
    placement consumer must see that."""

    __slots__ = ("reads", "bytes", "writes", "cache_hits", "errors",
                 "trace_id", "trace_ts")

    def __init__(self, half_life: float):
        self.reads = DecayedCounter(half_life)
        self.bytes = DecayedCounter(half_life)
        self.writes = DecayedCounter(half_life)
        self.cache_hits = DecayedCounter(half_life)
        self.errors = DecayedCounter(half_life)
        self.trace_id = ""     # latest sampled trace that touched it
        self.trace_ts = 0.0

    def doc(self, now: float) -> dict:
        reads = self.reads.rate(now)
        errors = self.errors.rate(now)
        total = reads + errors
        return {
            "read_rate": round(reads, 4),
            "byte_rate": round(self.bytes.rate(now), 1),
            "write_rate": round(self.writes.rate(now), 4),
            "cache_hit_rate": round(self.cache_hits.rate(now), 4),
            "error_rate": round(errors, 4),
            "error_share": round(errors / total, 4) if total > 1e-9
            else 0.0,
            "mass": round(self.reads.value(now), 3),
            "trace": self.trace_id,
        }


class HeatAccumulator:  # weedlint: concurrent-class
    """One per VOLUME SERVER (never process-global: co-located test
    fixtures must not pool heat, and the master attributes per peer).
    Fed from the HTTP router hook, the framed-TCP handlers and the
    needle-cache callbacks — all request threads, hence every public
    method is a thread root."""

    def __init__(self, server: str = "", half_life: float = 30.0,
                 top_k: int = 512, enabled: bool = True):
        self.server = server
        self.enabled = bool(enabled)
        self.half_life = float(half_life)  # guarded-by: _lock
        self._lock = threading.Lock()
        self._vols: dict[int, _VolumeHeat] = {}  # guarded-by: _lock
        self._sketch = SpaceSavingSketch(top_k, half_life)  # guarded-by: _lock
        self._noted = 0  # guarded-by: _lock

    # --- chokepoint feeds (hot path: keep the critical section tiny) --

    def _vol(self, vid: int) -> _VolumeHeat:  # holds: _lock
        vh = self._vols.get(vid)
        if vh is None:
            vh = self._vols[vid] = _VolumeHeat(self.half_life)
        return vh

    def note_http(self, method: str, path: str, status: int,
                  nbytes: int, trace_id: str = "") -> None:
        """Router.dispatch hook: every HTTP response, object routes
        only (the fid regex gates before any locking)."""
        m = _FID_PATH_RE.match(path)
        if m is None:
            return
        try:
            vid = int(m.group(1))
            fid = path[1:].partition("?")[0]
            if method in ("GET", "HEAD"):
                if status >= 500:
                    self.note_error(vid)
                elif status < 400:
                    self.note_read(vid, nbytes, fid=fid,
                                   trace_id=trace_id)
            elif status < 500:
                self.note_write(vid, nbytes)
            else:
                self.note_error(vid)
        except Exception:
            pass  # accounting must never break the serving path

    def note_read(self, vid: int, nbytes: int, fid: str = "",
                  trace_id: str = "") -> None:
        now = time.time()
        with self._lock:
            vh = self._vol(vid)
            vh.reads.add(1.0, now)
            if nbytes:
                vh.bytes.add(float(nbytes), now)
            if trace_id:
                vh.trace_id, vh.trace_ts = trace_id, now
            if fid:
                self._sketch.touch(fid, now)
            self._noted += 1

    def note_write(self, vid: int, nbytes: int = 0) -> None:
        now = time.time()
        with self._lock:
            vh = self._vol(vid)
            vh.writes.add(1.0, now)
            if nbytes:
                vh.bytes.add(float(nbytes), now)
            self._noted += 1

    def note_error(self, vid: int) -> None:
        now = time.time()
        with self._lock:
            self._vol(vid).errors.add(1.0, now)
            self._noted += 1

    def note_cache_hit(self, vid: int, key: int, nbytes: int) -> None:
        """needle_cache on_hit callback: hit MASS is a distinct signal
        (a fully cache-absorbed volume still holds the working set)."""
        now = time.time()
        with self._lock:
            self._vol(vid).cache_hits.add(1.0, now)
            self._sketch.touch(f"{vid},{key:x}", now, 0.5)

    def note_cache_admit(self, vid: int, key: int) -> None:
        """needle_cache on_admit callback: admission is the cache's own
        popularity verdict — boost the needle in the sketch."""
        now = time.time()
        with self._lock:
            self._sketch.touch(f"{vid},{key:x}", now, 1.0)

    # --- TCP plane (tcp.py _handle_one) -------------------------------

    def note_native(self, op: str, vid: int, nbytes: int,
                    fid: str = "", error: bool = False) -> None:
        if error:
            self.note_error(vid)
        elif op == "R":
            ctx = _trace_context.current_sampled()
            self.note_read(vid, nbytes, fid=fid,
                           trace_id=ctx.trace_id if ctx else "")
        else:  # W / D: write-side churn
            self.note_write(vid, nbytes)

    # --- snapshots -----------------------------------------------------

    def set_half_life(self, half_life: float) -> None:
        """Retune decay in place (scenario drills shrink it so a head
        shift shows within seconds)."""
        now = time.time()
        with self._lock:
            self.half_life = max(float(half_life), 1e-3)
            for vh in self._vols.values():
                for c in (vh.reads, vh.bytes, vh.writes, vh.cache_hits,
                          vh.errors):
                    c.retune(half_life, now)
            self._sketch.retune(half_life, now)

    def snapshot(self, top_k: int = 64) -> dict:
        """The wire/debug doc: decayed to NOW, JSON-ready."""
        now = time.time()
        with self._lock:
            vols = {str(vid): vh.doc(now)
                    for vid, vh in self._vols.items()}
            needles = [{"fid": r["key"], "mass": round(r["mass"], 3),
                        "err": round(r["err"], 3)}
                       for r in self._sketch.top(now, top_k)]
            noted = self._noted
            half_life = self.half_life
        return {"server": self.server, "ts": round(now, 3),
                "half_life_s": half_life, "noted": noted,
                "volumes": vols, "needles": needles}

    def status(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "half_life_s": self.half_life,
                    "volumes": len(self._vols),
                    "sketch_keys": len(self._sketch),
                    "noted": self._noted}


class HeatShipper:
    """Periodic snapshot shipper to POST /cluster/heat/ingest — the
    established transport contract (bounded pending buffer, leader-
    follow rotation on failure, loss counted, final best-effort flush
    on detach).  Time-driven rather than hook-driven: heat is a decayed
    STATE, so the freshest snapshot supersedes older ones and the
    buffer holds at most a short tail for a master that just came
    back."""

    def __init__(self, heat: HeatAccumulator, server: str,
                 master_url_fn: Optional[Callable[[], str]] = None,
                 interval: float = 1.0, buffer_cap: int = 8,
                 local_journal: Optional["ClusterHeatJournal"] = None):
        self.heat = heat
        self.server = server
        self.master_url_fn = master_url_fn
        self.interval = interval
        self.local_journal = local_journal
        self.buffer_cap = buffer_cap
        self._buf: deque[dict] = deque()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # shared leader-follow policy (utils/leader.py) — internally locked
        from ..utils.leader import LeaderFollowingTransport
        self.transport = LeaderFollowingTransport(master_url_fn,
                                                  name=f"heat:{server}")
        self.shipped = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock

    def attach(self) -> "HeatShipper":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"heat-ship:{self.server}")
        self._thread.start()
        return self

    def detach(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._snap()
        self._flush(timeout=0.5)

    def _snap(self) -> None:  # thread-entry
        try:
            doc = self.heat.snapshot()
        except Exception:
            return
        with self._lock:
            if len(self._buf) >= self.buffer_cap:
                self._buf.popleft()  # stale state: newest wins
                self.dropped += 1
                self._count_drop()
            self._buf.append(doc)

    def _count_drop(self) -> None:  # holds: _lock
        try:
            from ..stats.metrics import heat_metrics
            heat_metrics().snapshots_dropped.inc()
        except Exception:
            pass

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self._snap()
            self._flush()

    def _flush(self, timeout: float = 3.0) -> None:
        with self._lock:
            if not self._buf:
                return
            batch = list(self._buf)
            self._buf.clear()
        if self.local_journal is not None:
            self.local_journal.ingest(self.server, batch)
            with self._lock:
                self.shipped += len(batch)
            return
        try:
            # telemetry must never trace itself (same rule as spans)
            with _trace_context.scope(_trace_context.NOT_SAMPLED):
                self.transport.post("/cluster/heat/ingest",
                                    {"server": self.server,
                                     "snapshots": batch},
                                    timeout=timeout)
            with self._lock:
                self.shipped += len(batch)
        except Exception:
            # master down / not elected: stale heat is worthless — the
            # batch is LOST and counted; the transport rotated to the
            # next master and re-learns the leader from ingest replies
            with self._lock:
                self.dropped += len(batch)
                self._count_drop()


class ClusterHeatJournal:  # weedlint: concurrent-class
    """The master's merged heat view + head-set shift detector.
    Reached concurrently from the threaded HTTP router (ingest POSTs,
    /cluster/heat GETs) and the telemetry loop."""

    def __init__(self, head_size: int = 5, trail_s: float = 10.0,
                 head_min_share: float = 0.15,
                 shift_min_share: float = 0.25,
                 cold_share: float = 0.05,
                 flash_share: float = 0.5,
                 min_event_interval: float = 5.0,
                 stale_s: float = 15.0,
                 rack_fn: Optional[Callable[[str], str]] = None):
        self.head_size = head_size
        self.trail_s = trail_s
        self.head_min_share = head_min_share
        self.shift_min_share = shift_min_share
        self.cold_share = cold_share
        self.flash_share = flash_share
        self.min_event_interval = min_event_interval
        self.stale_s = stale_s
        self.rack_fn = rack_fn
        self._lock = threading.Lock()
        self._peers: dict[str, dict] = {}  # guarded-by: _lock
        # (ts, {vid: share}) trailing head-share history
        self._history: deque = deque(maxlen=256)  # guarded-by: _lock
        self._last_event: dict[int, float] = {}  # guarded-by: _lock
        self._shifts: deque = deque(maxlen=32)  # guarded-by: _lock
        self.ingested = 0  # guarded-by: _lock
        # post-ingest hook: called OUTSIDE _lock with each merged view
        # — the heat autoscaler's event-driven wake (set by the master,
        # mirroring ClusterEventJournal.on_ingest)
        self.on_ingest: Optional[Callable[[dict], None]] = None

    # --- ingest --------------------------------------------------------

    def ingest(self, server: str, snapshots: list[dict]) -> int:
        if not snapshots:
            return 0
        latest = max(snapshots,
                     key=lambda s: float(s.get("ts") or 0.0))
        with self._lock:
            self._peers[server] = latest
            self.ingested += len(snapshots)
        self._after_ingest()
        return len(snapshots)

    def _after_ingest(self) -> None:
        now = time.time()
        merged = self.merged(now)
        self._update_gauges(merged)
        self._detect_shift(merged, now)
        hook = self.on_ingest
        if hook is not None:
            try:
                hook(merged)
            except Exception:
                pass  # a consumer bug must never fail heat ingest

    # --- merge ---------------------------------------------------------

    def merged(self, now: Optional[float] = None) -> dict:
        """Cross-peer per-volume heat: rates summed (rates — unlike
        masses with differing half-lives — compose), holders listed,
        exemplar trace kept from the freshest peer that saw one."""
        now = time.time() if now is None else now
        with self._lock:
            peers = dict(self._peers)
        vols: dict[int, dict] = {}
        needle_mass: dict[str, float] = {}
        for server, snap in peers.items():
            if now - float(snap.get("ts") or 0.0) > self.stale_s:
                continue
            for vid_s, doc in (snap.get("volumes") or {}).items():
                try:
                    vid = int(vid_s)
                except ValueError:
                    continue
                agg = vols.setdefault(vid, {
                    "volume": vid, "read_rate": 0.0, "byte_rate": 0.0,
                    "write_rate": 0.0, "cache_hit_rate": 0.0,
                    "error_rate": 0.0, "servers": [], "trace": ""})
                for k in ("read_rate", "byte_rate", "write_rate",
                          "cache_hit_rate", "error_rate"):
                    agg[k] = round(agg[k] + float(doc.get(k) or 0.0), 4)
                agg["servers"].append(server)
                if doc.get("trace") and not agg["trace"]:
                    agg["trace"] = doc["trace"]
            for row in snap.get("needles") or []:
                fid = row.get("fid")
                if fid:
                    needle_mass[fid] = needle_mass.get(fid, 0.0) + \
                        float(row.get("mass") or 0.0)
        for agg in vols.values():
            total = agg["read_rate"] + agg["error_rate"]
            agg["error_share"] = round(
                agg["error_rate"] / total, 4) if total > 1e-9 else 0.0
            # the ranking signal: served reads plus cache-absorbed hits
            agg["heat"] = round(
                agg["read_rate"] + agg["cache_hit_rate"], 4)
        return {"volumes": vols, "needles": needle_mass,
                "peers": peers, "ts": now}

    @staticmethod
    def _shares(vols: dict[int, dict]) -> dict[int, float]:
        total = sum(v["heat"] for v in vols.values())
        if total <= 1e-9:
            return {}
        return {vid: v["heat"] / total for vid, v in vols.items()}

    def _head(self, shares: dict[int, float]) -> list[int]:
        ranked = sorted(shares, key=lambda v: -shares[v])
        return [v for v in ranked[:self.head_size]
                if shares[v] >= self.head_min_share]

    # --- gauges --------------------------------------------------------

    def _update_gauges(self, merged: dict) -> None:
        try:
            from ..stats.metrics import heat_metrics
            m = heat_metrics()
        except Exception:
            return
        vols = merged["volumes"]
        m.volume_heat.clear()
        per_server: dict[str, float] = {}
        for vid, agg in vols.items():
            m.volume_heat.set(str(vid), agg["heat"])
            share = agg["heat"] / max(len(agg["servers"]), 1)
            for s in agg["servers"]:
                per_server[s] = per_server.get(s, 0.0) + share
        m.imbalance.clear()
        m.imbalance.set("server", _imbalance(per_server.values()))
        if self.rack_fn is not None:
            racks: dict[str, float] = {}
            for s, h in per_server.items():
                try:
                    rack = self.rack_fn(s) or "unknown"
                except Exception:
                    rack = "unknown"
                racks[rack] = racks.get(rack, 0.0) + h
            m.imbalance.set("rack", _imbalance(racks.values()))

    # --- shift detection ----------------------------------------------

    def _detect_shift(self, merged: dict, now: float) -> None:
        shares = self._shares(merged["volumes"])
        with self._lock:
            # thin the history to ~trail_s/8 resolution
            if not self._history or \
                    now - self._history[-1][0] >= self.trail_s / 8.0:
                self._history.append((now, shares))
            trailing = None
            for ts, snap in reversed(self._history):
                if now - ts >= self.trail_s:
                    trailing = snap
                    break
        if not shares or trailing is None:
            return  # startup grace: no trailing baseline yet
        head = self._head(shares)
        trail_head = set(self._head(trailing))
        for vid in head:
            share = shares[vid]
            prev = trailing.get(vid, 0.0)
            if vid in trail_head or share < self.shift_min_share:
                continue
            with self._lock:
                if now - self._last_event.get(vid, 0.0) < \
                        self.min_event_interval:
                    continue
                self._last_event[vid] = now
            agg = merged["volumes"].get(vid) or {}
            flash = prev <= self.cold_share and share >= self.flash_share
            etype = "flash_crowd" if flash else "heat_shift"
            ev = _events.emit(
                etype,
                trace_id=agg.get("trace") or None,
                volume=vid, share=round(share, 3),
                prev_share=round(prev, 3),
                read_rate=agg.get("read_rate", 0.0),
                servers=list(agg.get("servers") or []),
                window_s=round(self.trail_s, 1))
            with self._lock:
                self._shifts.append(ev.to_dict())

    # --- the /cluster/heat document -----------------------------------

    def to_doc(self, top_needles: int = 20) -> dict:
        now = time.time()
        merged = self.merged(now)
        vols = merged["volumes"]
        shares = self._shares(vols)
        ranked = sorted(vols.values(), key=lambda v: -v["heat"])
        for row in ranked:
            row["share"] = round(shares.get(row["volume"], 0.0), 4)
        needles = sorted(merged["needles"].items(),
                         key=lambda kv: -kv[1])
        counts = [m for _, m in needles if m > 0.0]
        zipf_s = _zipf_fit(counts)
        per_server = {s: round(sum(
            v["heat"] / max(len(v["servers"]), 1)
            for v in vols.values() if s in v["servers"]), 4)
            for s in merged["peers"]}
        with self._lock:
            shifts = list(self._shifts)
            ingested = self.ingested
        return {
            "ts": round(now, 3),
            "volumes": ranked,
            "head": {"volumes": self._head(shares),
                     "min_share": self.head_min_share,
                     "size": self.head_size},
            "zipf": {"s": zipf_s, "distinct": len(counts),
                     "top": [{"fid": f, "mass": round(m, 3)}
                             for f, m in needles[:top_needles]]},
            "imbalance": {
                "server": _imbalance(per_server.values()),
                "per_server": per_server},
            "peers": {s: {"ts": snap.get("ts"),
                          "half_life_s": snap.get("half_life_s"),
                          "volumes": len(snap.get("volumes") or {}),
                          "stale": now - float(snap.get("ts") or 0.0)
                          > self.stale_s}
                      for s, snap in merged["peers"].items()},
            "shifts": shifts,
            "ingested": ingested,
        }


def _imbalance(values) -> float:
    """max/mean heat ratio across a scope (1.0 = perfectly balanced);
    0.0 when the scope is empty or entirely cold."""
    vals = [v for v in values]
    if not vals:
        return 0.0
    mean = sum(vals) / len(vals)
    if mean <= 1e-9:
        return 0.0
    return round(max(vals) / mean, 3)


def _zipf_fit(counts: list[float]) -> float:
    """Live Zipf skew over merged needle masses — the recorder's
    estimator (scenarios/replay.estimate_zipf_s), imported lazily to
    keep observability -> scenarios a runtime edge, not an import-time
    cycle."""
    if len(counts) < 3:
        return 0.0
    try:
        from ..scenarios.replay import estimate_zipf_s
        return estimate_zipf_s(counts)
    except Exception:
        return 0.0
