"""Structured cluster event journal: the WHAT-happened record.

PRs 1/4/6 made degraded moments *counted* (worker_restarts,
engine_fallbacks, corrupt_shards, ...) and *traced* (pipeline.retry /
pipeline.fallback spans) — but a counter says only "3 since boot" and a
span ring evicts under load, so the operator question "what went wrong
on this cluster in the last hour?" still had no answer.  This module is
that answer: a bounded, thread-safe ring of TYPED events emitted at the
exact chokepoints that already bump the degraded-path counters:

    from seaweedfs_tpu.observability import events as _events
    _events.emit("worker_restart", kind="staged", restarts=2)

Each event carries a type from EVENT_TYPES (with a default severity), a
wall timestamp, the emitting server (from the request thread-local when
inside one), the ACTIVE distributed-trace id (observability/context.py)
when the moment happened under a sampled trace — the join key back to
the stitched cluster trace that explains it — and a small details dict.

Served per server at GET /debug/events (type/severity/since filters)
and shipped master-ward by EventShipper (the PR-6 TraceShipper
transport pattern: chained hook, bounded buffer, batch POST, loss
counted never backpressured) into the master's ClusterEventJournal at
GET /cluster/events — the cluster-wide journal the alerting engine
(observability/alerts.py) annotates with alert_fired/alert_resolved
transitions.

Cost discipline: emit() is only ever called on degraded paths and alert
transitions — never on a clean hot path — so the journal needs no
enable gate; the ring is bounded and eviction is counted (`dropped`).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Optional

from . import context as _trace_context

# severity order matters: min_severity filters compare by rank
SEVERITIES = ("info", "warning", "error", "critical")
SEVERITY_RANK = {s: i for i, s in enumerate(SEVERITIES)}

# the event-type registry: every emit site uses one of these types, and
# each carries its default severity.  tools/check_health_keys.py lints
# this table against stats/aggregate.py HEALTH_FAMILIES and the default
# alert rules so a degraded counter added to one table but not the
# others fails tier-1 instead of drifting silently.
EVENT_TYPES = {
    # degraded-path chokepoints (each shadows a /metrics counter)
    "worker_restart": "warning",        # ec/overlap.py supervisor respawn
    "engine_fallback": "warning",       # ec/streaming.py + ec/codec.py
    "shard_corrupt": "error",           # ec/integrity.py note_corruption
    "scrub_repair": "warning",          # scrubber quarantine+rebuild ok
    "scrub_repair_failed": "error",     # rebuild raised; rot remains
    "scrub_unrepairable": "critical",   # < k clean shards left
    "degraded_bind": "warning",         # TCP plane bind failed
    "peer_stale": "warning",            # master scrape lost a peer
    # alerting engine state transitions (observability/alerts.py)
    "alert_pending": "info",
    "alert_fired": "error",
    "alert_resolved": "info",
    # flight recorder captures (observability/flightrecorder.py)
    "flight_capture": "info",
    # rebuild/rebalance coordinator (ops/coordinator.py, master-side)
    "ec_under_replicated": "error",  # volume dropped below k+1 clean
    "repair_planned": "info",        # coordinator queued + started one
    "repair_done": "info",           # volume back to full shard set
    "repair_failed": "error",        # plan step failed; will re-plan
    "rebalance_move": "info",        # one budgeted shard move executed
    # request-plane graceful degradation (utils/deadline.py,
    # utils/admission.py, utils/backoff.py)
    "load_shed": "warning",          # admission control answered 503
    "deadline_exceeded": "warning",  # X-Weed-Deadline budget spent: 504
    "retry_budget_exhausted": "warning",  # token bucket denied a retry
    # workload flight recorder (observability/reqlog.py)
    "reqlog_dropped": "warning",     # access records lost (ring/ship)
    # event-loop serving dataplane (utils/eventloop.py)
    "dataplane_conn_abort": "warning",  # conn torn down mid-flight
    # heat-telemetry shift detector (observability/heat.py, master):
    # both are watched by default `journal_event` alert rules — the
    # heat.HEAT_EVENT_TYPES tuple is W401-linted against this table
    # and default_rules() so neither side can drift
    "heat_shift": "warning",   # a volume newly entered the Zipf head
    "flash_crowd": "error",    # a COLD volume took the head outright
    # resource-ledger loop-stall relay (observability/ledger.py,
    # master): the ledger.LEDGER_EVENT_TYPES tuple is W401-linted the
    # same way HEAT_EVENT_TYPES is
    "loop_stall": "error",     # reactor loop blocked past threshold
    # heat autoscaler actuations (ops/autoscaler.py, master): every
    # replica_grow carries the causing heat alert id + exemplar trace;
    # tier_committed is journaled only after the raft commit record
    "replica_grow": "info",    # read replica added for a hot volume
    "replica_shrink": "info",  # hold-down elapsed: added replica drops
    "tier_committed": "info",  # cold .dat committed to remote backend
    "tier_recall": "info",     # heat returned: tiered .dat recalled
    "autoscale_failed": "error",  # a grow/shrink/tier/recall leg failed
}

# HEALTH_FAMILIES key (stats/aggregate.py) -> the event type emitted at
# the chokepoint that bumps that family's counter.  The check_health_keys
# lint walks this mapping both ways.
HEALTH_EVENT_TYPES = {
    "worker_restarts": "worker_restart",
    "engine_fallbacks": "engine_fallback",
    "degraded_binds": "degraded_bind",
    "corrupt_shards": "shard_corrupt",
    "scrub_repairs": "scrub_repair",
    "ec_under_replicated": "ec_under_replicated",
    "coordinator_repair_failures": "repair_failed",
    "requests_shed": "load_shed",
    "deadline_exceeded": "deadline_exceeded",
    "retry_budget_exhausted": "retry_budget_exhausted",
    "reqlog_records_dropped": "reqlog_dropped",
    "dataplane_conn_aborts": "dataplane_conn_abort",
    "loop_lag": "loop_stall",
    "autoscale_failures": "autoscale_failed",
}


class Event:
    """One journaled cluster event.  `id` is namespaced like span ids
    (process-unique salt + sequence) so the master-side journal can
    dedup re-ships and co-located in-process shippers."""

    __slots__ = ("type", "severity", "server", "ts", "trace_id",
                 "details", "seq", "id")

    def __init__(self, type_: str, severity: str, server: Optional[str],
                 ts: float, trace_id: Optional[str], details: dict,
                 seq: int, id_: str):
        self.type = type_
        self.severity = severity
        self.server = server
        self.ts = ts
        self.trace_id = trace_id
        self.details = details
        self.seq = seq
        self.id = id_

    def to_dict(self) -> dict:
        d = {"id": self.id, "seq": self.seq, "type": self.type,
             "severity": self.severity, "ts": round(self.ts, 3),
             "details": self.details}
        if self.server:
            d["server"] = self.server
        if self.trace_id:
            d["trace"] = self.trace_id
        return d


def _match(e: dict, type_: Optional[str] = None,
           severity: Optional[str] = None,
           min_severity: Optional[str] = None,
           since_seq: int = 0, since_ts: float = 0.0) -> bool:
    """Shared filter predicate over event DICTS (the wire shape)."""
    if type_ and e.get("type") != type_:
        return False
    if severity and e.get("severity") != severity:
        return False
    if min_severity:
        if SEVERITY_RANK.get(e.get("severity"), 0) < \
                SEVERITY_RANK.get(min_severity, 0):
            return False
    if since_seq and int(e.get("seq") or 0) <= since_seq:
        return False
    if since_ts and float(e.get("ts") or 0.0) <= since_ts:
        return False
    return True


class EventJournal:
    """Bounded thread-safe ring of typed events (one per process)."""

    def __init__(self, capacity: int = 2048,
                 namespace: Optional[str] = None):
        self._events: deque[Event] = deque(maxlen=capacity)  # guarded-by: _lock
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        # same salting rationale as the tracer: bare pids collide across
        # containerized hosts and the master journal dedups by event id
        self.namespace = namespace or (
            f"e{os.getpid():x}x{os.urandom(3).hex()}")
        # ring evictions — a truncated journal says so
        self.dropped = 0  # guarded-by: _lock
        # shipping hook (EventShipper): called with every emitted Event
        self.on_emit: Optional[Callable[[Event], None]] = None
        # server identities of the attached shippers: when exactly ONE
        # server owns this process's journal (the production shape),
        # emits from background threads (drainers, supervisors) that
        # carry no request thread-local still stamp correctly; with
        # co-located servers the stamp is AMBIGUOUS and the event ships
        # unattributed rather than letting whichever shipper's copy
        # wins the collector's dedup claim it
        self._servers: list[str] = []  # guarded-by: _lock

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0  # weedlint: disable=W501 maxlen is immutable configuration, not ring state

    def register_server(self, server: str) -> None:
        with self._lock:
            self._servers.append(server)

    def unregister_server(self, server: str) -> None:
        with self._lock:
            if server in self._servers:
                self._servers.remove(server)

    def _default_server(self) -> Optional[str]:
        with self._lock:
            unique = set(self._servers)
            return next(iter(unique)) if len(unique) == 1 else None

    def emit(self, type_: str, severity: Optional[str] = None,  # thread-entry
             server: Optional[str] = None,
             trace_id: Optional[str] = None, **details) -> Event:
        """Journal one event — from ANY thread (drainers, supervisors,
        scan loops; the thread-entry annotation makes the lockset
        checker model that).  Severity defaults from EVENT_TYPES; the
        trace id defaults to the calling thread's ACTIVE sampled trace
        context and the server to the request's owning-server identity
        (both thread-local reads — emit sites never plumb identity)."""
        if severity is None:
            severity = EVENT_TYPES.get(type_, "info")
        if trace_id is None:
            ctx = _trace_context.current_sampled()
            trace_id = ctx.trace_id if ctx is not None else None
        if server is None:
            server = _trace_context.current_server() or \
                self._default_server()
        with self._lock:
            self._seq += 1
            ev = Event(type_, severity, server, time.time(), trace_id,
                       details, self._seq,
                       f"{self.namespace}.{self._seq:x}")
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)
        hook = self.on_emit
        if hook is not None:
            try:
                hook(ev)
            except Exception:
                pass  # shipping must never break the degraded path
        return ev

    def snapshot(self) -> list[Event]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def query(self, type_: Optional[str] = None,
              severity: Optional[str] = None,
              min_severity: Optional[str] = None,
              since_seq: int = 0, since_ts: float = 0.0,
              limit: int = 256) -> list[dict]:
        """Filtered event dicts in chronological order, keeping the most
        RECENT `limit` matches (a tail, not a head — the fresh end is
        what an operator asks for)."""
        out = [e.to_dict() for e in self.snapshot()]
        out = [e for e in out
               if _match(e, type_, severity, min_severity,
                         since_seq, since_ts)]
        return out[-max(int(limit), 0):] if limit else out


class ClusterEventJournal:  # weedlint: concurrent-class
    """The master's merged journal: per-server journals ship here
    (EventShipper), dedup'd by event id, bounded by oldest-first
    eviction — the /cluster/events store.  Reached concurrently from
    the threaded HTTP router (ingest POSTs + query GETs)."""

    def __init__(self, capacity: int = 8192):
        self.capacity = capacity
        self._events: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock
        self._lock = threading.Lock()
        self.dropped = 0  # guarded-by: _lock
        # consumer hook: called OUTSIDE the lock with each batch of
        # newly-accepted (non-duplicate) event dicts — the rebuild
        # coordinator subscribes here instead of polling the journal
        self.on_ingest: Optional[Callable[[list[dict]], None]] = None

    def ingest(self, server: str, events: list[dict]) -> int:
        accepted: list[dict] = []
        with self._lock:
            for e in events:
                eid = e.get("id")
                if not eid or eid in self._events:
                    continue  # duplicate ship (chained shippers, retry)
                e = dict(e)
                # the transport's identity is only a LABEL of who
                # shipped, never a claim of who emitted: an event that
                # arrives unattributed (ambiguous co-located journal)
                # stays unattributed
                e["via"] = server
                self._events[eid] = e
                accepted.append(e)
            while len(self._events) > self.capacity:
                self._events.popitem(last=False)
                self.dropped += 1
        hook = self.on_ingest
        if hook is not None and accepted:
            try:
                hook(list(accepted))
            except Exception:
                pass  # a broken consumer must never break ingest
        return len(accepted)

    def query(self, type_: Optional[str] = None,
              severity: Optional[str] = None,
              min_severity: Optional[str] = None,
              since_ts: float = 0.0, server: Optional[str] = None,
              limit: int = 256) -> list[dict]:
        with self._lock:
            events = list(self._events.values())
        out = [e for e in events
               if _match(e, type_, severity, min_severity, 0, since_ts)
               and (not server or e.get("server") == server)]
        # shipped batches interleave across servers: order by time for a
        # coherent cluster timeline (id breaks ts ties stably)
        out.sort(key=lambda e: (float(e.get("ts") or 0.0),
                                str(e.get("id"))))
        return out[-max(int(limit), 0):] if limit else out

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


class EventShipper:
    """Ship this process's journal to the master's cluster journal —
    the TraceShipper transport pattern (collector.py): chained on_emit
    hook, bounded buffer, batch POST on a flush thread, loss COUNTED
    (never backpressure on the emitting path), `local_journal`
    short-circuit for the master's own events."""

    def __init__(self, journal: EventJournal, server: str,
                 master_url_fn: Optional[Callable[[], str]] = None,
                 local_journal: Optional[ClusterEventJournal] = None,
                 batch_size: int = 64, flush_interval: float = 0.5,
                 buffer_cap: int = 1024):
        self.journal = journal
        self.server = server
        self.master_url_fn = master_url_fn
        self.local_journal = local_journal
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.buffer_cap = buffer_cap
        self._buf: deque[Event] = deque()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # hook-chain handoff: written by attach()/detach() on the
        # server's lifecycle thread before the flush thread starts /
        # after it stops; read lock-free on every emit
        self._prev_hook: Optional[Callable[[Event], None]] = None
        # shared leader-follow policy (utils/leader.py) — internally locked
        from ..utils.leader import LeaderFollowingTransport
        self.transport = LeaderFollowingTransport(master_url_fn,
                                                  name=f"events:{server}")
        self.shipped = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock

    def attach(self) -> "EventShipper":
        self._prev_hook = self.journal.on_emit  # weedlint: disable=W502 lifecycle handoff: runs before the flush thread starts
        self.journal.on_emit = self._on_event
        self.journal.register_server(self.server)
        self._thread = threading.Thread(target=self._flush_loop,
                                        daemon=True,
                                        name=f"event-ship:{self.server}")
        self._thread.start()
        return self

    def detach(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self.journal.on_emit is self._on_event:
            self.journal.on_emit = self._prev_hook
        self.journal.unregister_server(self.server)
        # final flush with a sub-second timeout: at cluster teardown the
        # master is often already gone and stop() must not hang
        self._flush(timeout=0.5)

    def _on_event(self, ev: Event) -> None:  # thread-entry
        # called on whatever thread emitted (drainers, scan loops);
        # a detached shipper left mid-chain degrades to a pass-through
        if not self._stop.is_set():
            with self._lock:
                if len(self._buf) >= self.buffer_cap:
                    self.dropped += 1
                else:
                    self._buf.append(ev)
                    if len(self._buf) >= self.batch_size:
                        self._wake.set()
        prev = self._prev_hook
        if prev is not None:
            prev(ev)

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval)
            self._wake.clear()
            self._flush()

    def _flush(self, timeout: float = 5.0) -> None:
        with self._lock:
            if not self._buf:
                return
            batch = list(self._buf)
            self._buf.clear()
        # the server stamp is decided at EMIT time (thread-local or the
        # journal's sole-shipper default) — a shipper must not claim
        # unattributed events as its own: with co-located servers both
        # chained shippers ship the same event id and whichever copy
        # wins the collector's dedup would misattribute it
        docs = [ev.to_dict() for ev in batch]
        if self.local_journal is not None:
            self.local_journal.ingest(self.server, docs)
            with self._lock:
                self.shipped += len(docs)
            return
        try:
            # shipping must never trace itself (same rule as spans)
            with _trace_context.scope(_trace_context.NOT_SAMPLED):
                self.transport.post("/cluster/events/ingest",
                                    {"server": self.server, "events": docs},
                                    timeout=timeout)
            with self._lock:
                self.shipped += len(docs)
        except Exception:
            # master down / not elected: the batch is LOST and counted;
            # the transport rotated to the next configured master and
            # re-learns the leader from ingest replies post-election.
            # Counter updates ride _lock: the flush thread and the
            # detach()-time final flush race these read-modify-writes
            with self._lock:
                self.dropped += len(docs)


# --- process-global journal --------------------------------------------------
# Every layer emits into ONE journal per process (like the tracer), so
# /debug/events and the shipper see worker restarts from ec/, scrub
# verdicts from volume_server/, and alert transitions from the master
# without plumbing a journal handle through each constructor.

_GLOBAL = EventJournal()


def get_journal() -> EventJournal:
    return _GLOBAL


def emit(type_: str, severity: Optional[str] = None,
         server: Optional[str] = None, trace_id: Optional[str] = None,
         **details) -> Event:
    """Module-level convenience: journal one event on the process-global
    journal (the one-liner the degraded-path chokepoints call)."""
    return _GLOBAL.emit(type_, severity=severity, server=server,
                        trace_id=trace_id, **details)
