"""Cluster resource ledger: per-request CPU / bytes / queue-wait cost.

PR 16 built the HEAT side of the Haystack story — which objects are
hot.  This module builds the COST side: who is consuming which serving
resource, right now.  Every request through the two ingress
chokepoints (utils/httpd.Router.dispatch, utils/framing.serve_frame)
is stamped with its thread-CPU time (`time.thread_time_ns` delta
measured ON the executing thread, so the reactor's worker handoff
attributes the worker's CPU, never the loop's), bytes in/out,
dispatch-queue wait (stamped by the reactor when it hands the parsed
request to the pool) and needle-cache hits/misses (thread-local
pending counts fed by the cache callbacks, settled per request), and
accumulated into BOUNDED per-route-class and per-client-key ledgers:

  - route classes are observability/reqlog.classify_route's axis —
    the same key capacity numbers and replayed workloads use;
  - client keys are the peer /24 prefix for now: the future
    multi-tenant QoS key, already shaped like one.

Decay discipline is the heat plane's: every cell is a set of
exponentially-decayed masses sharing ONE timestamp (one 2**(-dt/h)
per settle per cell), so `rate()` answers "per second, recently" and
the master-side merge can sum RATES across peers without clock games.

The same accumulator carries the two satellite signals the ledger
contextualizes:

  - reactor saturation: the dataplane loop's lag stats / queue depth /
    worker occupancy (utils/eventloop.py watchdog) ride each snapshot
    via `loop_stats_fn`, and a request that ran ON the loop thread
    past LOOP_STALL_THRESHOLD_S is recorded as a stall with its route
    and exemplar trace — the master-side detector relays it as a
    `loop_stall` journal event that the default alert rules page on;
  - continuous profiling: the windowed sampling profiler's current
    top/rising stacks (observability/profiler.WindowedProfiler) ride
    via `profile_fn`.

Shipping mirrors heat end to end: LedgerShipper posts rotating
snapshots to POST /cluster/ledger/ingest (leader-follow transport,
bounded buffer, loss counted never backpressure), the master's
ClusterLedgerJournal keeps the latest snapshot per peer, merges the
cluster view for GET /cluster/ledger, and `weed shell cluster.top`
renders it ranked by CPU share.

Cost discipline: accounting-off is ONE attribute check at each
chokepoint (`router.ledger is None`); settle is a couple of clock
reads, one route classification and one decayed-cell update per
table.  The bench `resource_ledger` section gates the whole plane
(ledger + always-on profiler) under 1% of read rps.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Optional

from . import context as _trace_context
from . import reqlog as _reqlog

# journal-event types the master-side stall detector emits; W401 lints
# this tuple against events.EVENT_TYPES and alerts.default_rules() so
# neither side can drift (the heat.HEAT_EVENT_TYPES contract)
LEDGER_EVENT_TYPES = ("loop_stall",)

# Prometheus families this plane registers (stats/metrics.py
# LedgerMetrics + the dataplane loop additions); W401 checks they stay
# registered so a renamed family cannot silently detach dashboards
LEDGER_METRIC_FAMILIES = (
    "SeaweedFS_ledger_route_cpu_rate",
    "SeaweedFS_ledger_route_queue_wait_rate",
    "SeaweedFS_ledger_route_bytes_rate",
    "SeaweedFS_ledger_snapshots_dropped_total",
    "SeaweedFS_dataplane_loop_lag_seconds",
    "SeaweedFS_dataplane_loop_stalls_total",
    "SeaweedFS_dataplane_queue_depth",
)

# a request that held the reactor LOOP thread longer than this is a
# stall: the loop could not accept, parse or flush anything else for
# the duration (the inline fast path's budget is microseconds)
LOOP_STALL_THRESHOLD_S = 0.25

_LN2 = math.log(2.0)

# per-thread pending needle-cache verdicts: the cache callbacks fire
# on the request's own executing thread (the store lookup runs inside
# dispatch/serve_frame), so a plain thread-local count per request
# needs no lock and no plumbed identity
_tls = threading.local()


def _client_key(peer: str) -> str:
    """The per-client ledger key: peer /24 prefix for IPv4 — coarse
    enough to bound cardinality, specific enough to name a tenant's
    subnet (the future QoS key).  Non-IPv4 peers key as themselves."""
    parts = peer.split(".")
    if len(parts) == 4:
        return ".".join(parts[:3]) + ".*"
    return peer or "?"


class _Cell:
    """One ledger row: decayed masses for every accounted resource,
    sharing a single decay timestamp so a settle costs ONE exponential
    regardless of how many resources it touches."""

    __slots__ = ("ts", "req", "cpu", "bin", "bout", "qwait", "hit",
                 "miss", "trace", "trace_ts")

    def __init__(self, ts: float):
        self.ts = ts
        self.req = 0.0
        self.cpu = 0.0
        self.bin = 0.0
        self.bout = 0.0
        self.qwait = 0.0
        self.hit = 0.0
        self.miss = 0.0
        self.trace = ""
        self.trace_ts = 0.0

    def decay(self, now: float, half_life: float) -> None:
        dt = now - self.ts
        if dt > 0.0:
            f = 2.0 ** (-dt / half_life)
            self.req *= f
            self.cpu *= f
            self.bin *= f
            self.bout *= f
            self.qwait *= f
            self.hit *= f
            self.miss *= f
        self.ts = now

    def add(self, now: float, half_life: float, cpu_s: float,
            bytes_in: float, bytes_out: float, queue_wait_s: float,
            hits: float, misses: float, trace_id: str) -> None:
        self.decay(now, half_life)
        self.req += 1.0
        self.cpu += cpu_s
        self.bin += bytes_in
        self.bout += bytes_out
        self.qwait += queue_wait_s
        self.hit += hits
        self.miss += misses
        if trace_id:
            self.trace, self.trace_ts = trace_id, now

    def doc(self, now: float, half_life: float) -> dict:
        """JSON rates decayed to `now`: mass * ln2 / h estimates the
        recent per-second rate (the DecayedCounter identity)."""
        self.decay(now, half_life)
        k = _LN2 / half_life
        return {
            "req_rate": round(self.req * k, 4),
            "cpu_rate": round(self.cpu * k, 6),
            "bytes_in_rate": round(self.bin * k, 1),
            "bytes_out_rate": round(self.bout * k, 1),
            "queue_wait_rate": round(self.qwait * k, 6),
            "cache_hit_rate": round(self.hit * k, 4),
            "cache_miss_rate": round(self.miss * k, 4),
            "cpu_mass": round(self.cpu, 6),
            "trace": self.trace,
        }


class RequestLedger:
    """Per-server resource accounting at the ingress chokepoints.

    `begin()` is called at dispatch/serve_frame ENTRY on the executing
    thread and returns an opaque token; `settle_http`/`settle_native`
    close the ledger entry with the response facts.  Both are gated by
    the caller on `router.ledger is None` so accounting-off costs one
    attribute check, and the settle body is wrapped by the CALLER in
    try/except — accounting must never break the serving path."""

    def __init__(self, server: str, half_life: float = 60.0,
                 max_routes: int = 64, max_clients: int = 256,
                 enabled: bool = True):
        self.server = server
        self.half_life = max(float(half_life), 1e-3)
        self.max_routes = int(max_routes)
        self.max_clients = int(max_clients)
        self.enabled = enabled
        self._lock = threading.Lock()
        self._routes: dict[str, _Cell] = {}  # guarded-by: _lock
        self._clients: dict[str, _Cell] = {}  # guarded-by: _lock
        self._noted = 0  # guarded-by: _lock
        self._evicted = 0  # guarded-by: _lock
        # most recent loop stall (route + exemplar trace) and a count:
        # the snapshot carries both and the master-side detector pages
        self._stalls = 0  # guarded-by: _lock
        self._last_stall: Optional[dict] = None  # guarded-by: _lock
        self._last_stall_note = 0.0  # guarded-by: _lock
        # wiring hooks (volume_server/server.py): reactor lag stats and
        # the windowed profiler's current summary ride each snapshot
        self.loop_stats_fn: Optional[Callable[[], dict]] = None
        self.profile_fn: Optional[Callable[[], dict]] = None

    # --- chokepoint hooks ----------------------------------------------

    @staticmethod
    def begin() -> tuple:
        """Entry stamp, ON the executing thread (thread CPU clocks are
        per-thread: a token minted on the loop and settled on a worker
        would charge the wrong thread).  Also resets this thread's
        pending needle-cache verdicts."""
        _tls.hits = 0
        _tls.misses = 0
        return (time.thread_time_ns(), time.perf_counter())

    def settle_http(self, token: tuple, method: str, path: str,
                    handler_name: str, status: int, bytes_in: int,
                    bytes_out: int, peer: str, trace_id: str = "",
                    queue_wait_s: float = 0.0,
                    query: Optional[dict] = None) -> None:
        route = _reqlog.classify_route(method, path, handler_name,
                                       query=query)
        self._settle(token, route, status, bytes_in, bytes_out, peer,
                     trace_id, queue_wait_s)

    def settle_native(self, token: tuple, op: bytes, status: int,
                      bytes_in: int, bytes_out: int, peer: str,
                      trace_id: str = "",
                      queue_wait_s: float = 0.0) -> None:
        route = _reqlog.NATIVE_ROUTES.get(
            op, f"native_{op.decode('latin-1')}")
        self._settle(token, route, status, bytes_in, bytes_out, peer,
                     trace_id, queue_wait_s)

    def _settle(self, token: tuple, route: str, status: int,
                bytes_in: int, bytes_out: int, peer: str,
                trace_id: str, queue_wait_s: float) -> None:
        cpu_s = max(time.thread_time_ns() - token[0], 0) / 1e9
        wall_s = time.perf_counter() - token[1]
        hits = getattr(_tls, "hits", 0)
        misses = getattr(_tls, "misses", 0)
        _tls.hits = 0
        _tls.misses = 0
        now = time.time()
        client = _client_key(peer)
        with self._lock:
            cell = self._routes.get(route)
            if cell is None:
                cell = self._table_insert(self._routes, route,
                                          self.max_routes, now)
            cell.add(now, self.half_life, cpu_s, float(bytes_in),
                     float(bytes_out), queue_wait_s, hits, misses,
                     trace_id)
            ccell = self._clients.get(client)
            if ccell is None:
                ccell = self._table_insert(self._clients, client,
                                           self.max_clients, now)
            ccell.add(now, self.half_life, cpu_s, float(bytes_in),
                      float(bytes_out), queue_wait_s, hits, misses,
                      trace_id)
            self._noted += 1
        # a request that held the reactor LOOP itself past the stall
        # threshold blocked every other connection for the duration:
        # record it with its route + exemplar trace so the master-side
        # detector can page naming the offender
        if wall_s >= LOOP_STALL_THRESHOLD_S and _on_loop_thread():
            self.note_stall(route, wall_s, trace_id)

    def _table_insert(self, table: dict, key: str, cap: int,  # holds: _lock
                      now: float) -> _Cell:
        """Bounded insert: past the cap the COLDEST row (smallest
        decayed request mass) is evicted — the ledger keeps the heavy
        hitters, exactly like the heat sketch keeps the Zipf head."""
        if len(table) >= cap:
            coldest, cold_mass = None, float("inf")
            for k, c in table.items():
                c.decay(now, self.half_life)
                if c.req < cold_mass:
                    coldest, cold_mass = k, c.req
            if coldest is not None:
                del table[coldest]
                self._evicted += 1
        cell = _Cell(now)
        table[key] = cell
        return cell

    # --- needle-cache verdicts (volume_server wiring) ------------------

    @staticmethod
    def note_cache_hit(vid: int, key: int, nbytes: int) -> None:
        """needle_cache on_hit callback (composed with the heat hook):
        counts into the CURRENT request's thread-local pending tally,
        settled into its route/client cells at request end."""
        _tls.hits = getattr(_tls, "hits", 0) + 1

    @staticmethod
    def note_cache_miss(vid: int, key: int) -> None:
        _tls.misses = getattr(_tls, "misses", 0) + 1

    # --- loop stalls ---------------------------------------------------

    def note_stall(self, route: str, lag_s: float,
                   trace_id: str = "") -> None:  # thread-entry
        """One loop-stall moment (from a settled on-loop request, or
        from the reactor watchdog mid-block).  Rate-limited so a
        watchdog observing the SAME block every tick records one
        stall, and counted into the `loop_lag` HEALTH_FAMILIES counter
        (SeaweedFS_dataplane_loop_stalls_total) so the cluster rollup
        pages even before a snapshot ships."""
        if route.startswith("/"):
            # the watchdog only knows the RAW path the loop was busy
            # on (the inline fast path is GET-only); classify it into
            # the route class the rest of the ledger speaks, and
            # borrow that route's freshest exemplar trace — the
            # watchdog observes from outside the request, so it never
            # has one of its own
            route = _reqlog.classify_route("GET", route)
            if not trace_id:
                with self._lock:
                    cell = self._routes.get(route)
                    trace_id = cell.trace if cell is not None else ""
        now = time.time()
        with self._lock:
            if now - self._last_stall_note < 5.0:
                # same block, another observation: refresh the record
                # (the settle-side pass knows the route; the watchdog
                # may only know the loop was busy)
                if self._last_stall is not None and \
                        route != "(loop)":
                    self._last_stall["route"] = route
                    if trace_id:
                        self._last_stall["trace"] = trace_id
                    if lag_s * 1000.0 > self._last_stall["lag_ms"]:
                        self._last_stall["lag_ms"] = round(
                            lag_s * 1000.0, 1)
                return
            self._last_stall_note = now
            self._stalls += 1
            self._last_stall = {"ts": round(now, 3), "route": route,
                                "lag_ms": round(lag_s * 1000.0, 1),
                                "trace": trace_id}
        try:
            from ..stats.metrics import dataplane_metrics
            dataplane_metrics().loop_stalls.inc()
        except Exception:
            pass

    # --- snapshots -----------------------------------------------------

    def snapshot(self, top_clients: int = 32) -> dict:
        """The wire/debug doc: decayed to NOW, JSON-ready."""
        now = time.time()
        with self._lock:
            routes = {r: c.doc(now, self.half_life)
                      for r, c in self._routes.items()}
            clients = {k: c.doc(now, self.half_life)
                       for k, c in self._clients.items()}
            noted, evicted = self._noted, self._evicted
            stalls, last_stall = self._stalls, \
                dict(self._last_stall) if self._last_stall else None
        if top_clients and len(clients) > top_clients:
            keep = sorted(clients, key=lambda k: clients[k]["cpu_rate"],
                          reverse=True)[:top_clients]
            clients = {k: clients[k] for k in keep}
        doc = {"server": self.server, "ts": round(now, 3),
               "half_life_s": self.half_life, "noted": noted,
               "evicted": evicted, "routes": routes,
               "clients": clients,
               "stall": {"count": stalls, "last": last_stall}}
        if self.loop_stats_fn is not None:
            try:
                doc["loop"] = self.loop_stats_fn()
            except Exception:
                pass
        if self.profile_fn is not None:
            try:
                doc["profile"] = self.profile_fn()
            except Exception:
                pass
        return doc

    def status(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "half_life_s": self.half_life,
                    "routes": len(self._routes),
                    "clients": len(self._clients),
                    "noted": self._noted, "evicted": self._evicted,
                    "stalls": self._stalls}


def _on_loop_thread() -> bool:
    """Is the CURRENT thread a reactor loop thread?  The loop stamps
    its own thread object at startup (utils/eventloop.Reactor._run),
    so the check is one attribute read — no import of the reactor
    singleton, no lock."""
    return getattr(threading.current_thread(), "_weed_loop", False)


class LedgerShipper:
    """Periodic snapshot shipper to POST /cluster/ledger/ingest — the
    heat/trace/event transport contract: time-driven (the ledger is
    decayed STATE, the freshest snapshot supersedes older ones),
    bounded pending buffer, leader-follow rotation on failure, loss
    counted never backpressured, final best-effort flush on detach.
    Also refreshes the local per-route Prometheus gauges each cycle so
    /metrics carries the ledger without any per-request counter
    touches."""

    def __init__(self, ledger: RequestLedger, server: str,
                 master_url_fn: Optional[Callable[[], str]] = None,
                 interval: float = 1.0, buffer_cap: int = 8,
                 local_journal: Optional["ClusterLedgerJournal"] = None):
        self.ledger = ledger
        self.server = server
        self.master_url_fn = master_url_fn
        self.interval = interval
        self.local_journal = local_journal
        self.buffer_cap = buffer_cap
        self._buf: deque[dict] = deque()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # shared leader-follow policy (utils/leader.py) — internally locked
        from ..utils.leader import LeaderFollowingTransport
        self.transport = LeaderFollowingTransport(master_url_fn,
                                                  name=f"ledger:{server}")
        self.shipped = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock

    def attach(self) -> "LedgerShipper":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"ledger-ship:{self.server}")
        self._thread.start()
        return self

    def detach(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        self._snap()
        self._flush(timeout=0.5)

    def _snap(self) -> None:  # thread-entry
        try:
            doc = self.ledger.snapshot()
        except Exception:
            return
        self._set_gauges(doc)
        with self._lock:
            if len(self._buf) >= self.buffer_cap:
                self._buf.popleft()  # stale state: newest wins
                self.dropped += 1
                self._count_drop()
            self._buf.append(doc)

    def _set_gauges(self, doc: dict) -> None:
        """Per-route ledger gauges, refreshed at ship cadence — the
        Prometheus surface costs nothing on the request path."""
        try:
            from ..stats.metrics import ledger_metrics
            m = ledger_metrics()
            for route, row in (doc.get("routes") or {}).items():
                m.route_cpu.set(route, row["cpu_rate"])
                m.route_qwait.set(route, row["queue_wait_rate"])
                m.route_bytes.set(route, "in", row["bytes_in_rate"])
                m.route_bytes.set(route, "out", row["bytes_out_rate"])
        except Exception:
            pass

    def _count_drop(self) -> None:  # holds: _lock
        try:
            from ..stats.metrics import ledger_metrics
            ledger_metrics().snapshots_dropped.inc()
        except Exception:
            pass

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self._snap()
            self._flush()

    def _flush(self, timeout: float = 3.0) -> None:
        with self._lock:
            if not self._buf:
                return
            batch = list(self._buf)
            self._buf.clear()
        if self.local_journal is not None:
            self.local_journal.ingest(self.server, batch)
            with self._lock:
                self.shipped += len(batch)
            return
        try:
            # telemetry must never trace itself (same rule as spans)
            with _trace_context.scope(_trace_context.NOT_SAMPLED):
                self.transport.post("/cluster/ledger/ingest",
                                    {"server": self.server,
                                     "snapshots": batch},
                                    timeout=timeout)
            with self._lock:
                self.shipped += len(batch)
        except Exception:
            # master down / not elected: a stale ledger is worthless —
            # the batch is LOST and counted; the transport rotated to
            # the next master and re-learns the leader on reply
            with self._lock:
                self.dropped += len(batch)
                self._count_drop()


class ClusterLedgerJournal:  # weedlint: concurrent-class
    """The master's merged cost view + loop-stall relay.  Reached
    concurrently from the threaded HTTP router (ingest POSTs,
    /cluster/ledger GETs) and the telemetry loop."""

    def __init__(self, stale_s: float = 15.0,
                 min_event_interval: float = 5.0):
        self.stale_s = stale_s
        self.min_event_interval = min_event_interval
        self._lock = threading.Lock()
        self._peers: dict[str, dict] = {}  # guarded-by: _lock
        # per-peer stall relay floor: (count, last event wall time)
        self._stall_seen: dict[str, tuple] = {}  # guarded-by: _lock
        self._stall_events: deque = deque(maxlen=32)  # guarded-by: _lock
        self.ingested = 0  # guarded-by: _lock

    # --- ingest --------------------------------------------------------

    def ingest(self, server: str, snapshots: list[dict]) -> int:
        if not snapshots:
            return 0
        latest = max(snapshots,
                     key=lambda s: float(s.get("ts") or 0.0))
        with self._lock:
            self._peers[server] = latest
            self.ingested += len(snapshots)
        self._detect_stall(server, latest)
        return len(snapshots)

    def _detect_stall(self, server: str, snap: dict) -> None:
        """Relay a peer-reported loop stall as ONE `loop_stall`
        journal event (the detector-relay alert pattern): the snapshot
        already carries the verdict — offending route, lag, exemplar
        trace — so the rule pages without re-deriving anything."""
        stall = snap.get("stall") or {}
        count = int(stall.get("count") or 0)
        last = stall.get("last") or None
        if not count or not last:
            return
        now = time.time()
        with self._lock:
            seen, last_emit = self._stall_seen.get(server, (0, 0.0))
            if count <= seen or \
                    now - last_emit < self.min_event_interval:
                if count > seen:
                    # rate-limited: remember we saw it so a quiet peer
                    # does not re-fire an old stall later
                    self._stall_seen[server] = (count, last_emit)
                return
            self._stall_seen[server] = (count, now)
        from . import events as _events
        try:
            ev = _events.emit(
                "loop_stall", server=server,
                trace_id=last.get("trace") or None,
                route=last.get("route") or "?",
                lag_ms=last.get("lag_ms") or 0.0,
                stalls=count, servers=[server])
            with self._lock:
                self._stall_events.append(ev.to_dict())
        except Exception:
            pass

    # --- views ---------------------------------------------------------

    def merged(self, now: Optional[float] = None) -> dict:
        """Cluster-wide rates: per-route and per-client sums across
        non-stale peers (rates, not masses — peers decay locally), and
        per-server totals for the -by server axis."""
        now = time.time() if now is None else now
        with self._lock:
            peers = dict(self._peers)
        routes: dict[str, dict] = {}
        clients: dict[str, dict] = {}
        servers: dict[str, dict] = {}
        for url, snap in peers.items():
            if now - float(snap.get("ts") or 0.0) > self.stale_s:
                continue
            srv_cpu = srv_req = srv_qwait = 0.0
            for table, out in ((snap.get("routes") or {}, routes),
                               (snap.get("clients") or {}, clients)):
                for key, row in table.items():
                    agg = out.setdefault(key, {
                        "req_rate": 0.0, "cpu_rate": 0.0,
                        "bytes_in_rate": 0.0, "bytes_out_rate": 0.0,
                        "queue_wait_rate": 0.0, "cache_hit_rate": 0.0,
                        "cache_miss_rate": 0.0, "trace": "",
                        "servers": []})
                    for f in ("req_rate", "cpu_rate", "bytes_in_rate",
                              "bytes_out_rate", "queue_wait_rate",
                              "cache_hit_rate", "cache_miss_rate"):
                        agg[f] += float(row.get(f) or 0.0)
                    if row.get("trace"):
                        agg["trace"] = row["trace"]
                    agg["servers"].append(url)
            for row in (snap.get("routes") or {}).values():
                srv_cpu += float(row.get("cpu_rate") or 0.0)
                srv_req += float(row.get("req_rate") or 0.0)
                srv_qwait += float(row.get("queue_wait_rate") or 0.0)
            loop = snap.get("loop") or {}
            servers[url] = {
                "cpu_rate": round(srv_cpu, 6),
                "req_rate": round(srv_req, 4),
                "queue_wait_rate": round(srv_qwait, 6),
                "loop_lag_p99_ms":
                    float(loop.get("lag_p99_ms") or 0.0),
                "stalls":
                    int((snap.get("stall") or {}).get("count") or 0),
            }
        return {"routes": routes, "clients": clients,
                "servers": servers}

    def to_doc(self, top: int = 20) -> dict:
        """The full /cluster/ledger document."""
        now = time.time()
        merged = self.merged(now)
        total_cpu = sum(r["cpu_rate"]
                        for r in merged["routes"].values()) or 0.0

        def ranked(table: dict, key_name: str) -> list[dict]:
            rows = []
            for key, row in table.items():
                r = dict(row)
                r[key_name] = key
                r["cpu_share"] = round(r["cpu_rate"] / total_cpu, 4) \
                    if total_cpu > 0 else 0.0
                for f in ("req_rate", "cpu_rate", "bytes_in_rate",
                          "bytes_out_rate", "queue_wait_rate",
                          "cache_hit_rate", "cache_miss_rate"):
                    r[f] = round(r[f], 6)
                rows.append(r)
            rows.sort(key=lambda r: (-r["cpu_rate"], -r["req_rate"],
                                     r[key_name]))
            return rows[:top]

        with self._lock:
            peers_raw = dict(self._peers)
            stall_events = list(self._stall_events)
        peers = {}
        profiles = {}
        for url, snap in peers_raw.items():
            ts = float(snap.get("ts") or 0.0)
            peers[url] = {
                "ts": round(ts, 3),
                "stale": now - ts > self.stale_s,
                "noted": int(snap.get("noted") or 0),
                "loop": snap.get("loop") or {},
                "stall": snap.get("stall") or {},
            }
            if snap.get("profile"):
                profiles[url] = snap["profile"]
        srv_rows = [dict(v, server=u) for u, v in
                    merged["servers"].items()]
        total_srv_cpu = sum(r["cpu_rate"] for r in srv_rows) or 0.0
        for r in srv_rows:
            r["cpu_share"] = round(r["cpu_rate"] / total_srv_cpu, 4) \
                if total_srv_cpu > 0 else 0.0
        srv_rows.sort(key=lambda r: (-r["cpu_rate"], r["server"]))
        return {
            "ts": round(now, 3),
            "peers": peers,
            "routes": ranked(merged["routes"], "route"),
            "clients": ranked(merged["clients"], "client"),
            "servers": srv_rows,
            "profiles": profiles,
            "stalls": stall_events,
            "totals": {
                "cpu_rate": round(total_cpu, 6),
                "req_rate": round(sum(
                    r["req_rate"]
                    for r in merged["routes"].values()), 4),
            },
        }
