"""Workload flight recorder: a bounded, sampled ring of access records.

The observability stack answers "what went WRONG?" (events, alerts,
traces) but not "what does this cluster's TRAFFIC look like?" — and
without that record, every perf claim stays anchored to synthetic RPS
loops.  This module records the live request stream at the two ingress
chokepoints that see every operation:

  - ``Router.dispatch`` (utils/httpd.py) — the HTTP plane, every role;
  - ``FramedServer._serve_conn`` (utils/framing.py) — the native TCP
    plane (op R/W/D frames).

Each sampled request becomes one AccessRecord: route class (http_read /
http_write / http_delete / native_* / ops / other), method, status,
bytes in/out, duration, the remaining deadline budget at ingress, the
shed/degraded/deadline flags, the active sampled trace id, and the
peer address.  Secrets are redacted AT RECORD TIME (``redact_query``):
a ``?jwt=...`` query credential must never land in a recording that an
operator will export, attach to a ticket, or replay on a staging
cluster.

The recorder is a process-global singleton (like the tracer and the
event journal) so both chokepoints and every co-located server share
one ring.  Cost discipline: OFF is one attribute check per request;
ON pays one seeded-RNG draw per request and the record dict only for
the sampled fraction.  The ring is bounded and every loss is counted
(SeaweedFS_reqlog_records_dropped_total{reason}).

ReqlogShipper ships sampled records master-ward on the established
TraceShipper/EventShipper transport (chained hook, bounded buffer,
batch POST, loss counted never backpressured) into the master's
WorkloadJournal at GET /cluster/workload — whose ``/export`` view is
the recording document ``scenarios/replay.spec_from_recording`` fits
into a replayable ScenarioSpec.

Knobs: ``weed -reqlog.sample R -reqlog.size N <role>`` or
WEED_REQLOG_SAMPLE / WEED_REQLOG_SIZE, and live via
POST /debug/reqlog/start|stop (what ``weed shell workload.record``
drives cluster-wide).
"""

from __future__ import annotations

import os
import re
import threading
import time
import urllib.parse
from collections import OrderedDict, deque
from random import Random
from typing import Callable, Optional

from . import context as _trace_context

# query parameters whose VALUES are credentials: redacted at record
# time, before the record can reach the ring, the wire, or an export.
# Matching is case-insensitive and substring-free (exact key match) —
# ?keys=... is data, ?key=... is a credential.
SENSITIVE_PARAMS = frozenset((
    "jwt", "token", "auth", "authorization", "sig", "signature",
    "secret", "password", "accesskey", "secretkey", "key",
    "x-amz-signature", "x-amz-credential", "x-amz-security-token",
))
REDACTED = "REDACTED"

# request paths that are operator/telemetry plumbing, not workload:
# recorded only when the recorder is configured with include_ops=True
# (a recording meant for replay must not learn to replay its own
# metrics scrapes and shipper POSTs)
OPS_PREFIXES = ("/metrics", "/debug", "/cluster", "/admin", "/heartbeat",
                "/raft", "/stats", "/status", "/ui", "/dir/status",
                "/vol/", "/col/", "/ec/")

_OBJECT_PATH_RE = re.compile(r"^/\d+,[0-9a-f]+", re.IGNORECASE)


def redact_query(path: str) -> str:
    """Strip credential values from a ``path?query`` string.  The path
    itself and benign parameter values survive (replay fidelity needs
    them); any SENSITIVE_PARAMS value becomes ``REDACTED``.  The query
    is re-encoded with urlencode so percent/plus-encoded values
    round-trip intact (a manual join would turn an encoded ``%26``
    into a bare ``&`` and corrupt the recorded path).  Malformed query
    strings degrade to dropping the whole query — never to recording
    it unredacted."""
    base, sep, qs = path.partition("?")
    if not sep:
        return path
    try:
        pairs = urllib.parse.parse_qsl(qs, keep_blank_values=True)
    except ValueError:
        return base
    out = [(k, REDACTED if k.lower() in SENSITIVE_PARAMS else v)
           for k, v in pairs]
    return base + "?" + urllib.parse.urlencode(out) if out else base


def classify_route(method: str, path: str, handler: str = "",
                   query: Optional[dict] = None) -> str:
    """Route class for one HTTP request: the axis capacity numbers and
    replayed workloads are keyed by.  Object routes (``/<vid>,<fid>``)
    and master-proxied writes (``/submit``) are workload; the
    operator/telemetry surface is ``ops``; server-to-server hops
    (replication fan-out ``?type=replicate``, the master's /submit
    upload proxy ``?type=proxied``) are ``internal`` — recording them
    as client workload would double-count every proxied/replicated
    write and skew the fitted replay spec; everything else keeps a
    conservative ``other`` so an unknown route never masquerades as
    servable read capacity."""
    if query and query.get("type") in ("replicate", "proxied"):
        return "internal"
    if _OBJECT_PATH_RE.match(path):
        if method in ("GET", "HEAD"):
            return "http_read"
        if method == "DELETE":
            return "http_delete"
        return "http_write"
    if path.startswith("/batch/read"):
        # batched object IO (one request, N needles) is workload, not
        # ops — replays and capacity baselines must see it
        return "http_read"
    if path.startswith("/batch/write"):
        return "http_write"
    if path.startswith("/submit"):
        return "http_write"
    if path.startswith("/dir/assign"):
        return "assign"
    if path == "/dir/lookup" or path.startswith("/dir/lookup"):
        return "lookup"
    if any(path.startswith(p) for p in OPS_PREFIXES):
        return "ops"
    return "other"


NATIVE_ROUTES = {b"R": "native_read", b"W": "native_write",
                 b"D": "native_delete",
                 # batched frames carry N needles each but are still
                 # read/write workload for replay purposes
                 b"B": "native_read", b"P": "native_write"}


def _dropped_counter():
    """SeaweedFS_reqlog_records_dropped_total{reason}: access records
    lost to the bounded ring (ring_evict) or the shipper
    (ship_buffer/ship_error).  A recording whose window lost records
    says so — fidelity math must not trust a silently truncated
    sample."""
    global _dropped
    with _reqlog_lock:
        if _dropped is None:
            from ..stats import REGISTRY

            _dropped = REGISTRY.counter(
                "SeaweedFS_reqlog_records_dropped_total",
                "Workload access records dropped before export/shipping.",
                labels=("reason",))
        return _dropped


_dropped = None
_reqlog_lock = threading.Lock()


def dropped_total() -> int:
    """This process's total lost access records across every reason
    (ring/journal evictions, ship buffer/transport) — the master folds
    its own value into /cluster/health via the aggregator's local_fn
    (its registry is never peer-scraped, so journal evictions would
    otherwise be invisible to the reqlog_records_dropped alert)."""
    return int(sum(_dropped_counter().snapshot().values()))

# reqlog_dropped journal events are rate-limited: the counter counts
# every loss, the journal must not churn under a sustained overflow
_EVENT_MIN_INTERVAL_S = 10.0


class AccessRecord:
    """One sampled request, already redacted."""

    __slots__ = ("route", "method", "path", "status", "bytes_in",
                 "bytes_out", "duration_ms", "deadline_s", "shed",
                 "degraded", "trace_id", "peer", "server", "handler",
                 "ts", "seq", "id", "sample")

    def __init__(self, route: str, method: str, path: str, status: int,
                 bytes_in: int, bytes_out: int, duration_ms: float,
                 deadline_s: Optional[float], shed: bool, degraded: bool,
                 trace_id: Optional[str], peer: str, server: Optional[str],
                 handler: str, ts: float, seq: int, id_: str,
                 sample: float = 1.0):
        self.route = route
        self.method = method
        self.path = path
        self.status = status
        self.bytes_in = bytes_in
        self.bytes_out = bytes_out
        self.duration_ms = duration_ms
        self.deadline_s = deadline_s
        self.shed = shed
        self.degraded = degraded
        self.trace_id = trace_id
        self.peer = peer
        self.server = server
        self.handler = handler
        self.ts = ts
        self.seq = seq
        self.id = id_
        self.sample = sample

    def to_dict(self) -> dict:
        d = {"id": self.id, "seq": self.seq, "ts": round(self.ts, 3),
             "route": self.route, "method": self.method,
             "path": self.path, "status": self.status,
             "in": self.bytes_in, "out": self.bytes_out,
             "ms": round(self.duration_ms, 3)}
        if self.sample < 1.0:
            # each sampled record stands for ~1/sample real requests:
            # the fit corrects arrival rates by this, so a -sample 0.1
            # recording replays at PRODUCTION intensity, not a tenth
            d["sample"] = self.sample
        if self.handler:
            d["handler"] = self.handler
        if self.deadline_s is not None:
            d["ddl_s"] = round(self.deadline_s, 3)
        if self.shed:
            d["shed"] = True
        if self.degraded:
            d["degraded"] = True
        if self.trace_id:
            d["trace"] = self.trace_id
        if self.peer:
            d["peer"] = self.peer
        if self.server:
            d["server"] = self.server
        return d


class ReqlogRecorder:
    """Bounded sampled ring of AccessRecords (one per process).

    Sampling is a seeded RNG draw per request — deterministic under a
    fixed seed, so a recording taken with the same seed over the same
    request sequence admits the same subset (the property the fidelity
    tests pin).  ``enabled`` is the one-attribute-check fast-path gate
    the chokepoints read; start()/stop() flip it live."""

    def __init__(self, capacity: int = 8192, sample: float = 0.1,
                 seed: int = 0x5EED, include_ops: bool = False,
                 namespace: Optional[str] = None):
        self.enabled = False
        self.sample = float(sample)
        self.include_ops = include_ops
        self._records: deque[AccessRecord] = deque(maxlen=max(int(capacity), 16))  # guarded-by: _lock
        self._rng = Random(seed)  # guarded-by: _lock
        self._seed = seed
        self._lock = threading.Lock()
        self._seq = 0  # guarded-by: _lock
        self.seen = 0  # guarded-by: _lock
        self.recorded = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock
        self.started_at = 0.0  # guarded-by: _lock
        self._last_drop_event = 0.0  # guarded-by: _lock
        # same salting rationale as spans/events: bare pids collide
        # across containerized hosts and the master journal dedups by id
        self.namespace = namespace or (
            f"r{os.getpid():x}x{os.urandom(3).hex()}")
        # shipping hook (ReqlogShipper): called with every record
        self.on_record: Optional[Callable[[AccessRecord], None]] = None

    @property
    def capacity(self) -> int:
        return self._records.maxlen or 0  # weedlint: disable=W501 maxlen is immutable configuration, not ring state

    def configure(self, sample: Optional[float] = None,
                  capacity: Optional[int] = None,
                  seed: Optional[int] = None,
                  include_ops: Optional[bool] = None) -> None:
        """Apply knobs (live: a running recording re-sizes/re-rates
        without losing what it already holds unless the ring shrinks)."""
        with self._lock:
            if sample is not None:
                self.sample = max(0.0, min(float(sample), 1.0))
            if include_ops is not None:
                self.include_ops = bool(include_ops)
            if seed is not None:
                self._seed = int(seed)
                self._rng = Random(self._seed)
            if capacity is not None:
                # clamp BEFORE the compare/slice: capacity=0 would hit
                # the [-0:] falsy-zero slice (keep everything, count
                # nothing) and then silently truncate to the floor —
                # a loss the "every loss is counted" invariant forbids
                capacity = max(int(capacity), 16)
                if capacity != self._records.maxlen:
                    keep = list(self._records)[-capacity:]
                    lost = len(self._records) - len(keep)
                    self._records = deque(keep, maxlen=capacity)
                    if lost > 0:
                        self.dropped += lost
                        _dropped_counter().inc("ring_evict", amount=lost)

    def start(self, sample: Optional[float] = None,
              capacity: Optional[int] = None,
              seed: Optional[int] = None,
              include_ops: Optional[bool] = None,
              reset: bool = True) -> None:
        self.configure(sample=sample, capacity=capacity, seed=seed,
                       include_ops=include_ops)
        with self._lock:
            if reset:
                self._records.clear()
                self.seen = 0
                self.recorded = 0
                self._rng = Random(self._seed)
            self.started_at = time.time()
        self.enabled = True  # weedlint: disable=W502 monotonic on/off gate: single atomic bool store, chokepoints read it once per request and either value is safe

    def stop(self) -> None:
        self.enabled = False  # weedlint: disable=W502 monotonic on/off gate: single atomic bool store

    def record(self, route: str, method: str, path: str, status: int,  # thread-entry
               bytes_in: int = 0, bytes_out: int = 0,
               duration_ms: float = 0.0,
               deadline_s: Optional[float] = None, shed: bool = False,
               degraded: bool = False, peer: str = "",
               handler: str = "") -> Optional[AccessRecord]:
        """Sample-and-record one request — called from the ingress
        chokepoints on whatever thread served it.  Returns None when
        the sample draw rejected (the common case at low rates).  The
        path MUST arrive pre-redacted (the chokepoints call
        redact_query before this)."""
        if route in ("ops", "internal") and not self.include_ops:
            return None
        note_drop = False
        with self._lock:
            self.seen += 1
            if self.sample < 1.0 and self._rng.random() >= self.sample:
                return None
            self._seq += 1
            trace_ctx = _trace_context.current_sampled()
            rec = AccessRecord(
                route, method, path, int(status), int(bytes_in),
                int(bytes_out), float(duration_ms), deadline_s,
                bool(shed), bool(degraded),
                trace_ctx.trace_id if trace_ctx is not None else None,
                peer, _trace_context.current_server(), handler,
                time.time(), self._seq,
                f"{self.namespace}.{self._seq:x}",
                sample=self.sample)
            if len(self._records) == self._records.maxlen:
                self.dropped += 1
                _dropped_counter().inc("ring_evict")
                now = time.monotonic()
                if now - self._last_drop_event >= _EVENT_MIN_INTERVAL_S:
                    self._last_drop_event = now
                    note_drop = True
            self._records.append(rec)
            self.recorded += 1
        if note_drop:
            # journal the loss (rate-limited) OUTSIDE the ring lock —
            # the events module takes its own lock and its shipper hook
            # does real work
            _emit_drop_event("ring_evict")
        hook = self.on_record
        if hook is not None:
            try:
                hook(rec)
            except Exception:
                pass  # shipping must never break the serving path
        return rec

    def snapshot(self) -> list[AccessRecord]:
        with self._lock:
            return list(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self.seen = 0
            self.recorded = 0
            self.dropped = 0

    def query(self, route: Optional[str] = None, since_ts: float = 0.0,
              limit: int = 512) -> list[dict]:
        """Filtered record dicts, newest `limit` (<= 0 = unlimited —
        the export path; the HTTP routes clamp their own caps)."""
        out = [r.to_dict() for r in self.snapshot()
               if (not route or r.route == route)
               and (not since_ts or r.ts > since_ts)]
        limit = max(int(limit), 0)
        return out[-limit:] if limit else out

    def status(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled, "sample": self.sample,
                    "capacity": self._records.maxlen,
                    "records": len(self._records),
                    "seen": self.seen, "recorded": self.recorded,
                    "dropped": self.dropped,
                    "include_ops": self.include_ops,
                    "seed": self._seed,
                    "started_at": round(self.started_at, 3),
                    "namespace": self.namespace}


def _emit_drop_event(reason: str) -> None:
    from . import events as _events

    try:
        _events.emit("reqlog_dropped", reason=reason)
    except Exception:
        pass


def summarize_records(records: list[dict]) -> dict:
    """Shared recording rollup (the /cluster/workload summary block and
    the shell's one-line view): per-route counts, byte totals, error
    counts, observed window."""
    routes: dict[str, dict] = {}
    t0 = t1 = 0.0
    for r in records:
        row = routes.setdefault(r.get("route", "other"), {
            "ops": 0, "errors": 0, "bytes_in": 0, "bytes_out": 0})
        row["ops"] += 1
        if int(r.get("status") or 0) >= 400:
            row["errors"] += 1
        row["bytes_in"] += int(r.get("in") or 0)
        row["bytes_out"] += int(r.get("out") or 0)
        ts = float(r.get("ts") or 0.0)
        if ts:
            t0 = ts if not t0 else min(t0, ts)
            t1 = max(t1, ts)
    return {"records": len(records), "routes": routes,
            "window_s": round(max(t1 - t0, 0.0), 3),
            "t0": round(t0, 3), "t1": round(t1, 3)}


class WorkloadJournal:  # weedlint: concurrent-class
    """The master's merged workload recording: per-server recorders
    ship here, dedup'd by record id, bounded by oldest-first eviction —
    the /cluster/workload store and the source of the exportable
    recording document.  Reached concurrently from the threaded HTTP
    router (ingest POSTs + query/export GETs)."""

    FORMAT = "seaweedfs-tpu-workload-recording-v1"

    def __init__(self, capacity: int = 65536):
        self.capacity = capacity
        self._records: "OrderedDict[str, dict]" = OrderedDict()  # guarded-by: _lock
        self._lock = threading.Lock()
        self.dropped = 0  # guarded-by: _lock
        # consumer hook (same contract as ClusterEventJournal): called
        # OUTSIDE the lock with each batch of newly-accepted records —
        # the master's raft replication chokepoint subscribes here
        self.on_ingest: Optional[Callable[[list[dict]], None]] = None

    def ingest(self, server: str, records: list[dict]) -> int:
        accepted: list[dict] = []
        with self._lock:
            for r in records:
                rid = r.get("id")
                if not rid or rid in self._records:
                    continue  # duplicate ship (chained shippers, retry)
                r = dict(r)
                r["via"] = server
                self._records[rid] = r
                accepted.append(r)
            while len(self._records) > self.capacity:
                self._records.popitem(last=False)
                self.dropped += 1
                _dropped_counter().inc("journal_evict")
        hook = self.on_ingest
        if hook is not None and accepted:
            try:
                hook(list(accepted))
            except Exception:
                pass  # a broken consumer must never break ingest
        return len(accepted)

    def query(self, route: Optional[str] = None, server: Optional[str] = None,
              since_ts: float = 0.0, limit: int = 512) -> list[dict]:
        with self._lock:
            records = list(self._records.values())
        out = [r for r in records
               if (not route or r.get("route") == route)
               and (not server or r.get("server") == server
                    or r.get("via") == server)
               and (not since_ts or float(r.get("ts") or 0.0) > since_ts)]
        out.sort(key=lambda r: (float(r.get("ts") or 0.0),
                                str(r.get("id"))))
        limit = max(int(limit), 0)
        return out[-limit:] if limit else out

    def export(self, route: Optional[str] = None,
               since_ts: float = 0.0) -> dict:
        """The recording document — what ``weed shell workload.export``
        writes and ``scenarios/replay.spec_from_recording`` consumes.
        Time-ordered, loss-annotated, format-versioned."""
        records = self.query(route=route, since_ts=since_ts, limit=0)
        with self._lock:
            dropped = self.dropped
        return {"format": self.FORMAT,
                "exported_at": round(time.time(), 3),
                "dropped": dropped,
                "summary": summarize_records(records),
                "records": records}

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


class ReqlogShipper:
    """Ship this process's sampled access records to the master's
    workload journal — the TraceShipper/EventShipper transport pattern
    (chained on_record hook, bounded buffer, batch POST on a flush
    thread, loss COUNTED never backpressured, ``local_journal``
    short-circuit for the master's own records)."""

    def __init__(self, recorder: ReqlogRecorder, server: str,
                 master_url_fn: Optional[Callable[[], str]] = None,
                 local_journal: Optional[WorkloadJournal] = None,
                 batch_size: int = 128, flush_interval: float = 0.5,
                 buffer_cap: int = 4096):
        self.recorder = recorder
        self.server = server
        self.master_url_fn = master_url_fn
        self.local_journal = local_journal
        self.batch_size = batch_size
        self.flush_interval = flush_interval
        self.buffer_cap = buffer_cap
        self._buf: deque[AccessRecord] = deque()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # hook-chain handoff: written by attach()/detach() on the
        # server's lifecycle thread before the flush thread starts /
        # after it stops; read lock-free on every record
        self._prev_hook: Optional[Callable[[AccessRecord], None]] = None
        # shared leader-follow policy (utils/leader.py) — internally locked
        from ..utils.leader import LeaderFollowingTransport
        self.transport = LeaderFollowingTransport(master_url_fn,
                                                  name=f"workload:{server}")
        self.shipped = 0  # guarded-by: _lock
        self.dropped = 0  # guarded-by: _lock

    def attach(self) -> "ReqlogShipper":
        self._prev_hook = self.recorder.on_record  # weedlint: disable=W502 lifecycle handoff: runs before the flush thread starts
        self.recorder.on_record = self._on_record
        self._thread = threading.Thread(target=self._flush_loop,
                                        daemon=True,
                                        name=f"reqlog-ship:{self.server}")
        self._thread.start()
        return self

    def detach(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self.recorder.on_record is self._on_record:
            self.recorder.on_record = self._prev_hook
        # final flush with a sub-second timeout: at cluster teardown the
        # master is often already gone and stop() must not hang
        self._flush(timeout=0.5)

    def _on_record(self, rec: AccessRecord) -> None:  # thread-entry
        # called on whatever request thread recorded; a detached
        # shipper left mid-chain degrades to a pass-through
        if not self._stop.is_set():
            with self._lock:
                if len(self._buf) >= self.buffer_cap:
                    self.dropped += 1
                    _dropped_counter().inc("ship_buffer")
                else:
                    self._buf.append(rec)
                    if len(self._buf) >= self.batch_size:
                        self._wake.set()
        prev = self._prev_hook
        if prev is not None:
            prev(rec)

    def _flush_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval)
            self._wake.clear()
            self._flush()

    def _flush(self, timeout: float = 5.0) -> None:
        with self._lock:
            if not self._buf:
                return
            batch = list(self._buf)
            self._buf.clear()
        docs = [rec.to_dict() for rec in batch]
        if self.local_journal is not None:
            self.local_journal.ingest(self.server, docs)
            with self._lock:
                self.shipped += len(docs)
            return
        try:
            # shipping must never trace (or record) itself: the POST
            # runs NOT_SAMPLED, and its ingress on the master classifies
            # as `ops` which the recorder skips by default
            with _trace_context.scope(_trace_context.NOT_SAMPLED):
                self.transport.post("/cluster/workload/ingest",
                                    {"server": self.server, "records": docs},
                                    timeout=timeout)
            with self._lock:
                self.shipped += len(docs)
        except Exception:
            # master down / not elected: the batch is LOST and counted;
            # the transport rotated to the next configured master and
            # re-learns the leader from ingest replies post-election.
            # Counter updates ride _lock: the flush thread and the
            # detach()-time final flush race these read-modify-writes
            _dropped_counter().inc("ship_error", amount=len(docs))
            with self._lock:
                self.dropped += len(docs)


# --- process-global recorder -------------------------------------------------
# Both ingress chokepoints record into ONE recorder per process (like
# the tracer and the event journal), so /debug/reqlog and the shipper
# see the HTTP and native planes in one stream without plumbing a
# handle through every server constructor.

_GLOBAL = ReqlogRecorder()


def get_recorder() -> ReqlogRecorder:
    return _GLOBAL


def enable_reqlog(sample: float = 0.1, capacity: Optional[int] = None,
                  seed: Optional[int] = None,
                  include_ops: Optional[bool] = None) -> ReqlogRecorder:
    """Turn the process-global recorder on (the -reqlog.sample /
    WEED_REQLOG_SAMPLE entry point)."""
    _GLOBAL.start(sample=sample, capacity=capacity, seed=seed,
                  include_ops=include_ops, reset=False)
    return _GLOBAL


def disable_reqlog() -> None:
    _GLOBAL.stop()
