"""Thread-safe span tracer with Chrome trace-event + Prometheus exporters.

The EC pipeline's four coarse counters (fill_s/dispatch_s/write_s/
drain_wait_s in ec/streaming.py) say WHERE time went in aggregate but not
WHEN: BENCH_r05 showed drain_wait_s eating ~90% of the e2e wall with no
way to see which dispatch, stage, or host/device boundary it vanished
into.  This module turns those counters into per-dispatch spans:

  with tracer.span("pipeline.dispatch", dispatch=3, bytes=n):
      ...

Design constraints, in order:

  - near-zero cost when disabled: span() on a disabled tracer returns a
    shared no-op context manager (one attribute check, no allocation) so
    the instrumentation can live permanently on hot paths;
  - thread-safe: spans nest per-thread via threading.local; the ring
    append takes one lock;
  - bounded: spans land in a deque(maxlen=capacity) ring — a long-lived
    server can trace forever without growing;
  - mergeable across processes: span ids are namespaced (pid-derived by
    default) and timestamps are wall-anchored monotonic clocks, so a
    worker process's serializable span log (export_log/ingest_log, or
    the overlap workers' timed acks fed through add_span) merges into
    the parent's timeline without id collisions;
  - exportable two ways: to_chrome() emits Chrome trace_event JSON
    (load in chrome://tracing or https://ui.perfetto.dev), and an
    optional Prometheus bridge observes every span's duration into a
    stats.metrics Histogram so stage latencies appear on /metrics.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from . import context as _trace_context

# wall-anchored monotonic clock: perf_counter() gives monotonic intervals,
# the captured offset maps them onto the unix epoch so timestamps from
# different processes land on one comparable timeline
_EPOCH_WALL = time.time() - time.perf_counter()


def _now() -> float:
    return _EPOCH_WALL + time.perf_counter()


class Span:
    """One finished span: wall-anchored [t0, t1) plus identity/attrs.
    `trace_id` is set when the span was recorded under a sampled
    distributed-trace context (observability/context.py) — it is the
    stitching key the master-side collector groups cross-server spans
    by; locally minted spans outside any request carry None."""

    __slots__ = ("name", "span_id", "parent_id", "pid", "tid",
                 "thread", "t0", "t1", "attrs", "trace_id", "server")

    def __init__(self, name: str, span_id: str, parent_id: Optional[str],
                 pid: str, tid: int, thread: str,
                 t0: float, t1: float, attrs: dict,
                 trace_id: Optional[str] = None,
                 server: Optional[str] = None):
        self.name = name
        self.span_id = span_id
        self.parent_id = parent_id
        self.pid = pid          # namespace string (process identity)
        self.tid = tid          # thread ident within the namespace
        self.thread = thread    # human thread name
        self.t0 = t0
        self.t1 = t1
        self.attrs = attrs
        self.trace_id = trace_id
        # the server (host:port) whose request produced this span,
        # stamped at record time from the Router's thread-local
        # (context.swap_server) so co-located servers sharing one
        # process tracer still attribute per-span; None = recorded
        # outside any request (the shipper's identity stands in then)
        self.server = server

    @property
    def duration(self) -> float:
        return self.t1 - self.t0

    def to_dict(self) -> dict:
        """Serializable log entry (export_log/ingest_log wire format)."""
        d = {"name": self.name, "id": self.span_id,
             "parent": self.parent_id, "pid": self.pid, "tid": self.tid,
             "thread": self.thread, "t0": self.t0, "t1": self.t1,
             "attrs": self.attrs}
        if self.trace_id:
            d["trace"] = self.trace_id
        if self.server:
            d["server"] = self.server
        return d


class _NoopSpan:
    """Shared do-nothing context manager for disabled tracers."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    @property
    def span_id(self):
        return None


_NOOP = _NoopSpan()
# public alias: hot paths pre-guard on tracer.enabled and fall back to
# this shared context manager to skip even the attrs-dict allocation
NOOP_SPAN = _NOOP


class _SpanCtx:
    """Live span context manager: records on exit, nests via the
    tracer's per-thread stack, tags the span with the exception type on
    an abnormal exit."""

    __slots__ = ("tracer", "name", "attrs", "span_id", "parent_id", "t0")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        tr = self.tracer
        stack = getattr(tr._stack, "ids", None)
        if stack is None:
            stack = tr._stack.ids = []
        if stack:
            self.parent_id = stack[-1]
        else:
            # first span of this thread's request: re-root under the
            # caller's span id carried in by the trace context, so a
            # downstream server's request span nests below the upstream
            # rpc.client span when the collector stitches them
            ctx = _trace_context.current_sampled()
            self.parent_id = (ctx.span_id or None) if ctx else None
        self.span_id = tr._next_id()
        stack.append(self.span_id)
        self.t0 = _now()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = _now()
        tr = self.tracer
        stack = tr._stack.ids
        if stack and stack[-1] == self.span_id:
            stack.pop()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        ct = threading.current_thread()
        ctx = _trace_context.current_sampled()
        sp = Span(self.name, self.span_id, self.parent_id, tr.namespace,
                  ct.ident or 0, ct.name, self.t0, t1, self.attrs,
                  trace_id=ctx.trace_id if ctx else None,
                  server=_trace_context.current_server())
        tr._record(sp)
        return False


class Tracer:
    """Bounded in-memory span collector; see module docstring."""

    def __init__(self, capacity: int = 8192, enabled: bool = True,
                 namespace: Optional[str] = None, prometheus: bool = False):
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._stack = threading.local()
        self.enabled = enabled
        # pid alone collides across hosts (containerized servers are all
        # pid 1) and the collector dedups by span id, so a bare-pid
        # namespace would silently discard one server's spans from every
        # stitched trace — salt with random bytes to make ids unique
        # cluster-wide while staying stable within this tracer.  No "-"
        # in the salt: span ids ride the dash-delimited Traceparent
        # header as the parent field
        self.namespace = namespace or (
            f"p{os.getpid():x}x{os.urandom(3).hex()}")
        self._hist = _span_histogram() if prometheus else None
        # ring-eviction accounting: a bounded deque evicts silently, so a
        # truncated trace would masquerade as a complete one without this
        self.dropped = 0
        # trace shipping hook (observability/collector.py TraceShipper):
        # called with every recorded span that carries a trace_id
        self.on_record: Optional[Callable[[Span], None]] = None

    # --- recording --------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._spans.maxlen or 0

    def span(self, name: str, **attrs):
        """Context manager for a timed span.  Disabled tracers hand back
        a shared no-op — the hot-path cost of dormant instrumentation is
        one attribute check.  A thread whose ingress decided NOT to
        sample (head-based sampling, observability/context.py) also gets
        the no-op: at 1% sampling, 99% of requests pay one thread-local
        read here instead of span allocation + ring append."""
        if not self.enabled or _trace_context.is_not_sampled():
            return _NOOP
        return _SpanCtx(self, name, attrs)

    def add_span(self, name: str, t0: float, t1: float,
                 parent_id: Optional[str] = None,
                 thread: str = "", tid: Optional[int] = None,
                 **attrs) -> Optional[str]:
        """Record an externally timed span (wall-clock seconds — e.g.
        the overlap worker's compute window shipped back in its ack).
        `tid` places the span on its own thread track (defaults to the
        calling thread)."""
        if not self.enabled or _trace_context.is_not_sampled():
            return None
        span_id = self._next_id()
        ct = threading.current_thread()
        ctx = _trace_context.current_sampled()
        self._record(Span(name, span_id, parent_id, self.namespace,
                          tid if tid is not None else (ct.ident or 0),
                          thread or ct.name, t0, t1, attrs,
                          trace_id=ctx.trace_id if ctx else None,
                          server=_trace_context.current_server()))
        return span_id

    def event(self, name: str, **attrs) -> Optional[str]:
        """Record an instant (zero-duration) span — for punctual facts
        like a supervisor restart, an engine fallback decision, or a
        degraded bind, where the interesting thing is THAT it happened
        and its attrs, not how long it took."""
        t = _now()
        return self.add_span(name, t, t, **attrs)

    def current_span_id(self) -> Optional[str]:
        """The calling thread's innermost OPEN span id — the parent a
        cross-server hop stamps into its outbound Traceparent header."""
        stack = getattr(self._stack, "ids", None)
        return stack[-1] if stack else None

    def _next_id(self) -> str:
        with self._lock:
            self._seq += 1
            return f"{self.namespace}.{self._seq:x}"

    def _record(self, sp: Span) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
                counted_drop = True
            else:
                counted_drop = False
            self._spans.append(sp)
        if counted_drop:
            # counted regardless of the Prometheus bridge: the shared
            # registry counter must never diverge from Tracer.dropped
            _dropped_counter().inc("ring_evict")
        if self._hist is not None:
            self._hist.observe(sp.name, sp.t1 - sp.t0)
        hook = self.on_record
        if hook is not None and sp.trace_id:
            try:
                hook(sp)
            except Exception:
                pass  # shipping must never break the traced operation

    def attach_prometheus(self) -> None:
        """Bridge span durations into the shared stats REGISTRY so stage
        latencies appear on every server's /metrics."""
        self._hist = _span_histogram()
        # pre-touch EVERY drop reason so scrapers see all series at 0
        # before the first loss — an absent series breaks rate()/absent()
        # dashboards exactly when the first incident needs them
        c = _dropped_counter()
        for reason in ("ring_evict", "ship_buffer", "ship_error",
                       "collector_cap", "collector_evict"):
            c.labels(reason)

    # --- inspection -------------------------------------------------------
    def snapshot(self, clear: bool = False) -> list[Span]:
        """Point-in-time copy; clear=True drains ATOMICALLY so a
        poll-and-clear capture loop never drops spans recorded between
        the read and the clear.  Draining also re-baselines `dropped`:
        it counts losses from the CURRENT ring contents, so a complete
        capture taken after a clear must not inherit an old overflow's
        TRUNCATED verdict (the Prometheus counter stays cumulative)."""
        with self._lock:
            spans = list(self._spans)
            if clear:
                self._spans.clear()
                self.dropped = 0
            return spans

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # --- cross-process merge ----------------------------------------------
    def export_log(self) -> list[dict]:
        """Serializable span log (plain dicts; json/pickle/queue-safe)."""
        return [sp.to_dict() for sp in self.snapshot()]

    def ingest_log(self, log: list[dict], parent_id: Optional[str] = None,
                   namespace: Optional[str] = None) -> None:
        """Merge another tracer's export_log().  Ids keep their source
        namespace (distinct per process by construction), or are
        re-prefixed with `namespace` when the caller must disambiguate
        same-namespace logs; entries without a parent are reparented
        under `parent_id` so worker spans nest below the dispatching
        span."""
        if not self.enabled:
            return
        spans = []
        for e in log:
            sid, pid_ns = e["id"], e.get("pid", "?")
            par = e.get("parent")
            if namespace:
                sid = f"{namespace}/{sid}"
                par = f"{namespace}/{par}" if par else None
                pid_ns = f"{namespace}/{pid_ns}"
            spans.append(Span(e["name"], sid, par or parent_id, pid_ns,
                              int(e.get("tid", 0)), e.get("thread", ""),
                              float(e["t0"]), float(e["t1"]),
                              dict(e.get("attrs") or {}),
                              trace_id=e.get("trace"),
                              server=e.get("server")))
        with self._lock:
            overflow = max(0, len(self._spans) + len(spans)
                           - (self._spans.maxlen or 0))
            self.dropped += overflow
            self._spans.extend(spans)
        if self._hist is not None:
            for sp in spans:
                self._hist.observe(sp.name, sp.t1 - sp.t0)

    # --- whole-ring serialization -----------------------------------------
    def to_dict(self) -> dict:
        """Whole-ring snapshot as one JSON-safe document — the offline
        hand-off format for the critical-path analyzer: save it next to a
        bench run, load it later with from_dict(), and analysis.analyze()
        produces the SAME report it would against the live ring."""
        return {"format": "seaweedfs-tpu-trace-v1",
                "namespace": self.namespace,
                "capacity": self.capacity,
                "dropped": self.dropped,
                "spans": self.export_log()}

    @classmethod
    def from_dict(cls, doc: dict) -> "Tracer":
        """Rebuild a tracer from to_dict() output.  Ids keep their source
        namespaces (already distinct per process), so a round-trip
        preserves every parent/child edge and worker track."""
        spans = doc.get("spans") or []
        cap = int(doc.get("capacity") or 0) or max(len(spans), 1)
        tr = cls(capacity=max(cap, len(spans)), enabled=True,
                 namespace=doc.get("namespace"))
        tr.ingest_log(spans)
        return tr

    # --- Chrome trace-event export ----------------------------------------
    def to_chrome(self, clear: bool = False,
                  spans: Optional[list[Span]] = None) -> dict:
        """{"traceEvents": [...]} loadable in chrome://tracing/Perfetto.
        Spans become "X" (complete) events; process/thread metadata rides
        "M" events.  ts is strictly increasing per (pid, tid) — ties are
        nudged by 1ns so downstream tooling never sees a zero-width
        reordering ambiguity.  clear=True drains the ring atomically with
        the read (the /debug/traces?clear=1 contract).  `spans` renders a
        pre-filtered subset (the ?trace_id=/?root= debug filters) instead
        of the whole ring; clear is ignored then — filtering must never
        drain spans the caller did not see."""
        if spans is None:
            spans = self.snapshot(clear=clear)
        if not spans:
            return {"traceEvents": [], "displayTimeUnit": "ms"}
        base = min(sp.t0 for sp in spans)
        pid_map: dict[str, int] = {}
        tid_map: dict[tuple, int] = {}
        meta: list[dict] = []
        events: list[dict] = []
        for sp in spans:
            pid = pid_map.get(sp.pid)
            if pid is None:
                pid = pid_map[sp.pid] = len(pid_map) + 1
                meta.append({"ph": "M", "name": "process_name", "pid": pid,
                             "args": {"name": sp.pid}})
            tkey = (pid, sp.tid)
            tid = tid_map.get(tkey)
            if tid is None:
                tid = tid_map[tkey] = len(
                    [k for k in tid_map if k[0] == pid]) + 1
                meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                             "tid": tid, "args": {"name": sp.thread
                                                  or f"thread-{sp.tid}"}})
            args = dict(sp.attrs)
            args["span_id"] = sp.span_id
            if sp.parent_id:
                args["parent_id"] = sp.parent_id
            if sp.trace_id:
                args["trace_id"] = sp.trace_id
            events.append({"name": sp.name, "ph": "X",
                           "ts": (sp.t0 - base) * 1e6,
                           "dur": max((sp.t1 - sp.t0) * 1e6, 1e-3),
                           "pid": pid, "tid": tid, "args": args})
        # strictly increasing ts per thread track
        events.sort(key=lambda e: (e["pid"], e["tid"], e["ts"]))
        last: dict[tuple, float] = {}
        for e in events:
            key = (e["pid"], e["tid"])
            prev = last.get(key)
            if prev is not None and e["ts"] <= prev:
                e["ts"] = prev + 1e-3
            last[key] = e["ts"]
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


# --- Prometheus bridge -------------------------------------------------------

_span_hist = None
_span_hist_lock = threading.Lock()
_dropped = None


def _dropped_counter():
    """SeaweedFS_trace_spans_dropped_total{reason}: spans lost to the
    bounded ring (ring_evict) or the collector ship buffer
    (ship_buffer/ship_error) — the accounting that keeps a truncated
    trace from masquerading as a complete one."""
    global _dropped
    with _span_hist_lock:
        if _dropped is None:
            from ..stats import REGISTRY

            _dropped = REGISTRY.counter(
                "SeaweedFS_trace_spans_dropped_total",
                "Trace spans dropped before analysis/shipping.",
                labels=("reason",))
        return _dropped


def _span_histogram():
    """The shared SeaweedFS_trace_span_seconds family, registered once in
    the global stats REGISTRY (imported lazily: stats must not become an
    import-time dependency of every tracer user)."""
    global _span_hist
    with _span_hist_lock:
        if _span_hist is None:
            from ..stats import REGISTRY

            _span_hist = REGISTRY.histogram(
                "SeaweedFS_trace_span_seconds",
                "Span durations from the observability tracer.",
                labels=("name",))
        return _span_hist


# --- process-global tracer ---------------------------------------------------
# Servers and instrumented modules record into ONE tracer per process
# (disabled by default), so /debug/traces and /metrics see every layer's
# spans without plumbing a tracer handle through each constructor.

_GLOBAL = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _GLOBAL


def enable_tracing(capacity: Optional[int] = None,
                   prometheus: bool = True) -> Tracer:
    """Turn on the process-global tracer (optionally resizing its ring)
    and attach the /metrics histogram bridge.  Returns the tracer."""
    if capacity is not None and capacity != _GLOBAL.capacity:
        with _GLOBAL._lock:
            _GLOBAL._spans = deque(_GLOBAL._spans, maxlen=capacity)
    if prometheus:
        _GLOBAL.attach_prometheus()
    _GLOBAL.enabled = True
    return _GLOBAL


def disable_tracing() -> Tracer:
    _GLOBAL.enabled = False
    return _GLOBAL
