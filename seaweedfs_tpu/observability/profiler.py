"""Stdlib-only sampling wall-clock profiler (collapsed-stack output).

The critical-path analyzer names the pipeline stage a run is bound by;
what it cannot see is time spent BETWEEN spans — python overhead in the
drain loop, GIL convoys, a slow json encoder on a status route.  This
sampler makes that visible without any dependency or interpreter switch:
a daemon thread snapshots sys._current_frames() at a configurable rate
and aggregates whole stacks, so python-side overhead is distinguishable
from device time (the device never appears on a python stack; a hot
`_fetch` frame does).

Design constraints, matching the tracer's:

  - zero cost unless running: nothing is installed globally, no
    settrace/setprofile (those bias the measurement); start()/stop()
    own the only thread;
  - bounded memory: stacks aggregate into a counts dict capped at
    max_stacks distinct stacks (overflow collapses into one bucket) and
    stack depth is capped at max_depth frames;
  - thread-safe: the counts dict is guarded by one lock; collapsed()
    can run while sampling continues.

Output is the collapsed-stack format flamegraph.pl / speedscope / any
flamegraph viewer consumes: one line per distinct stack,
``thread;root_frame;...;leaf_frame <count>``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Optional

_MAX_SECONDS = 3600.0


class SamplingProfiler:
    """Wall-clock sampler over every thread but its own."""

    def __init__(self, hz: float = 100.0, max_stacks: int = 10000,
                 max_depth: int = 96):
        self.interval = 1.0 / max(min(hz, 1000.0), 0.1)
        self.max_stacks = max_stacks
        self.max_depth = max_depth
        self.samples = 0
        self.dropped = 0  # samples folded into the overflow bucket
        self._counts: dict[tuple, int] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = 0.0
        self.elapsed = 0.0
        # threads the capture must not observe (e.g. the HTTP handler
        # thread that is just sleeping out a run_for window)
        self._exclude: set[int] = set()

    # --- lifecycle --------------------------------------------------------
    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            raise RuntimeError("profiler already running")
        self._stop.clear()
        self._t0 = time.perf_counter()  # weedlint: disable=W502 lifecycle: only the controlling thread writes (start/stop); the sampler thread never touches it
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="sampling-profiler")
        self._thread.start()
        return self

    def stop(self) -> "SamplingProfiler":
        if self._thread is None:
            return self
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self.elapsed = time.perf_counter() - self._t0  # weedlint: disable=W502 lifecycle: only the controlling thread writes (start/stop); the sampler thread never touches it
        return self

    def run_for(self, seconds: float) -> "SamplingProfiler":
        """Blocking capture: sample for `seconds`, then stop."""
        seconds = max(0.0, min(seconds, _MAX_SECONDS))
        self._exclude.add(threading.get_ident())
        self.start()
        try:
            time.sleep(seconds)
        finally:
            self.stop()
        return self

    # --- sampling ---------------------------------------------------------
    def _loop(self) -> None:
        skip = {threading.get_ident()} | self._exclude
        while not self._stop.wait(self.interval):
            self._sample_once(skip)

    def _sample_once(self, skip: set) -> None:
        # thread names resolved per sample: threads come and go
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        stacks: list[tuple] = []
        for ident, frame in frames.items():
            if ident in skip:
                continue
            stack: list[tuple] = []
            while frame is not None and len(stack) < self.max_depth:
                code = frame.f_code
                stack.append((code.co_filename, frame.f_lineno,
                              code.co_name))
                frame = frame.f_back
            stack.reverse()  # root first (collapsed-stack order)
            stacks.append((names.get(ident, f"thread-{ident}"),
                           tuple(stack)))
        with self._lock:
            self.samples += 1
            for key in stacks:
                if key not in self._counts and \
                        len(self._counts) >= self.max_stacks:
                    key = ("(overflow)", ())
                    self.dropped += 1
                self._counts[key] = self._counts.get(key, 0) + 1

    # --- reports ----------------------------------------------------------
    def _snapshot(self) -> dict[tuple, int]:
        with self._lock:
            return dict(self._counts)

    def drain_counts(self) -> tuple[dict[tuple, int], int]:
        """-> (counts, samples) accumulated since the last drain, and
        reset both — the windowed mode's rotation primitive.  Sampling
        continues across the drain (the lock covers the swap only)."""
        with self._lock:
            counts, self._counts = self._counts, {}
            samples, self.samples = self.samples, 0
            return counts, samples

    @staticmethod
    def _frame_label(fr: tuple) -> str:
        fname, lineno, func = fr
        return f"{func} ({os.path.basename(fname)}:{lineno})"

    def collapsed(self) -> str:
        """flamegraph.pl input: `thread;frame;...;frame count` lines,
        heaviest stacks first."""
        lines = []
        for (thread, stack), n in sorted(self._snapshot().items(),
                                         key=lambda kv: -kv[1]):
            # ';' is the collapsed-format separator: scrub it from labels
            parts = [thread.replace(";", ":")]
            parts.extend(self._frame_label(fr).replace(";", ":")
                         for fr in stack)
            lines.append(";".join(parts) + f" {n}")
        return "\n".join(lines) + ("\n" if lines else "")

    def hotspots(self, limit: int = 40) -> tuple[list, list]:
        """(self_hits, cum_hits) aggregates for the text report:
        self keyed (file, line, func) on leaf frames, cumulative keyed
        (file, func) once per stack (recursion counts once)."""
        self_hits: dict[tuple, int] = {}
        cum_hits: dict[tuple, int] = {}
        for (_thread, stack), n in self._snapshot().items():
            if not stack:
                continue
            leaf = stack[-1]
            self_hits[leaf] = self_hits.get(leaf, 0) + n
            seen = set()
            for fname, _lineno, func in stack:
                ckey = (fname, func)
                if ckey not in seen:
                    cum_hits[ckey] = cum_hits.get(ckey, 0) + n
                    seen.add(ckey)
        top_self = sorted(self_hits.items(), key=lambda kv: -kv[1])[:limit]
        top_cum = sorted(cum_hits.items(), key=lambda kv: -kv[1])[:limit]
        return top_self, top_cum

    def report_text(self) -> str:
        """The /debug/pprof/profile view: self + cumulative hit tables."""
        samples = max(self.samples, 1)
        lines = [f"sampling profile: {self.samples} samples over "
                 f"{self.elapsed:.1f}s "
                 f"({self.interval * 1e3:.0f}ms interval), all threads",
                 "", "-- self time (leaf frames) --"]
        top_self, top_cum = self.hotspots()
        for (fname, lineno, func), n in top_self:
            lines.append(f"{n:>6} {100 * n / samples:5.1f}% "
                         f"{func} ({fname}:{lineno})")
        lines += ["", "-- cumulative (anywhere on stack) --"]
        for (fname, func), n in top_cum:
            lines.append(f"{n:>6} {100 * n / samples:5.1f}% "
                         f"{func} ({fname})")
        if self.dropped:
            lines += ["", f"(overflow: {self.dropped} samples past the "
                          f"{self.max_stacks}-stack bound)"]
        return "\n".join(lines) + "\n"


def profile_collapsed(seconds: float, hz: float = 100.0) -> str:
    """One-call capture -> collapsed-stack text (the /debug/profile and
    bench --profile-out entry point)."""
    prof = SamplingProfiler(hz=hz)
    prof.run_for(seconds)
    return prof.collapsed()


class WindowedProfiler:
    """Continuous profiling: the sampling profiler, always on at a low
    rate, rotated into bounded collapsed-stack WINDOWS.

    The one-shot profiler answers "where is time going right now, for
    the 10s I asked"; production regressions ask the opposite question
    — "what CHANGED in the last minute".  This mode keeps a rotating
    spool of per-window stack counts (window_s each, max_windows deep,
    so memory is bounded by construction) and `diff()` ranks the
    stacks RISING between the two most recent complete windows: the
    flamegraph delta that names a creeping hot path without anyone
    having been watching.

    Cost model: the sampler thread wakes `hz` times a second and walks
    sys._current_frames(); at the default 7hz that is ~2 orders below
    the one-shot profiler and is covered by the bench
    `resource_ledger` overhead gate (the ledger snapshot ships each
    window's top/rising stacks to the master, so cluster-wide profile
    windows cost no extra thread anywhere)."""

    def __init__(self, hz: float = 7.0, window_s: float = 10.0,
                 max_windows: int = 12, top_k: int = 10):
        self.hz = hz
        self.window_s = max(window_s, 1.0)
        self.top_k = top_k
        self._prof = SamplingProfiler(hz=hz)
        self._windows: deque = deque(maxlen=max_windows)  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.rotations = 0  # guarded-by: _lock

    def start(self) -> "WindowedProfiler":
        if self._thread is not None:
            return self
        self._prof.start()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="windowed-profiler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        try:
            self._prof.stop()
        except Exception:
            pass
        self._rotate()  # keep the partial tail window

    def _loop(self) -> None:  # thread-entry
        while not self._stop.wait(self.window_s):
            self._rotate()

    def _rotate(self) -> None:
        counts, samples = self._prof.drain_counts()
        if not samples:
            return
        with self._lock:
            self._windows.append({"ts": time.time(),
                                  "samples": samples,
                                  "counts": counts})
            self.rotations += 1

    @staticmethod
    def _label(key: tuple) -> str:
        thread, stack = key
        parts = [thread.replace(";", ":")]
        parts.extend(
            SamplingProfiler._frame_label(fr).replace(";", ":")
            for fr in stack)
        return ";".join(parts)

    def top(self, k: Optional[int] = None) -> list[dict]:
        """Heaviest stacks of the most recent window, share-of-window
        normalized: [{stack, hits, share}]."""
        with self._lock:
            win = self._windows[-1] if self._windows else None
        if win is None:
            return []
        total = max(win["samples"], 1)
        rows = sorted(win["counts"].items(), key=lambda kv: -kv[1])
        return [{"stack": self._label(key), "hits": n,
                 "share": round(n / total, 4)}
                for key, n in rows[:k or self.top_k]]

    def diff(self, k: Optional[int] = None) -> list[dict]:
        """Stacks RISING between the two most recent windows, ranked
        by share delta (sample counts normalize per window, so an hz
        hiccup does not read as a regression): [{stack, delta,
        share, prev_share}]."""
        with self._lock:
            if len(self._windows) < 2:
                return []
            prev, cur = self._windows[-2], self._windows[-1]
        pt, ct = max(prev["samples"], 1), max(cur["samples"], 1)
        deltas: list[tuple] = []
        for key, n in cur["counts"].items():
            share = n / ct
            prev_share = prev["counts"].get(key, 0) / pt
            if share > prev_share:
                deltas.append((share - prev_share, share, prev_share,
                               key))
        deltas.sort(key=lambda row: -row[0])
        return [{"stack": self._label(key),
                 "delta": round(d, 4), "share": round(s, 4),
                 "prev_share": round(ps, 4)}
                for d, s, ps, key in deltas[:k or self.top_k]]

    def summary(self) -> dict:
        """The ledger snapshot's `profile` section."""
        with self._lock:
            windows = len(self._windows)
        return {"hz": self.hz, "window_s": self.window_s,
                "windows": windows, "top": self.top(),
                "rising": self.diff()}
