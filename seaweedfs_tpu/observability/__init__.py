"""Observability: span tracing, trace analysis, sampling profiler.

See tracer.py for the span model.  Quick use:

    from seaweedfs_tpu.observability import enable_tracing, get_tracer
    tracer = enable_tracing()
    ...  # run the pipeline / serve requests
    open("trace.json", "w").write(json.dumps(tracer.to_chrome()))

Every server also exposes GET /debug/traces (the same Chrome trace JSON)
and, with the Prometheus bridge attached, span latency histograms on
/metrics as SeaweedFS_trace_span_seconds{name=...}.

Answering "which stage bounds this run?" is analysis.analyze() — served
as GET /debug/traces/analyze, the `weed shell` trace.analyze command,
and the bench JSON attribution block.  Python-side overhead between
spans is the sampling profiler's job (profiler.py, GET /debug/profile,
bench --profile-out).
"""

from .alerts import AlertEngine, Rule, default_rules
from .analysis import (analyze, analyze_cluster, attribution_summary,
                       render_cluster_report, render_report)
from .collector import TraceCollector, TraceShipper
from .context import (ingress_context, inject_trace_headers,
                      sample_rate, set_sample_rate)
from .events import (ClusterEventJournal, Event, EventJournal,
                     EventShipper, get_journal)
from .flightrecorder import FlightRecorder, get_flightrecorder
from .heat import (ClusterHeatJournal, DecayedCounter, HeatAccumulator,
                   HeatShipper, SpaceSavingSketch)
from .ledger import (ClusterLedgerJournal, LedgerShipper, RequestLedger)
from .profiler import (SamplingProfiler, WindowedProfiler,
                       profile_collapsed)
from .reqlog import (AccessRecord, ReqlogRecorder, ReqlogShipper,
                     WorkloadJournal, disable_reqlog, enable_reqlog,
                     get_recorder)
from .tracer import (Span, Tracer, disable_tracing, enable_tracing,
                     get_tracer)

__all__ = ["Span", "Tracer", "get_tracer", "enable_tracing",
           "disable_tracing", "analyze", "analyze_cluster",
           "attribution_summary", "render_report",
           "render_cluster_report", "TraceCollector", "TraceShipper",
           "ingress_context", "inject_trace_headers", "sample_rate",
           "set_sample_rate", "SamplingProfiler", "profile_collapsed",
           "Event", "EventJournal", "ClusterEventJournal",
           "EventShipper", "get_journal", "AlertEngine", "Rule",
           "default_rules", "FlightRecorder", "get_flightrecorder",
           "AccessRecord", "ReqlogRecorder", "ReqlogShipper",
           "WorkloadJournal", "get_recorder", "enable_reqlog",
           "disable_reqlog", "DecayedCounter", "SpaceSavingSketch",
           "HeatAccumulator", "HeatShipper", "ClusterHeatJournal",
           "RequestLedger", "LedgerShipper", "ClusterLedgerJournal",
           "WindowedProfiler"]
