"""Observability: span tracing for the EC pipeline and HTTP servers.

See tracer.py for the model.  Quick use:

    from seaweedfs_tpu.observability import enable_tracing, get_tracer
    tracer = enable_tracing()
    ...  # run the pipeline / serve requests
    open("trace.json", "w").write(json.dumps(tracer.to_chrome()))

Every server also exposes GET /debug/traces (the same Chrome trace JSON)
and, with the Prometheus bridge attached, span latency histograms on
/metrics as SeaweedFS_trace_span_seconds{name=...}.
"""

from .tracer import (Span, Tracer, disable_tracing, enable_tracing,
                     get_tracer)

__all__ = ["Span", "Tracer", "get_tracer", "enable_tracing",
           "disable_tracing"]
