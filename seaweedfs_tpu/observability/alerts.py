"""Cluster alerting engine: declarative rules over the master's rollup.

The master already *collects* everything this needs — /cluster/metrics
merges every peer's Prometheus families, /cluster/health summarizes the
degraded-path counters per peer — but nothing *watches* them: a shard
goes corrupt, a restart storm begins, a peer goes stale, and the only
way anyone finds out is polling by hand.  This engine closes that loop
on the master's existing aggregation cadence (the
-metricsAggregationSeconds loop), so the serving hot path pays nothing.

Rule kinds (all declarative: a Rule is data, the engine interprets it,
and /cluster/alerts serves the full table):

  counter_increase — a HEALTH_FAMILIES counter rose since the last
      evaluation, attributed per peer (worker_restarts,
      engine_fallbacks, corrupt_shards, ...).  Counter resets (a peer
      restart makes the value DROP) re-baseline silently — a reset is
      never an increase.
  threshold        — a /cluster/health totals key breaches a floor
      (scrub_unrepairable > 0: data is at risk RIGHT NOW).
  peer_down        — any registered peer is stale/unreachable.
  burn_rate        — multi-window SLO burn over the per-route RED
      histograms of the MERGED cluster metrics: error-ratio and
      p99-latency, each evaluated over a fast (5m) AND a slow (1h)
      window and active only when BOTH breach — a blip doesn't page,
      a sustained burn does (the SRE-workbook multi-window pattern).
  journal_event    — a typed event of params["event"] landed in the
      process journal within params["window_s"] (and after this
      engine started — stale events from a previous run never fire a
      fresh engine).  This is how DETECTORS page: the heat-telemetry
      shift detector (observability/heat.py) emits heat_shift /
      flash_crowd events that already carry the verdict (the hot
      volume, its share, holders, an exemplar trace), so the rule
      relays rather than re-derives.

State machine per rule:  inactive -> pending -> firing -> resolved.
`for_s` is the pending hold-down (condition must hold that long before
firing); `keep_firing_s` keeps a firing alert up through flapping and
resolves it only after that much sustained quiet.  Every transition is
journaled as an alert_pending / alert_fired / alert_resolved event
(observability/events.py), and the firing transition hands the rule +
implicated servers to `on_fire` — the master's flight-recorder capture
hook — exactly once per fire.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from . import events as _events

# alert states, in lifecycle order
STATES = ("inactive", "pending", "firing", "resolved")

# /cluster/health totals keys that are NOT HEALTH_FAMILIES counters but
# are still legal threshold-rule targets (computed by the aggregator's
# scrub rollup) — the check_health_keys lint consults this
EXTRA_HEALTH_KEYS = ("scrub_unrepairable",)


class Rule:
    """One declarative alert rule.  Pure data — serializable for the
    /cluster/alerts rules table and the README's default-rule table."""

    __slots__ = ("name", "kind", "severity", "for_s", "keep_firing_s",
                 "params", "description")

    def __init__(self, name: str, kind: str, severity: str = "warning",
                 for_s: float = 0.0, keep_firing_s: float = 300.0,
                 params: Optional[dict] = None, description: str = ""):
        self.name = name
        self.kind = kind
        self.severity = severity
        self.for_s = float(for_s)
        self.keep_firing_s = float(keep_firing_s)
        self.params = dict(params or {})
        self.description = description

    def to_dict(self) -> dict:
        return {"name": self.name, "kind": self.kind,
                "severity": self.severity, "for_s": self.for_s,
                "keep_firing_s": self.keep_firing_s,
                "params": dict(self.params),
                "description": self.description}


def default_rules() -> list[Rule]:
    """The shipped rule set.  One counter_increase rule per
    HEALTH_FAMILIES key, the unrepairable-data threshold, peer
    reachability, and the two multi-window burn-rate SLOs on the volume
    servers' per-route RED histograms."""
    from ..stats.aggregate import HEALTH_FAMILIES

    rules: list[Rule] = []
    for key in sorted(HEALTH_FAMILIES):
        # the rule's severity IS the severity of the event type that
        # chokepoint journals — one table (EVENT_TYPES, which the
        # check_health_keys lint guards), not a fifth copy to drift
        sev = _events.EVENT_TYPES.get(
            _events.HEALTH_EVENT_TYPES.get(key, ""), "warning")
        rules.append(Rule(
            f"{key}_increase", "counter_increase", severity=sev,
            for_s=0.0, keep_firing_s=300.0, params={"key": key},
            description=f"cluster {key} counter increased "
                        "(self-healing activity: something degraded)"))
    rules.append(Rule(
        "scrub_unrepairable", "threshold", severity="critical",
        for_s=0.0, keep_firing_s=600.0,
        params={"key": "scrub_unrepairable", "min": 1},
        description="a scrub verdict says < k clean shards remain "
                    "somewhere: data is at risk until repaired"))
    rules.append(Rule(
        "peer_down", "peer_down", severity="error",
        # keep_firing damps flapping: a peer timing out every other
        # scrape must stay ONE firing alert (one capture fan-out), not
        # fire/resolve per cycle and churn the bundle spool
        for_s=0.0, keep_firing_s=60.0,
        description="a heartbeat-registered volume server is "
                    "unreachable or serving stale metrics"))
    rules.append(Rule(
        "volume_error_burn", "burn_rate", severity="critical",
        for_s=0.0, keep_firing_s=300.0,
        params={"mode": "error_ratio",
                "errors": "SeaweedFS_volumeServer_request_errors_total",
                "requests": "SeaweedFS_volumeServer_request_total",
                "max_ratio": 0.01, "fast_s": 300.0, "slow_s": 3600.0,
                "min_requests": 10},
        description="volume-server 5xx ratio > 1% over BOTH the 5m "
                    "and 1h windows (sustained error budget burn)"))
    rules.append(Rule(
        "volume_latency_burn", "burn_rate", severity="critical",
        for_s=0.0, keep_firing_s=300.0,
        params={"mode": "p99",
                "family": "SeaweedFS_volumeServer_request_seconds",
                "max_p99_s": 0.5, "fast_s": 300.0, "slow_s": 3600.0,
                "min_requests": 10},
        description="volume-server per-route p99 latency > 500ms over "
                    "BOTH the 5m and 1h windows"))
    # heat-telemetry shift detector relays (observability/heat.py):
    # one journal_event rule per HEAT_EVENT_TYPES entry, severity from
    # EVENT_TYPES — W401 walks the tuple, the rules and the event
    # table against each other
    heat_descriptions = {
        "heat_shift": "the Zipf head moved: a volume newly entered "
                      "the cluster heat head set",
        "flash_crowd": "a previously-cold volume took the heat head "
                       "outright (flash crowd): replicate/cache it NOW",
    }
    from .heat import HEAT_EVENT_TYPES
    for etype in HEAT_EVENT_TYPES:
        rules.append(Rule(
            etype, "journal_event",
            severity=_events.EVENT_TYPES.get(etype, "warning"),
            for_s=0.0, keep_firing_s=120.0,
            params={"event": etype, "window_s": 30.0},
            description=heat_descriptions.get(etype, "")))
    # resource-ledger loop-stall relay (observability/ledger.py): the
    # master's ClusterLedgerJournal emits one loop_stall event per
    # peer-reported stall, carrying the offending route + exemplar
    # trace — same journal_event contract as the heat detectors
    ledger_descriptions = {
        "loop_stall": "a reactor event loop was blocked past the "
                      "stall threshold: every connection on that "
                      "server froze (the event names the route)",
    }
    from .ledger import LEDGER_EVENT_TYPES
    for etype in LEDGER_EVENT_TYPES:
        rules.append(Rule(
            etype, "journal_event",
            severity=_events.EVENT_TYPES.get(etype, "warning"),
            for_s=0.0, keep_firing_s=120.0,
            params={"event": etype, "window_s": 30.0},
            description=ledger_descriptions.get(etype, "")))
    return rules


class AlertState:
    """Mutable per-rule evaluation state (serialized for
    /cluster/alerts)."""

    __slots__ = ("rule", "state", "pending_since", "fired_at",
                 "resolved_at", "last_active", "value", "detail",
                 "servers", "fires", "bundles", "exemplar_trace")

    def __init__(self, rule: Rule):
        self.rule = rule
        self.state = "inactive"
        self.pending_since = 0.0
        self.fired_at = 0.0
        self.resolved_at = 0.0
        self.last_active = 0.0
        self.value = 0.0
        self.detail = ""
        self.servers: list[str] = []
        self.fires = 0
        self.bundles: list[dict] = []
        self.exemplar_trace = ""

    def to_dict(self) -> dict:
        d = {"name": self.rule.name, "severity": self.rule.severity,
             "state": self.state, "value": self.value,
             "detail": self.detail, "servers": list(self.servers),
             "fires": self.fires}
        if self.pending_since:
            d["pending_since"] = round(self.pending_since, 3)
        if self.fired_at:
            d["fired_at"] = round(self.fired_at, 3)
        if self.resolved_at:
            d["resolved_at"] = round(self.resolved_at, 3)
        if self.bundles:
            d["bundles"] = list(self.bundles)
        if self.exemplar_trace:
            d["exemplar_trace"] = self.exemplar_trace
        return d


class AlertEngine:  # weedlint: concurrent-class
    """Evaluate rules against (health, families) snapshots.  Reached
    concurrently: the master's telemetry loop evaluates on a timer
    while HTTP threads serve on-demand GET /cluster/alerts.

    `source_fn()` returns the pair the master already computes:
    aggregator.health() and aggregator.merged().  `on_fire(rule,
    state_doc, servers)` runs on the firing transition (the flight-
    recorder hook; the caller backgrounds any slow work).
    `exemplar_fn(rule)` may return a trace id correlated with the fire
    (the master looks the most recent matching journal event up), so
    the alert carries the trace that explains it."""

    def __init__(self, rules: list[Rule],
                 source_fn: Callable[[], tuple],
                 server: str = "",
                 journal=None,
                 on_fire: Optional[Callable] = None,
                 exemplar_fn: Optional[Callable[[Rule], str]] = None,
                 min_interval: float = 1.0):
        self.rules = list(rules)
        self.source_fn = source_fn
        self.server = server
        self.journal = journal or _events.get_journal()
        self.on_fire = on_fire
        self.exemplar_fn = exemplar_fn
        self.min_interval = min_interval
        self._states = {r.name: AlertState(r) for r in self.rules}  # guarded-by: _lock
        # counter_increase baselines: rule name -> {peer|__total__: val}
        self._baselines: dict[str, dict] = {}  # guarded-by: _lock
        # burn_rate sample history: rule name -> deque[(ts, digest)]
        self._history: dict[str, deque] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self.evaluated_at = 0.0  # guarded-by: _lock
        self.evaluations = 0  # guarded-by: _lock
        # journal_event floor: events emitted before this engine
        # existed (a previous drill in the same process) never fire it
        self._created = time.time()

    # --- evaluation -------------------------------------------------------
    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> dict:
        """One evaluation round; TTL-guarded so the on-demand
        /cluster/alerts GET cannot be turned into an evaluation
        amplifier next to the periodic loop.  `now` is injectable for
        the state-machine tests."""
        now = time.time() if now is None else now
        with self._lock:
            fresh = not force and \
                now - self.evaluated_at < self.min_interval
            if not fresh:
                self.evaluated_at = now
                self.evaluations += 1
        if fresh:
            # serve the last round's state (to_dict retakes the lock,
            # so the early return must happen outside it)
            return self.to_dict()
        health, families = self.source_fn()
        fired: list[tuple[Rule, dict, list[str]]] = []
        with self._lock:
            for rule in self.rules:
                try:
                    active, value, detail, servers = self._eval_rule(
                        rule, health, families, now)
                except Exception as e:  # a broken rule must not stop
                    active, value = False, 0.0  # the others evaluating
                    detail = f"rule error: {type(e).__name__}: {e}"
                    servers = []
                    # surface the breakage on the (inactive) alert —
                    # _transition only records detail while active
                    self._states[rule.name].detail = detail
                f = self._transition(rule, active, value, detail,
                                     servers, now)
                if f is not None:
                    fired.append(f)
            doc = self._to_dict_locked()
        # callbacks OUTSIDE the lock: capture fan-out does HTTP
        for rule, state_doc, servers in fired:
            if self.on_fire is not None:
                try:
                    self.on_fire(rule, state_doc, servers)
                except Exception:
                    pass
        return doc

    # --- replication (master HA) ------------------------------------------
    def export_state(self) -> dict:
        """The per-rule state machines as a plain replicable document
        (what the leader appends as `alert` raft-log entries): every
        field a promoted follower needs to CONTINUE a firing alert —
        pending windows, fire timestamps, exemplar traces — rather
        than re-learn it from scratch mid-incident.  Evaluation
        internals (counter baselines, burn-rate history) stay local:
        a new leader re-seeds them from its own first scrape."""
        with self._lock:
            return {name: {
                "state": st.state,
                "pending_since": st.pending_since,
                "fired_at": st.fired_at,
                "resolved_at": st.resolved_at,
                "last_active": st.last_active,
                "value": st.value,
                "detail": st.detail,
                "servers": list(st.servers),
                "fires": st.fires,
                "bundles": list(st.bundles),
                "exemplar_trace": st.exemplar_trace,
            } for name, st in self._states.items()}

    def import_state(self, doc: dict) -> None:  # raft-apply
        """Replay a replicated alert-state document into the local
        state machines (follower apply-loop / snapshot install).
        Unknown rule names are skipped — rule tables are configuration,
        not replicated state.  Idempotent: applying the same document
        twice is a no-op."""
        with self._lock:
            for name, d in (doc or {}).items():
                st = self._states.get(name)
                if st is None or not isinstance(d, dict):
                    continue
                st.state = str(d.get("state") or "inactive")
                st.pending_since = float(d.get("pending_since") or 0.0)
                st.fired_at = float(d.get("fired_at") or 0.0)
                st.resolved_at = float(d.get("resolved_at") or 0.0)
                st.last_active = float(d.get("last_active") or 0.0)
                st.value = float(d.get("value") or 0.0)
                st.detail = str(d.get("detail") or "")
                st.servers = [str(s) for s in (d.get("servers") or [])]
                st.fires = int(d.get("fires") or 0)
                st.bundles = list(d.get("bundles") or [])
                st.exemplar_trace = str(d.get("exemplar_trace") or "")

    def _transition(self, rule: Rule, active: bool, value: float,  # holds: _lock
                    detail: str, servers: list[str], now: float):
        """Advance one rule's state machine; returns (rule, state_doc,
        servers) when this round crossed into firing, else None.
        Called by evaluate() with _lock held."""
        st = self._states[rule.name]
        if active:
            st.last_active = now
            st.value = value
            st.detail = detail
            st.servers = servers
            if st.state == "firing" and not st.exemplar_trace \
                    and self.exemplar_fn is not None:
                # the correlated event often lands a shipper-flush
                # AFTER the fire: keep looking while firing so the
                # alert self-heals its trace link on the next cadence
                try:
                    st.exemplar_trace = self.exemplar_fn(rule) or ""
                except Exception:
                    pass
            if st.state in ("inactive", "resolved"):
                st.state = "pending"
                st.pending_since = now
                self.journal.emit("alert_pending", server=self.server,
                                  alert=rule.name, value=value,
                                  detail=detail)
            if st.state == "pending" and \
                    now - st.pending_since >= rule.for_s:
                st.state = "firing"
                st.fired_at = now
                st.fires += 1
                st.bundles = []
                if self.exemplar_fn is not None:
                    try:
                        st.exemplar_trace = self.exemplar_fn(rule) or ""
                    except Exception:
                        st.exemplar_trace = ""
                self.journal.emit("alert_fired", severity=rule.severity,
                                  server=self.server, alert=rule.name,
                                  value=value, detail=detail,
                                  servers=servers,
                                  exemplar_trace=st.exemplar_trace)
                return rule, st.to_dict(), list(servers)
        else:
            if st.state == "pending":
                # never fired: a blip shorter than for_s leaves no scar
                st.state = "inactive"
                st.pending_since = 0.0
            elif st.state == "firing" and \
                    now - st.last_active >= rule.keep_firing_s:
                st.state = "resolved"
                st.resolved_at = now
                self.journal.emit("alert_resolved", server=self.server,
                                  alert=rule.name,
                                  active_s=round(
                                      st.last_active - st.fired_at, 3))
        return None

    # --- rule kinds -------------------------------------------------------
    def _eval_rule(self, rule: Rule, health: dict, families: dict,  # holds: _lock
                   now: float):
        if rule.kind == "counter_increase":
            return self._eval_counter_increase(rule, health)
        if rule.kind == "threshold":
            return self._eval_threshold(rule, health)
        if rule.kind == "peer_down":
            return self._eval_peer_down(health)
        if rule.kind == "burn_rate":
            return self._eval_burn_rate(rule, families, now)
        if rule.kind == "journal_event":
            return self._eval_journal_event(rule, now)
        raise ValueError(f"unknown rule kind {rule.kind!r}")

    def _eval_journal_event(self, rule: Rule, now: float):
        """Active while a matching typed event sits inside the window.
        The event already carries the detector's verdict: surface its
        details (volume, share, servers) instead of re-deriving."""
        p = rule.params
        window = float(p.get("window_s", 30.0))
        events = self.journal.query(
            type_=p["event"],
            since_ts=max(now - window, self._created), limit=8)
        if not events:
            return False, 0.0, "", []
        latest = events[-1]
        d = latest.get("details") or {}
        servers = [s for s in (d.get("servers") or []) if s]
        detail = ", ".join(f"{k}={d[k]}" for k in
                           ("volume", "share", "prev_share",
                            "route", "lag_ms")
                           if k in d) or latest.get("type", "")
        return True, float(len(events)), detail, servers

    def _eval_counter_increase(self, rule: Rule, health: dict):  # holds: _lock
        key = rule.params["key"]
        cur: dict[str, float] = {}
        for url, peer in (health.get("peers") or {}).items():
            cur[url] = float(
                (peer.get("pipeline_health") or {}).get(key, 0))
        cur["__total__"] = float(
            (health.get("totals") or {}).get(key, 0))
        prev = self._baselines.get(rule.name)
        self._baselines[rule.name] = cur
        if prev is None:
            # first sight is the baseline: pre-existing totals (old
            # incidents, restarts) must not fire on engine startup
            return False, 0.0, "", []
        inc = {u: cur[u] - prev[u] for u in cur
               if u in prev and cur[u] > prev[u]}
        # cur < prev is a counter RESET (peer restart): tolerated — the
        # new lower value just became the baseline above
        servers = sorted(u for u in inc if u != "__total__")
        if not inc:
            return False, 0.0, "", []
        value = sum(v for u, v in inc.items() if u != "__total__") or \
            inc.get("__total__", 0.0)
        detail = (f"{key} +{int(value)} on "
                  f"{', '.join(servers) if servers else 'cluster'}")
        return True, value, detail, servers

    def _eval_threshold(self, rule: Rule, health: dict):
        key = rule.params["key"]
        floor = float(rule.params.get("min", 1))
        v = float((health.get("totals") or {}).get(key, 0))
        if v < floor:
            return False, v, "", []
        # name the peers whose scrub verdicts carry the damage
        servers = sorted(
            url for url, peer in (health.get("peers") or {}).items()
            if (peer.get("scrub") or {}).get("verdicts", {})
            .get("unrepairable", 0) > 0) if key == "scrub_unrepairable" \
            else []
        return True, v, f"{key}={int(v)}", servers

    def _eval_peer_down(self, health: dict):
        stale = sorted(health.get("stale_peers") or [])
        if not stale:
            return False, 0.0, "", []
        # the implicated servers are unreachable — capture targets are
        # empty; the master-local bundle still records the cluster view
        return True, float(len(stale)), \
            f"unreachable/stale peers: {', '.join(stale)}", []

    # --- burn rate --------------------------------------------------------
    def _eval_burn_rate(self, rule: Rule, families: dict, now: float):  # holds: _lock
        p = rule.params
        digest = self._burn_digest(rule, families)
        hist = self._history.setdefault(rule.name, deque())
        # thin the sample stream: a 1h window at a 1s evaluation
        # cadence must not retain 3600 full per-route snapshots —
        # one sample per ~fast_s/16 bounds memory without changing
        # which windows are answerable
        min_gap = max(1.0, float(p.get("fast_s", 300.0)) / 16.0)
        if not hist or now - hist[-1][0] >= min_gap:
            hist.append((now, digest))
        horizon = now - float(p.get("slow_s", 3600.0)) - 60.0
        while hist and hist[0][0] < horizon:
            hist.popleft()
        fast = self._window_breach(rule, hist, digest, now,
                                   float(p.get("fast_s", 300.0)))
        slow = self._window_breach(rule, hist, digest, now,
                                   float(p.get("slow_s", 3600.0)))
        if fast is None or slow is None:
            return False, 0.0, "", []
        value, detail = fast
        return True, value, \
            f"{detail} (fast+slow windows both breached)", []

    def _burn_digest(self, rule: Rule, families: dict):
        """Per-evaluation snapshot of just what the rule's windows
        need, keyed by route label tuple."""
        p = rule.params
        if p.get("mode") == "error_ratio":
            errs = families.get(p["errors"])
            reqs = families.get(p["requests"])
            e = errs.snapshot() if errs is not None else {}
            r = reqs.snapshot() if reqs is not None else {}
            return {"err": e, "req": r}
        fam = families.get(p["family"])
        if fam is None or not hasattr(fam, "buckets"):
            return {"buckets": (), "hist": {}}
        return {"buckets": tuple(fam.buckets),
                "hist": {k: (tuple(c), t)
                         for k, (c, _s, t) in fam.snapshot().items()}}

    def _window_breach(self, rule: Rule, hist, cur, now: float,
                       window_s: float):
        """The worst (value, detail) breach across routes over one
        window, None when the window has no base sample yet or nothing
        breaches.  The base is the NEWEST sample at least window_s old,
        so a window never fires before it has actually elapsed; `cur`
        is THIS evaluation's digest (which sample-thinning may not have
        appended to the history)."""
        base = None
        for ts, digest in hist:
            if ts <= now - window_s:
                base = digest
            else:
                break
        if base is None:
            return None
        p = rule.params
        min_req = int(p.get("min_requests", 10))
        worst = None
        if p.get("mode") == "error_ratio":
            max_ratio = float(p.get("max_ratio", 0.01))
            for key, req_now in cur["req"].items():
                req_base = base["req"].get(key, 0.0)
                dreq = req_now - req_base
                if dreq < min_req:
                    continue  # negative delta = counter reset: skip
                derr = cur["err"].get(key, 0.0) - \
                    base["err"].get(key, 0.0)
                if derr < 0:
                    continue
                ratio = derr / dreq
                if ratio > max_ratio and \
                        (worst is None or ratio > worst[0]):
                    route = ",".join(key) or "(all)"
                    worst = (ratio,
                             f"route {route} error ratio "
                             f"{ratio:.2%} > {max_ratio:.2%}")
            return worst
        # p99 mode
        max_p99 = float(p.get("max_p99_s", 0.5))
        buckets = cur.get("buckets") or ()
        if not buckets or base.get("buckets") != buckets:
            return None  # grid changed mid-window: not comparable
        for key, (counts, total) in cur["hist"].items():
            bcounts, btotal = base["hist"].get(key, ((), 0))
            dtotal = total - btotal
            if dtotal < min_req:
                continue
            if bcounts and len(bcounts) != len(counts):
                continue
            dcounts = [c - (bcounts[i] if bcounts else 0)
                       for i, c in enumerate(counts)]
            if any(c < 0 for c in dcounts):
                continue  # counter reset
            target = 0.99 * dtotal
            cum, p99 = 0, float("inf")
            for i, c in enumerate(dcounts):
                cum += c
                if cum >= target:
                    p99 = buckets[i]
                    break
            # cum never reaching target means >1% of observations sat
            # past the largest bucket: p99 stays +inf and breaches
            if p99 > max_p99 and (worst is None or p99 > worst[0]):
                route = ",".join(key) or "(all)"
                shown = "inf" if p99 == float("inf") else f"{p99:g}s"
                worst = (p99 if p99 != float("inf") else
                         (buckets[-1] * 10 if buckets else 1e9),
                         f"route {route} p99 ~{shown} > {max_p99:g}s")
        return worst

    def add_rule(self, rule: Rule) -> None:
        """Install one more rule at runtime — the scenario engine
        (seaweedfs_tpu/scenarios) registers run-scoped SLO rules with
        windows short enough to breach and resolve inside a drill."""
        with self._lock:
            self.rules.append(rule)
            self._states[rule.name] = AlertState(rule)

    # --- views ------------------------------------------------------------
    def note_bundles(self, rule_name: str, bundles: list[dict]) -> None:
        """Attach flight-recorder capture results to the alert that
        triggered them (the capture fan-out runs on a background
        thread, after evaluate() returned)."""
        with self._lock:
            st = self._states.get(rule_name)
            if st is not None:
                st.bundles = list(bundles)

    def firing(self) -> list[dict]:
        with self._lock:
            return [st.to_dict() for st in self._states.values()
                    if st.state == "firing"]

    def to_dict(self) -> dict:
        with self._lock:
            return self._to_dict_locked()

    def _to_dict_locked(self) -> dict:
        order = {"firing": 0, "pending": 1, "resolved": 2, "inactive": 3}
        alerts = sorted(
            (st.to_dict() for st in self._states.values()),
            key=lambda a: (order.get(a["state"], 9),
                           -_events.SEVERITY_RANK.get(a["severity"], 0),
                           a["name"]))
        return {"alerts": alerts,
                "firing": sum(1 for a in alerts
                              if a["state"] == "firing"),
                "rules": [r.to_dict() for r in self.rules],
                "evaluated_at": round(self.evaluated_at, 3),
                "evaluations": self.evaluations}
