"""Critical-path analysis of EC pipeline traces.

PR 1 made the drain-wait stall *recorded* (per-dispatch spans on a
bounded ring, /debug/traces) and PR 3 made recovery *visible*
(pipeline.retry / pipeline.fallback spans, restart counters) — but
answering "which stage bounds throughput, and was this run clean or
degraded?" still meant eyeballing raw span dumps.  This module computes
that answer:

  report = analyze(tracer_or_trace_doc, counters=ec_pipeline_totals)

The input is anything a trace can arrive as: a live Tracer, a list of
Span objects, a Tracer.to_dict() document, or the Chrome trace-event
JSON that `bench.py --trace-out` / GET /debug/traces persist — offline
analysis of a saved trace produces the same report as the live ring.

Per pipeline run (each pipeline.encode_file / pipeline.rebuild_files
root span) the report carries:

  - stage occupancy: seconds and share-of-wall per pipeline stage
    (setup/fill/dispatch/compute/drain/write/fallback/close), plus the
    concurrent worker.compute track kept separate so overlapped compute
    never reads as serial host time;
  - an overlap_efficiency decomposition that ties every second of the
    wall to a named stage (drain = host BLOCKED on results; anything
    not inside a span is "unattributed" — python overhead the sampling
    profiler can then break down);
  - the critical path through the dispatch sequence: the dominant stage
    of each dispatch, compressed into segments, and the overall
    critical_path_stage (the argmax of the wall decomposition);
  - gap analysis between consecutive worker.compute windows: each idle
    gap on the worker track is classified by what the host was doing
    meanwhile — input_starved (fill/dispatch), drain_blocked,
    link_bound (blocked while the async drainer was actively pulling
    parity off the wire), writer_blocked, or other;
  - drain-track awareness (PR 7): pipeline.drain spans recorded on a
    DIFFERENT thread than the run root are the async drainer's
    concurrent fetch track — reported as drain_track_s + a
    drain_profile classifying the run as none / overlapped /
    link_bound / drain_blocked — while pipeline.drain_wait (the
    producer blocked on the slot pool) folds into the host "drain"
    bucket, so overlap_efficiency keeps meaning "1 - host-blocked
    share" across old and new traces;
  - a degraded flag driven by pipeline.retry / pipeline.fallback spans,
    resumed-attempt roots, and (when given) the restart/fallback
    counters — so BENCH numbers self-label clean vs degraded.

Everything is stdlib + already-recorded spans: no hardware, no new
threads, nothing on the hot path.
"""

from __future__ import annotations

from typing import Optional

# stages that run ON the pipeline's host thread, in dispatch order;
# "drain" is the one where the host is BLOCKED waiting for results
HOST_STAGES = ("setup", "fill", "dispatch", "compute", "drain", "write",
               "fallback", "close")
ROOT_NAMES = ("pipeline.encode_file", "pipeline.rebuild_files")
# span names that are evidence of a degraded (self-healed) run
DEGRADE_EVENT_NAMES = ("pipeline.retry", "pipeline.fallback")
# counter keys (ec_pipeline_metrics().totals() / per-call encode stats)
# whose nonzero value marks the measured path degraded
DEGRADE_COUNTER_KEYS = ("worker_restarts", "engine_fallbacks",
                        "retries", "fallbacks",
                        # bit-rot defense (ec/integrity.py): nonzero
                        # means some measurement read shards that rotted
                        # and were demoted or repaired mid-run
                        "corrupt_shards", "scrub_repairs")

_EPS = 1e-6


def _normalize(trace) -> list[dict]:
    """Any trace shape -> list of plain span dicts
    {name, t0, t1, id, parent, tid, attrs} sorted by t0."""
    spans: list[dict] = []
    if hasattr(trace, "snapshot"):  # live Tracer
        trace = trace.snapshot()
    if isinstance(trace, dict):
        if "spans" in trace:        # Tracer.to_dict() document
            trace = trace["spans"]
        elif "traceEvents" in trace:
            return _from_chrome(trace)
        else:
            raise ValueError("unrecognized trace document: expected "
                             "'spans' or 'traceEvents'")
    for sp in trace:
        if hasattr(sp, "to_dict"):  # Span object
            sp = sp.to_dict()
        spans.append({"name": sp["name"], "t0": float(sp["t0"]),
                      "t1": float(sp["t1"]), "id": sp.get("id"),
                      "parent": sp.get("parent"),
                      "tid": sp.get("tid", 0),
                      # cross-server identity: the shipping server's url
                      # (collector-stitched docs), falling back to the
                      # recording process's namespace
                      "server": sp.get("server") or sp.get("pid"),
                      "attrs": dict(sp.get("attrs") or {})})
    spans.sort(key=lambda s: s["t0"])
    return spans


def _from_chrome(doc: dict) -> list[dict]:
    """Chrome trace-event JSON (to_chrome() / --trace-out output) back to
    span dicts.  ts/dur are µs on a run-relative axis; the analysis only
    ever compares times within one document, so the lost absolute epoch
    is irrelevant."""
    spans = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        args = dict(e.get("args") or {})
        sid = args.pop("span_id", None)
        parent = args.pop("parent_id", None)
        t0 = float(e["ts"]) / 1e6
        spans.append({"name": e["name"], "t0": t0,
                      "t1": t0 + float(e.get("dur", 0)) / 1e6,
                      "id": sid, "parent": parent,
                      "tid": e.get("tid", 0), "attrs": args})
    spans.sort(key=lambda s: s["t0"])
    return spans


def _overlap_s(a0: float, a1: float, b0: float, b1: float) -> float:
    return max(0.0, min(a1, b1) - max(a0, b0))


def _stage_of(span: dict) -> Optional[str]:
    name = span["name"]
    if name in ROOT_NAMES or not name.startswith("pipeline."):
        return None
    stage = name.split(".", 1)[1]
    if stage == "drain_wait":
        # async drain (PR 7): the producer thread blocked on the slot
        # pool / final join — host-blocked time, the same bucket the
        # old inline fetch landed in
        return "drain"
    return stage if stage in HOST_STAGES else None


def _merged_intervals(spans: list[dict]) -> list[tuple[float, float]]:
    ivs = sorted((s["t0"], s["t1"]) for s in spans)
    out: list[tuple[float, float]] = []
    for a, b in ivs:
        if b <= a:
            continue
        if out and a <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], b))
        else:
            out.append((a, b))
    return out


def _cover_len(a: float, b: float,
               ivs: list[tuple[float, float]]) -> float:
    """Length of [a, b] covered by the merged intervals."""
    total = 0.0
    for i0, i1 in ivs:
        if i1 <= a:
            continue
        if i0 >= b:
            break
        total += min(b, i1) - max(a, i0)
    return total


def _gap_analysis(members: list[dict],
                  drain_track: Optional[list[dict]] = None,
                  offthread: Optional[list[dict]] = None) -> dict:
    """Classify idle gaps between consecutive worker.compute windows by
    what the HOST thread was doing during each gap: filling/dispatching
    the next input (the worker is input-starved), blocked in drain, or
    writing shards.  Host drain time that coincides with an ACTIVE
    fetch on the concurrent drainer track is split out as `link_bound`
    (the host waits because the wire is still moving parity) vs
    `drain_blocked` (the host waits on drain machinery that is not
    actually transferring)."""
    drain_track = drain_track or []
    track_ivs = _merged_intervals(drain_track)
    windows = sorted((s for s in members
                      if s["name"].startswith("worker.")),
                     key=lambda s: s["t0"])
    out = {"worker_windows": len(windows), "worker_busy_s": 0.0,
           "gap_total_s": 0.0,
           "classes": {"input_starved": 0.0, "drain_blocked": 0.0,
                       "link_bound": 0.0,
                       "writer_blocked": 0.0, "other": 0.0}}
    if not windows:
        return out
    out["worker_busy_s"] = round(
        sum(s["t1"] - s["t0"] for s in windows), 4)
    # identity-based exclusion of the concurrent tracks: value equality
    # would be O(n*m) dict compares on 10^4-span bench traces
    excl = {id(s) for s in drain_track}
    excl.update(id(s) for s in (offthread or []))
    host = [s for s in members if id(s) not in excl]
    by_class = {
        "input_starved": [s for s in host
                          if _stage_of(s) in ("fill", "dispatch")],
        "writer_blocked": [s for s in host if _stage_of(s) == "write"],
    }
    drain_spans = [s for s in host if _stage_of(s) == "drain"]
    for prev, nxt in zip(windows, windows[1:]):
        g0, g1 = prev["t1"], nxt["t0"]
        gap = g1 - g0
        if gap <= 0:
            continue
        out["gap_total_s"] += gap
        covered = 0.0
        for cls, stage_spans in by_class.items():
            s = sum(_overlap_s(g0, g1, sp["t0"], sp["t1"])
                    for sp in stage_spans)
            out["classes"][cls] += s
            covered += s
        for sp in drain_spans:
            a = max(g0, sp["t0"])
            b = min(g1, sp["t1"])
            if b <= a:
                continue
            lb = _cover_len(a, b, track_ivs)
            out["classes"]["link_bound"] += lb
            out["classes"]["drain_blocked"] += (b - a) - lb
            covered += b - a
        out["classes"]["other"] += max(0.0, gap - covered)
    out["classes"] = {k: round(v, 4) for k, v in out["classes"].items()}
    # the classes decompose gap_total_s: independent rounding could push
    # their sum past the rounded total, so the total absorbs the rounding
    out["gap_total_s"] = round(max(out["gap_total_s"],
                                   sum(out["classes"].values())), 4)
    return out


def _analyze_run(root: dict, members: list[dict],
                 max_path_items: int = 48) -> dict:
    wall = max(root["t1"] - root["t0"], _EPS)
    # the async drain (PR 7) fetches on a DIFFERENT thread than the
    # pipeline root: those pipeline.drain spans are a concurrent track
    # (like worker.compute) — counting them as host time would let the
    # wall decomposition exceed 1.0 and misread an overlapped link
    # transfer as a stall.  Old (inline-drain) traces record drain on
    # the root's thread and keep the host-blocked semantics.
    host_tid = root.get("tid")
    stage_s: dict[str, float] = {}
    stage_n: dict[str, int] = {}
    per_dispatch: dict[int, dict[str, float]] = {}
    fallback_reasons: dict[str, int] = {}
    drain_track: list[dict] = []
    offthread: list[dict] = []           # writer/fallback threads
    offthread_s: dict[str, float] = {}
    drain_host_spans: list[dict] = []
    retries = 0
    for sp in members:
        stage = _stage_of(sp)
        if sp["name"] == "pipeline.retry":
            retries += 1
        if sp["name"] == "pipeline.fallback":
            reason = str(sp["attrs"].get("reason", "unknown"))
            fallback_reasons[reason] = fallback_reasons.get(reason, 0) + 1
        if stage is None:
            continue
        dur = sp["t1"] - sp["t0"]
        d = sp["attrs"].get("dispatch")
        if host_tid is not None and sp.get("tid") != host_tid:
            # async-drain tracks: the fetch (pipeline.drain), the
            # writer's parity writes, and fallback recomputes all ride
            # other threads — CONCURRENT with the host stages, so they
            # leave the wall decomposition (shares would sum past 1.0
            # and an overlapped transfer would read as a stall).  They
            # still vote in the per-dispatch critical path: they ARE
            # the dominant cost of a link- or writer-bound dispatch.
            if sp["name"] == "pipeline.drain":
                drain_track.append(sp)
            else:
                offthread.append(sp)
                offthread_s[stage] = offthread_s.get(stage, 0.0) + dur
            if d is not None:
                row = per_dispatch.setdefault(int(d), {})
                row[stage] = row.get(stage, 0.0) + dur
            continue
        if stage == "drain":
            drain_host_spans.append(sp)
        stage_s[stage] = stage_s.get(stage, 0.0) + dur
        stage_n[stage] = stage_n.get(stage, 0) + 1
        if d is not None:
            row = per_dispatch.setdefault(int(d), {})
            row[stage] = row.get(stage, 0.0) + dur

    attributed = sum(stage_s.values())
    unattributed = max(0.0, wall - attributed)
    drain_s = stage_s.get("drain", 0.0)
    track_s = sum(s["t1"] - s["t0"] for s in drain_track)
    # per-device breakdown (the -ec.engine=mesh plane tags its
    # dispatch/drain spans with a `device` attr): occupancy per device
    # on the dispatch side, fetch seconds per drain lane — how the
    # profile says WHICH device queue is the straggler
    per_device: dict[str, dict] = {}
    for sp in drain_track:
        dev = sp["attrs"].get("device")
        if dev is None:
            continue
        row = per_device.setdefault(
            str(dev), {"fetch_s": 0.0, "drain_spans": 0, "dispatches": 0})
        row["fetch_s"] += sp["t1"] - sp["t0"]
        row["drain_spans"] += 1
    for sp in members:
        if sp["name"] != "pipeline.dispatch":
            continue
        dev = sp["attrs"].get("device")
        if dev is None:
            continue
        row = per_device.setdefault(
            str(dev), {"fetch_s": 0.0, "drain_spans": 0, "dispatches": 0})
        row["dispatches"] += 1
    track_ivs = _merged_intervals(drain_track)
    # host-blocked drain seconds coinciding with an ACTIVE fetch on the
    # drainer track: the host waited on the WIRE (link-bound); the rest
    # of the blocked time is drain machinery (drain-blocked)
    link_covered_s = sum(_cover_len(s["t0"], s["t1"], track_ivs)
                         for s in drain_host_spans)
    if drain_s + track_s < 0.02 * wall:
        drain_cls = "none"
    elif drain_s < 0.15 * wall:
        drain_cls = "overlapped"
    elif link_covered_s >= 0.5 * drain_s:
        drain_cls = "link_bound"
    else:
        drain_cls = "drain_blocked"

    # every second of the wall lands in a named bucket
    attribution = {stage: {"s": round(s, 4),
                           "share": round(s / wall, 4),
                           "spans": stage_n.get(stage, 0)}
                   for stage, s in sorted(stage_s.items())}
    attribution["unattributed"] = {"s": round(unattributed, 4),
                                   "share": round(unattributed / wall, 4),
                                   "spans": 0}
    critical_path_stage = max(attribution,
                              key=lambda k: attribution[k]["s"])

    # critical path through the dispatch sequence: dominant stage per
    # dispatch, compressed into consecutive segments
    segments: list[dict] = []
    for d in sorted(per_dispatch):
        row = per_dispatch[d]
        dom = max(row, key=row.get)
        if segments and segments[-1]["stage"] == dom:
            seg = segments[-1]
            seg["dispatches"][1] = d
            seg["s"] += row[dom]
        else:
            segments.append({"stage": dom, "dispatches": [d, d],
                             "s": row[dom]})
    truncated = max(0, len(segments) - max_path_items)
    segments = segments[:max_path_items]
    for seg in segments:
        seg["s"] = round(seg["s"], 4)

    drain_profile = {
        "host_blocked_s": round(drain_s, 4),
        "fetch_s": round(track_s if drain_track else drain_s, 4),
        "link_bound_s": round(link_covered_s, 4),
        "classification": drain_cls,
    }
    if per_device:
        for row in per_device.values():
            row["fetch_s"] = round(row["fetch_s"], 4)
            row["fetch_share"] = round(
                row["fetch_s"] / max(track_s, _EPS), 4)
        drain_profile["per_device"] = {
            k: per_device[k]
            for k in sorted(per_device,
                            key=lambda d: int(d) if d.isdigit() else -1)}

    degraded = bool(retries or fallback_reasons
                    or int(root["attrs"].get("resume_entry") or 0) > 0)
    worker_s = sum(s["t1"] - s["t0"] for s in members
                   if s["name"].startswith("worker."))
    report = {
        "name": root["name"],
        "mode": root["attrs"].get("mode"),
        "engine": root["attrs"].get("engine"),
        "bytes": root["attrs"].get("bytes"),
        "wall_s": round(wall, 4),
        "dispatches": len(per_dispatch),
        "stage_s": {k: round(v, 4) for k, v in sorted(stage_s.items())},
        "worker_compute_s": round(worker_s, 4),  # concurrent track
        "drain_track_s": round(track_s, 4),      # concurrent fetch track
        # writer/fallback work on the drainer's threads, per stage
        "concurrent_stage_s": {k: round(v, 4)
                               for k, v in sorted(offthread_s.items())},
        "unattributed_s": round(unattributed, 4),
        "overlap_efficiency": round(1.0 - drain_s / wall, 4),
        "drain_profile": drain_profile,
        "attribution": attribution,
        "critical_path_stage": critical_path_stage,
        "critical_path": segments,
        "gap_analysis": _gap_analysis(members, drain_track, offthread),
        "degraded": degraded,
        "retries": retries,
        "fallbacks": sum(fallback_reasons.values()),
        "fallback_reasons": fallback_reasons,
    }
    if truncated:
        report["critical_path_truncated"] = truncated
    blocked_pct = round(100.0 * drain_s / wall)
    report["summary"] = (
        f"{critical_path_stage}-bound: {critical_path_stage} holds "
        f"{round(100.0 * attribution[critical_path_stage]['share'])}% of "
        f"{report['wall_s']}s wall ({blocked_pct}% blocked in drain); "
        f"{'DEGRADED' if degraded else 'clean'} run")
    return report


def _dropped_of(trace) -> int:
    """Span-loss accounting for the input: a live Tracer's ring-eviction
    counter, or the `dropped` field a to_dict()/collector document
    carries.  Surfaced on every report so a truncated trace cannot
    masquerade as a complete one."""
    if hasattr(trace, "dropped"):
        return int(trace.dropped)
    if isinstance(trace, dict):
        try:
            return int(trace.get("dropped")
                       or trace.get("spansDropped") or 0)
        except (TypeError, ValueError):
            return 0
    return 0


def analyze(trace, counters: Optional[dict] = None,
            max_path_items: int = 48) -> dict:
    """Trace (live Tracer, span list, to_dict() doc, or Chrome doc) ->
    attribution report.  `counters` is an optional restart/fallback
    totals dict (ec_pipeline_metrics().totals() or per-call encode
    stats); nonzero values mark the report degraded even when the
    ring has already rotated the retry spans out."""
    spans_dropped = _dropped_of(trace)
    spans = _normalize(trace)
    roots = [s for s in spans if s["name"] in ROOT_NAMES]
    runs = []
    claimed: set[int] = set()
    for root in roots:
        members = []
        for i, s in enumerate(spans):
            if s is root or i in claimed:
                continue
            if s["t0"] >= root["t0"] - _EPS and s["t1"] <= root["t1"] + _EPS:
                members.append(s)
                claimed.add(i)
        runs.append(_analyze_run(root, members, max_path_items))
    if not roots and spans:
        # no root captured (ring rotated / partial dump): synthesize one
        # run over the whole span set so the report stays useful
        synth = {"name": "pipeline.(partial)", "attrs": {},
                 "t0": min(s["t0"] for s in spans),
                 "t1": max(s["t1"] for s in spans)}
        runs.append(_analyze_run(synth, spans, max_path_items))
        runs[-1]["partial"] = True

    retry_n = sum(1 for s in spans if s["name"] == "pipeline.retry")
    fallback_n = sum(1 for s in spans if s["name"] == "pipeline.fallback")
    degraded = bool(retry_n or fallback_n or any(r["degraded"]
                                                for r in runs))
    health = dict(counters or {})
    if any(float(health.get(k) or 0) > 0 for k in DEGRADE_COUNTER_KEYS):
        degraded = True
    return {"span_count": len(spans), "runs": runs,
            "degraded": degraded, "retry_spans": retry_n,
            "fallback_spans": fallback_n, "health": health,
            "spans_dropped": spans_dropped}


# --- cross-server (cluster) analysis -----------------------------------------
# Input: a stitched trace document from the master's TraceCollector
# (observability/collector.py) — spans from every participating server,
# joined by trace id, with parent edges crossing process boundaries via
# the Traceparent header.  Output: per-hop occupancy, the network-vs-
# server time split, the cluster critical path naming the bounding hop,
# and a degraded verdict folding in every participating server's
# pipeline counters.

# outbound-hop span name (utils/httpd.py client helpers)
RPC_CLIENT = "rpc.client"


def _self_time(span: dict, children: list[dict]) -> float:
    """Duration minus time covered by child spans (merged intervals,
    clipped to the parent) — the seconds this span itself is
    responsible for."""
    t0, t1 = span["t0"], span["t1"]
    ivs = sorted((max(c["t0"], t0), min(c["t1"], t1)) for c in children)
    covered = 0.0
    cur0 = cur1 = None
    for a, b in ivs:
        if b <= a:
            continue
        if cur0 is None:
            cur0, cur1 = a, b
        elif a <= cur1:
            cur1 = max(cur1, b)
        else:
            covered += cur1 - cur0
            cur0, cur1 = a, b
    if cur0 is not None:
        covered += cur1 - cur0
    return max(0.0, (t1 - t0) - covered)


def _resolve_hop(sp: dict, kids: list[dict]) -> tuple[list[dict], str, str]:
    """Name an rpc.client span's far side: (remote children, to-server,
    remote op).  Prefers child spans recorded on a DIFFERENT server (the
    stitched request span); a hop whose remote never shipped its spans
    falls back to the client-side peer/path attrs.  Single source of
    truth for the hops table and the bounding-hop name — they must
    never attribute the same span to different servers."""
    remote = [c for c in kids if c.get("server") != sp.get("server")] \
        or kids
    attrs = sp.get("attrs") or {}
    to = remote[0].get("server") if remote else attrs.get("peer", "?")
    op = remote[0]["name"] if remote else str(attrs.get("path", "?"))
    return remote, to, op


def analyze_cluster(doc, health: Optional[dict] = None,
                    max_path_items: int = 32) -> dict:
    """Stitched cluster trace -> cross-server attribution report.

    `health` maps participating server url -> its pipeline_health
    counters (the master's aggregator view); any nonzero degrade
    counter on a PARTICIPATING server flips the verdict, so a rebuild
    that quietly demoted a corrupt survivor on a remote peer reads
    DEGRADED even though every span looks clean."""
    spans_dropped = _dropped_of(doc)
    trace_id = doc.get("trace_id") if isinstance(doc, dict) else None
    spans = _normalize(doc)
    by_id = {s["id"]: s for s in spans if s.get("id")}
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    for s in spans:
        par = s.get("parent")
        if par and par in by_id:
            children.setdefault(par, []).append(s)
        else:
            roots.append(s)
    servers = sorted({s["server"] for s in spans if s.get("server")})

    if not spans:
        # keep every key render_cluster_report() indexes — a trace whose
        # only content is a shipper loss ledger must still render (as a
        # truncation warning), not KeyError
        return {"trace_id": trace_id, "span_count": 0, "servers": [],
                "wall_s": 0.0, "root": None, "per_server": {}, "hops": [],
                "network_s": 0.0, "server_s": {}, "unattributed_s": 0.0,
                "critical_path": [], "bounding_hop": None,
                "degrade_events": 0, "error_spans": 0,
                "degraded": False, "degraded_servers": [],
                "health": dict(health or {}),
                "spans_dropped": spans_dropped,
                "summary": "empty trace"}

    wall_t0 = min(s["t0"] for s in spans)
    wall_t1 = max(s["t1"] for s in spans)
    wall = max(wall_t1 - wall_t0, _EPS)

    # per-server occupancy: each span's SELF time (children subtracted)
    # summed by server, so nested spans never double-count and time
    # spent waiting on a remote hop lands on the rpc.client span, not
    # the server that was waiting
    server_s: dict[str, float] = {}
    network_s = 0.0
    for s in spans:
        own = _self_time(s, children.get(s.get("id"), []))
        if s["name"] == RPC_CLIENT:
            # the caller-side slice of a hop not covered by the remote
            # server's recorded request span = wire + connect + queue
            network_s += own
        else:
            key = s.get("server") or "?"
            server_s[key] = server_s.get(key, 0.0) + own

    per_server = {}
    for srv in servers or ["?"]:
        busy = server_s.get(srv, 0.0)
        n = sum(1 for s in spans if s.get("server") == srv)
        per_server[srv] = {"spans": n, "busy_s": round(busy, 4),
                           "share": round(busy / wall, 4)}

    # hops: every rpc.client span, aggregated by (from, to, remote op)
    hops: dict[tuple, dict] = {}
    for s in spans:
        if s["name"] != RPC_CLIENT:
            continue
        remote, to, op = _resolve_hop(s, children.get(s.get("id"), []))
        key = (s.get("server") or "?", to or "?", op)
        row = hops.setdefault(key, {"from": key[0], "to": key[1],
                                    "op": op, "calls": 0,
                                    "client_s": 0.0, "server_s": 0.0,
                                    "network_s": 0.0})
        dur = s["t1"] - s["t0"]
        srv_covered = sum(c["t1"] - c["t0"] for c in remote)
        row["calls"] += 1
        row["client_s"] += dur
        row["server_s"] += min(srv_covered, dur)
        row["network_s"] += max(0.0, dur - srv_covered)
    hop_rows = sorted(hops.values(), key=lambda r: -r["client_s"])
    for row in hop_rows:
        for k in ("client_s", "server_s", "network_s"):
            row[k] = round(row[k], 4)

    # cluster critical path: from the earliest root, keep descending
    # into the child subtree that ends last (the one the parent's exit
    # actually waited for), recording each step's server + self time
    root = min(roots, key=lambda s: s["t0"]) if roots else spans[0]
    path: list[dict] = []
    cur = root
    seen: set[str] = set()
    while cur is not None and len(path) < max_path_items:
        sid = cur.get("id")
        if sid in seen:
            break  # defensive: a cyclic parent edge must not hang us
        seen.add(sid or f"@{len(path)}")
        kids = children.get(sid, [])
        path.append({"server": cur.get("server") or "?",
                     "name": cur["name"],
                     "s": round(_self_time(cur, kids), 4),
                     "span_id": sid})
        cur = max(kids, key=lambda c: c["t1"]) if kids else None

    # the bounding hop: the rpc.client on the critical path holding the
    # most wall time; with no hop on the path, the path step with the
    # largest self time bounds the trace
    path_rpcs = [p for p in path if p["name"] == RPC_CLIENT]
    if path_rpcs:
        worst = max(path_rpcs, key=lambda p: p["s"])
        sp = by_id.get(worst["span_id"]) or {}
        _, to, op = _resolve_hop(sp, children.get(worst["span_id"], []))
        dur = (sp.get("t1", 0.0) - sp.get("t0", 0.0)) if sp else worst["s"]
        bounding = {"kind": "hop", "from": worst["server"], "to": to,
                    "op": op, "s": round(dur, 4),
                    "network_s": worst["s"]}
    elif path:
        worst = max(path, key=lambda p: p["s"])
        bounding = {"kind": "local", "server": worst["server"],
                    "op": worst["name"], "s": worst["s"]}
    else:
        bounding = None

    # degraded verdict: in-trace recovery events, error-tagged spans,
    # or nonzero degrade counters on ANY participating server
    degrade_events = sum(1 for s in spans
                         if s["name"] in DEGRADE_EVENT_NAMES)
    errors = sum(1 for s in spans if s["attrs"].get("error"))
    health = dict(health or {})
    degraded_servers = sorted(
        srv for srv, counters in health.items()
        if any(float((counters or {}).get(k) or 0) > 0
               for k in DEGRADE_COUNTER_KEYS))
    degraded = bool(degrade_events or errors or degraded_servers)

    total_attr = sum(server_s.values()) + network_s
    summary_bits = []
    if bounding is not None:
        if bounding["kind"] == "hop":
            summary_bits.append(
                f"bounding hop {bounding['from']} -> {bounding['to']} "
                f"{bounding['op']} ({bounding['s']}s, "
                f"{bounding['network_s']}s network)")
        else:
            summary_bits.append(
                f"bounded locally by {bounding['op']} on "
                f"{bounding['server']} ({bounding['s']}s)")
    summary_bits.append(
        f"network {round(network_s, 4)}s vs server "
        f"{round(sum(server_s.values()), 4)}s over {wall:.4f}s wall")
    summary_bits.append("DEGRADED" if degraded else "clean")
    return {
        "trace_id": trace_id,
        "span_count": len(spans),
        "servers": servers,
        "wall_s": round(wall, 4),
        "root": {"name": root["name"],
                 "server": root.get("server") or "?"},
        "per_server": per_server,
        "hops": hop_rows,
        "network_s": round(network_s, 4),
        "server_s": {k: round(v, 4) for k, v in sorted(server_s.items())},
        "unattributed_s": round(max(0.0, wall - total_attr), 4),
        "critical_path": path,
        "bounding_hop": bounding,
        "degrade_events": degrade_events,
        "error_spans": errors,
        "degraded": degraded,
        "degraded_servers": degraded_servers,
        "health": health,
        "spans_dropped": spans_dropped,
        "summary": "; ".join(summary_bits),
    }


def render_cluster_report(report: dict) -> str:
    """Human rendering of analyze_cluster() (`weed shell trace.fetch`)."""
    lines = [f"trace {report.get('trace_id')}: "
             f"{report['span_count']} spans across "
             f"{len(report['servers'])} server(s), "
             f"wall {report['wall_s']}s — "
             f"{'DEGRADED' if report['degraded'] else 'clean'}"]
    if report.get("spans_dropped"):
        lines.append(f"WARNING: {report['spans_dropped']} spans dropped — "
                     "stitched trace is INCOMPLETE")
    lines.append(f"  {report['summary']}")
    for srv, row in sorted(report["per_server"].items(),
                           key=lambda kv: -kv[1]["busy_s"]):
        bar = "#" * int(round(40 * row["share"]))
        lines.append(f"  {srv:<22} {row['busy_s']:>9.3f}s "
                     f"{100 * row['share']:5.1f}% "
                     f"({row['spans']} spans) {bar}")
    if report["hops"]:
        lines.append("  hops (client / server / network seconds):")
        for h in report["hops"][:12]:
            lines.append(f"    {h['from']} -> {h['to']} {h['op']} x"
                         f"{h['calls']}: {h['client_s']} / "
                         f"{h['server_s']} / {h['network_s']}")
    if report["critical_path"]:
        steps = " -> ".join(f"{p['server']}:{p['name']}"
                            for p in report["critical_path"][:10])
        lines.append(f"  critical path: {steps}")
    if report["degraded_servers"]:
        lines.append("  degraded servers: "
                     + ", ".join(report["degraded_servers"]))
    return "\n".join(lines) + "\n"


def attribution_summary(report: dict) -> dict:
    """The compact block bench.py embeds as e2e_pipeline_*.attribution:
    per-stage seconds, the critical-path stage, and the degraded flag
    for the report's LAST run (the measured rep)."""
    if not report.get("runs"):
        return {"degraded": report.get("degraded", False)}
    run = report["runs"][-1]
    return {
        "stage_s": run["stage_s"],
        "unattributed_s": run["unattributed_s"],
        "wall_s": run["wall_s"],
        "critical_path_stage": run["critical_path_stage"],
        "overlap_efficiency": run["overlap_efficiency"],
        "drain_profile": run.get("drain_profile"),
        "degraded": bool(report.get("degraded") or run["degraded"]),
        "summary": run["summary"],
    }


def render_report(report: dict) -> str:
    """Human-readable rendering (the `weed shell` trace.analyze view)."""
    lines = [f"spans analyzed: {report['span_count']}  "
             f"degraded: {report['degraded']}  "
             f"(retry spans: {report['retry_spans']}, "
             f"fallback spans: {report['fallback_spans']})"]
    if report.get("spans_dropped"):
        lines.append(f"WARNING: {report['spans_dropped']} spans dropped "
                     "(ring eviction / ship loss) — this trace is "
                     "TRUNCATED, attribution may under-count")
    health = report.get("health") or {}
    if health:
        lines.append("health counters: " + ", ".join(
            f"{k}={v}" for k, v in sorted(health.items())))
    if not report["runs"]:
        lines.append("no pipeline runs in the trace "
                     "(enable tracing, run an encode, re-analyze)")
    for i, run in enumerate(report["runs"]):
        lines.append("")
        lines.append(f"run {i}: {run['name']} mode={run['mode']} "
                     f"engine={run['engine']} "
                     f"dispatches={run['dispatches']} "
                     f"wall={run['wall_s']}s")
        lines.append(f"  {run['summary']}")
        lines.append(f"  overlap_efficiency={run['overlap_efficiency']}")
        dp = run.get("drain_profile") or {}
        if dp.get("classification") and dp["classification"] != "none":
            lines.append(
                f"  drain: {dp['classification']} (host blocked "
                f"{dp['host_blocked_s']}s, concurrent fetch "
                f"{dp['fetch_s']}s, link-covered {dp['link_bound_s']}s)")
        width = max((len(k) for k in run["attribution"]), default=1)
        for stage, row in sorted(run["attribution"].items(),
                                 key=lambda kv: -kv[1]["s"]):
            bar = "#" * int(round(40 * row["share"]))
            lines.append(f"  {stage:<{width}} {row['s']:>9.3f}s "
                         f"{100 * row['share']:5.1f}% {bar}")
        ga = run["gap_analysis"]
        if ga["worker_windows"]:
            cls = ", ".join(f"{k}={v}s" for k, v in ga["classes"].items()
                            if v > 0)
            lines.append(f"  worker gaps: {ga['gap_total_s']}s over "
                         f"{ga['worker_windows']} windows ({cls or 'none'})")
        if run["critical_path"]:
            path = " -> ".join(
                f"{seg['stage']}[d{seg['dispatches'][0]}"
                + (f"-{seg['dispatches'][1]}"
                   if seg["dispatches"][1] != seg["dispatches"][0] else "")
                + "]" for seg in run["critical_path"][:12])
            lines.append(f"  critical path: {path}")
    return "\n".join(lines) + "\n"
