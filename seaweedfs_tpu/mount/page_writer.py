"""Write-back page cache for one open file.

Equivalent of weed/mount/page_writer/ (upload_pipeline.go,
page_chunk_mem.go, dirty_pages_chunked.go): writes land in fixed-size
in-memory chunk buffers aligned to the filer chunk size; a chunk seals
when fully written past or on flush, and sealed chunks upload through
the supplied uploader.  Reads at unflushed offsets are served from the
dirty pages so read-your-writes holds before flush.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class _DirtyChunk:
    __slots__ = ("index", "buf", "intervals")

    def __init__(self, index: int, chunk_size: int):
        self.index = index
        self.buf = bytearray(chunk_size)
        self.intervals: list[tuple[int, int]] = []  # sorted (start, stop)

    def write(self, off: int, data: bytes) -> None:
        self.buf[off:off + len(data)] = data
        self.intervals = _merge(self.intervals, (off, off + len(data)))

    def read(self, off: int, size: int) -> Optional[bytes]:
        """Bytes if fully covered by written intervals, else None."""
        stop = off + size
        for a, b in self.intervals:
            if a <= off and stop <= b:
                return bytes(self.buf[off:stop])
        return None

    @property
    def written_span(self) -> tuple[int, int]:
        return (self.intervals[0][0], self.intervals[-1][1]) \
            if self.intervals else (0, 0)

    def is_complete(self, chunk_size: int) -> bool:
        return self.intervals == [(0, chunk_size)]


def _merge(ivs: list[tuple[int, int]],
           new: tuple[int, int]) -> list[tuple[int, int]]:
    out: list[tuple[int, int]] = []
    a, b = new
    for x, y in ivs:
        if y < a or x > b:
            out.append((x, y))
        else:
            a, b = min(a, x), max(b, y)
    out.append((a, b))
    out.sort()
    return out


class PageWriter:
    """Dirty pages for one file handle.

    uploader(chunk_logical_offset, data) -> chunk dict (FileChunk.to_dict
    shape); flush() returns every uploaded chunk in offset order.
    """

    def __init__(self, uploader: Callable[[int, bytes], dict],
                 chunk_size: int = 8 * 1024 * 1024):
        self.chunk_size = chunk_size
        self.uploader = uploader
        self._lock = threading.Lock()
        self._chunks: dict[int, _DirtyChunk] = {}
        self._uploaded: list[dict] = []
        self.file_size_hint = 0

    def write(self, offset: int, data: bytes) -> int:
        """Buffer a write; seals+uploads any chunk that becomes full."""
        written = len(data)
        with self._lock:
            self.file_size_hint = max(self.file_size_hint,
                                      offset + written)
            pos = 0
            sealed: list[_DirtyChunk] = []
            while pos < len(data):
                idx = (offset + pos) // self.chunk_size
                in_off = (offset + pos) % self.chunk_size
                can = min(len(data) - pos, self.chunk_size - in_off)
                chunk = self._chunks.get(idx)
                if chunk is None:
                    chunk = self._chunks[idx] = _DirtyChunk(
                        idx, self.chunk_size)
                chunk.write(in_off, data[pos:pos + can])
                if chunk.is_complete(self.chunk_size):
                    sealed.append(self._chunks.pop(idx))
                pos += can
            for chunk in sealed:
                self._upload_locked(chunk)
        return written

    def _upload_locked(self, chunk: _DirtyChunk) -> None:
        start, stop = chunk.written_span
        base = chunk.index * self.chunk_size
        uploaded = self.uploader(base + start, bytes(chunk.buf[start:stop]))
        self._uploaded.append(uploaded)

    def read_dirty(self, offset: int, size: int) -> Optional[bytes]:
        """Serve a read from unflushed pages when fully covered."""
        with self._lock:
            idx = offset // self.chunk_size
            in_off = offset % self.chunk_size
            if in_off + size <= self.chunk_size:
                chunk = self._chunks.get(idx)
                return chunk.read(in_off, size) if chunk else None
            # spans chunks: assemble or give up
            parts: list[bytes] = []
            pos = 0
            while pos < size:
                idx = (offset + pos) // self.chunk_size
                in_off = (offset + pos) % self.chunk_size
                can = min(size - pos, self.chunk_size - in_off)
                chunk = self._chunks.get(idx)
                piece = chunk.read(in_off, can) if chunk else None
                if piece is None:
                    return None
                parts.append(piece)
                pos += can
            return b"".join(parts)

    def truncate(self, size: int) -> None:
        """Drop dirty state at/past the new size — data buffered beyond a
        truncate point must never resurface when the handle flushes
        (POSIX write-then-ftruncate).  Already-uploaded chunk dicts are
        trimmed the same way; partially-covered dirty chunks are trimmed
        by shrinking their written span."""
        with self._lock:
            self.file_size_hint = min(self.file_size_hint, size)
            for idx in [i for i in self._chunks
                        if i * self.chunk_size >= size]:
                del self._chunks[idx]
            cut = size % self.chunk_size
            boundary_idx = size // self.chunk_size
            chunk = self._chunks.get(boundary_idx)
            if chunk is not None:
                chunk.intervals = [
                    (a, min(b, cut)) for a, b in chunk.intervals if a < cut]
                if not chunk.intervals:
                    del self._chunks[boundary_idx]
            kept = []
            for c in self._uploaded:
                if c["offset"] >= size:
                    continue
                if c["offset"] + c["size"] > size:
                    c = dict(c, size=size - c["offset"])
                kept.append(c)
            self._uploaded = kept

    def flush(self) -> list[dict]:
        """Seal + upload every dirty chunk; returns all uploaded chunk
        dicts (offset order) and resets the uploaded list."""
        with self._lock:
            for idx in sorted(self._chunks):
                self._upload_locked(self._chunks.pop(idx))
            out, self._uploaded = self._uploaded, []
            out.sort(key=lambda c: c["offset"])
            return out

    @property
    def has_dirty(self) -> bool:
        with self._lock:
            return bool(self._chunks) or bool(self._uploaded)
