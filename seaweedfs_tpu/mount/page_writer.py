"""Write-back page cache + bounded async upload pipeline for one file.

Equivalent of weed/mount/page_writer/ (upload_pipeline.go,
page_chunk_mem.go, dirty_pages_chunked.go): writes land in fixed-size
in-memory chunk buffers aligned to the filer chunk size, tracked as
merged dirty intervals so random writes upload only what was dirtied.
A chunk SEALS when fully written, when memory pressure evicts the
oldest dirty chunk, or on flush; sealed chunks upload concurrently on a
small worker pool (ref upload_pipeline.go's bounded uploaders) while
later writes keep landing.  Reads at unflushed offsets are served from
dirty AND sealed-uploading buffers, so read-your-writes holds before
flush; once a sealed buffer's upload completes it is freed (the chunk
dict is collected by flush()).

Back-pressure: writes block when too many sealed uploads are in flight
(oldest-future wait), and the oldest dirty chunk is force-sealed when
the dirty set outgrows its budget — a random writer to a huge file
holds O(budget) memory, not O(file).
"""

from __future__ import annotations

import concurrent.futures
import threading
from typing import Callable, Optional


class _DirtyChunk:
    __slots__ = ("index", "buf", "intervals")

    def __init__(self, index: int, chunk_size: int):
        self.index = index
        self.buf = bytearray(chunk_size)
        self.intervals: list[tuple[int, int]] = []  # sorted (start, stop)

    def write(self, off: int, data: bytes) -> None:
        self.buf[off:off + len(data)] = data
        self.intervals = _merge(self.intervals, (off, off + len(data)))

    def read(self, off: int, size: int) -> Optional[bytes]:
        """Bytes if fully covered by written intervals, else None."""
        stop = off + size
        for a, b in self.intervals:
            if a <= off and stop <= b:
                return bytes(self.buf[off:stop])
        return None

    @property
    def written_span(self) -> tuple[int, int]:
        return (self.intervals[0][0], self.intervals[-1][1]) \
            if self.intervals else (0, 0)

    def is_complete(self, chunk_size: int) -> bool:
        return self.intervals == [(0, chunk_size)]


def _merge(ivs: list[tuple[int, int]],
           new: tuple[int, int]) -> list[tuple[int, int]]:
    out: list[tuple[int, int]] = []
    a, b = new
    for x, y in ivs:
        if y < a or x > b:
            out.append((x, y))
        else:
            a, b = min(a, x), max(b, y)
    out.append((a, b))
    out.sort()
    return out


class _SealedChunk:
    """A chunk handed to the upload pool: its buffer stays readable
    (read-your-writes during the upload) until the worker finishes."""

    __slots__ = ("index", "buf", "intervals", "future", "seq")

    def __init__(self, chunk: _DirtyChunk, seq: int):
        self.index = chunk.index
        self.buf = chunk.buf
        self.intervals = chunk.intervals
        self.future: Optional[concurrent.futures.Future] = None
        self.seq = seq  # seal-time ns: write order survives out-of-order
        #                 upload completion (overlap shadowing)

    def read(self, off: int, size: int) -> Optional[bytes]:
        if self.buf is None:  # upload done, buffer released
            return None
        stop = off + size
        for a, b in self.intervals:
            if a <= off and stop <= b:
                return bytes(self.buf[off:stop])
        return None


class PageWriter:
    """Dirty pages + upload pipeline for one file handle.

    uploader(chunk_logical_offset, data) -> chunk dict (FileChunk.to_dict
    shape); flush() returns every uploaded chunk in offset order.
    """

    def __init__(self, uploader: Callable[[int, bytes], dict],
                 chunk_size: int = 8 * 1024 * 1024,
                 concurrency: int = 4, max_dirty_chunks: int = 8):
        self.chunk_size = chunk_size
        self.uploader = uploader
        self.concurrency = concurrency
        self.max_dirty_chunks = max_dirty_chunks
        self._lock = threading.Lock()
        self._chunks: dict[int, _DirtyChunk] = {}
        self._order: list[int] = []  # dirty chunk LRU (insertion order)
        self._sealed: list[_SealedChunk] = []
        self._uploaded: list[tuple[int, dict]] = []  # (seal seq, chunk)
        self._errors: list[Exception] = []  # failed uploads, raised at flush
        self._last_seal_ns = 0
        self._pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self.file_size_hint = 0

    def _ensure_pool(self) -> concurrent.futures.ThreadPoolExecutor:
        if self._pool is None:
            self._pool = concurrent.futures.ThreadPoolExecutor(
                max_workers=self.concurrency,
                thread_name_prefix="page-upload")
        return self._pool

    # --- write path -------------------------------------------------------
    def write(self, offset: int, data: bytes) -> int:
        """Buffer a write; seals any chunk that becomes full and hands it
        to the upload pool.  Blocks (back-pressure) when too many uploads
        are already in flight."""
        written = len(data)
        wait_on: list[concurrent.futures.Future] = []
        with self._lock:
            self.file_size_hint = max(self.file_size_hint,
                                      offset + written)
            pos = 0
            while pos < len(data):
                idx = (offset + pos) // self.chunk_size
                in_off = (offset + pos) % self.chunk_size
                can = min(len(data) - pos, self.chunk_size - in_off)
                chunk = self._chunks.get(idx)
                if chunk is None:
                    chunk = self._chunks[idx] = _DirtyChunk(
                        idx, self.chunk_size)
                    self._order.append(idx)
                chunk.write(in_off, data[pos:pos + can])
                if chunk.is_complete(self.chunk_size):
                    self._seal_locked(idx)
                pos += can
            # memory budget: a random writer dirties many chunks that
            # never complete — seal the OLDEST so memory stays O(budget)
            while len(self._chunks) > self.max_dirty_chunks:
                self._seal_locked(self._order[0])
            self._reap_locked()
            # back-pressure: bound in-flight uploads
            inflight = [s.future for s in self._sealed
                        if not s.future.done()]
            if len(inflight) > 2 * self.concurrency:
                wait_on = inflight[:len(inflight) - 2 * self.concurrency]
        for f in wait_on:  # outside the lock: readers stay unblocked
            f.exception()  # stashed by _reap; raised at flush, not here
        return written

    def _reap_locked(self) -> None:
        """Drop sealed chunks whose upload finished (their buffer is
        already freed), stashing any upload exception for flush()."""
        keep = []
        for s in self._sealed:
            if s.future.done():
                exc = s.future.exception()
                if exc is not None:
                    self._errors.append(exc)
            else:
                keep.append(s)
        self._sealed = keep

    def _seal_locked(self, idx: int) -> None:
        import time as _time

        chunk = self._chunks.pop(idx)
        self._order.remove(idx)
        if not chunk.intervals:
            return
        self._last_seal_ns = seq = max(_time.time_ns(),
                                       self._last_seal_ns + 1)
        sealed = _SealedChunk(chunk, seq)
        self._sealed.append(sealed)
        sealed.future = self._ensure_pool().submit(self._do_upload, sealed)

    def _do_upload(self, sealed: _SealedChunk) -> None:
        start, stop = sealed.intervals[0][0], sealed.intervals[-1][1]
        base = sealed.index * self.chunk_size
        uploaded = self.uploader(base + start,
                                 bytes(sealed.buf[start:stop]))
        if "modified_ts_ns" in uploaded:
            # overlap resolution keys on mtime: write (seal) order must
            # win, not upload COMPLETION order across pool workers
            uploaded["modified_ts_ns"] = sealed.seq
        with self._lock:
            self._uploaded.append((sealed.seq, uploaded))
            sealed.buf = None  # readable window ends; memory released

    # --- read path --------------------------------------------------------
    def read_dirty(self, offset: int, size: int) -> Optional[bytes]:
        """Serve a read from unflushed pages (dirty or sealed-uploading)
        when fully covered."""
        with self._lock:
            parts: list[bytes] = []
            pos = 0
            while pos < size:
                idx = (offset + pos) // self.chunk_size
                in_off = (offset + pos) % self.chunk_size
                can = min(size - pos, self.chunk_size - in_off)
                piece = None
                chunk = self._chunks.get(idx)
                if chunk is not None:
                    piece = chunk.read(in_off, can)
                if piece is None:
                    for s in reversed(self._sealed):  # newest seal wins
                        if s.index == idx:
                            piece = s.read(in_off, can)
                            if piece is not None:
                                break
                if piece is None:
                    return None
                parts.append(piece)
                pos += can
            return b"".join(parts)

    # --- truncate ---------------------------------------------------------
    def truncate(self, size: int) -> None:
        """Drop dirty state at/past the new size — data buffered beyond a
        truncate point must never resurface when the handle flushes
        (POSIX write-then-ftruncate).  In-flight uploads drain first so
        their chunk dicts can be trimmed synchronously too."""
        errors = self._drain()
        with self._lock:
            # upload failures must still surface at the next flush —
            # truncation doesn't absolve lost chunks below the cut
            self._errors.extend(errors)
            self.file_size_hint = min(self.file_size_hint, size)
            for idx in [i for i in self._chunks
                        if i * self.chunk_size >= size]:
                del self._chunks[idx]
                self._order.remove(idx)
            cut = size % self.chunk_size
            boundary_idx = size // self.chunk_size
            chunk = self._chunks.get(boundary_idx)
            if chunk is not None:
                chunk.intervals = [
                    (a, min(b, cut)) for a, b in chunk.intervals if a < cut]
                if not chunk.intervals:
                    del self._chunks[boundary_idx]
                    self._order.remove(boundary_idx)
            kept = []
            for seq, c in self._uploaded:
                if c["offset"] >= size:
                    continue
                if c["offset"] + c["size"] > size:
                    c = dict(c, size=size - c["offset"])
                kept.append((seq, c))
            self._uploaded = kept

    # --- flush ------------------------------------------------------------
    def _drain(self) -> list[Exception]:
        """Wait for every in-flight upload; sealed chunks stay readable
        (and listed) until their future completes.  Returns accumulated
        upload errors."""
        while True:
            with self._lock:
                pending = list(self._sealed)
                if not pending:
                    errors, self._errors = self._errors, []
                    return errors
            for s in pending:
                s.future.exception()  # wait; error stashed by reap below
            with self._lock:
                self._reap_locked()

    def flush(self) -> list[dict]:
        """Seal + upload every dirty chunk, wait for the pipeline, and
        return all uploaded chunk dicts (offset order).  Upload failures
        surface here (the kernel's flush/fsync gets the EIO)."""
        with self._lock:
            for idx in sorted(self._chunks):
                self._seal_locked(idx)
        errors = self._drain()
        if errors:
            raise errors[0]
        with self._lock:
            pairs, self._uploaded = self._uploaded, []
            # entry chunk-list order carries overlap shadowing: same
            # range rewritten later must append later
            pairs.sort(key=lambda p: (p[1]["offset"], p[0]))
            return [c for _, c in pairs]

    @property
    def has_dirty(self) -> bool:
        with self._lock:
            return bool(self._chunks) or bool(self._sealed) \
                or bool(self._uploaded)
