"""WFS: the mount's filesystem operation layer, kernel-independent.

Equivalent of weed/mount/weedfs.go:57-180 plus the per-op files
(weedfs_file_read.go, weedfs_file_write.go, weedfs_dir_*.go,
weedfs_attr.go, weedfs_rename.go): every FUSE op implemented against
the filer HTTP API, with a MetaCache for stats/listings, an
InodeToPath map, and per-handle PageWriter write-back.  The libfuse
bridge (fuse_bridge.py) is a thin adapter over this class, so the
whole surface tests in-process without a kernel.
"""

from __future__ import annotations

import errno
import hashlib
import threading
import time
import urllib.parse
from typing import Optional

from ..client.operation import WeedClient
from ..filer.entry import DIRECTORY_MODE_BIT, Attr, Entry, FileChunk
from ..filer.filechunks import total_size
from ..utils.httpd import HttpError, http_bytes, http_json
from .inode_to_path import InodeToPath
from .meta_cache import MetaCache
from .page_writer import PageWriter


class FuseError(OSError):
    def __init__(self, err: int, msg: str = ""):
        super().__init__(err, msg or errno.errorcode.get(err, str(err)))
        self.errno = err


class FileHandle:
    _next_id = [2]
    _id_lock = threading.Lock()

    def __init__(self, wfs: "WFS", path: str, entry: Entry):
        with self._id_lock:
            self.fh = self._next_id[0]
            self._next_id[0] += 1
        self.wfs = wfs
        self.path = path
        self.entry = entry
        self.lock = threading.Lock()
        self.writer = PageWriter(self._upload_chunk,
                                 chunk_size=wfs.chunk_size)

    def _upload_chunk(self, logical_offset: int, data: bytes) -> dict:
        fid = self.wfs.client.upload(data, collection=self.wfs.collection,
                                     replication=self.wfs.replication)
        return FileChunk(
            file_id=fid, offset=logical_offset, size=len(data),
            modified_ts_ns=time.time_ns(),
            etag=hashlib.md5(data).hexdigest()).to_dict()


class WFS:
    """One mounted filesystem rooted at filer_path."""

    def __init__(self, filer_url: str, filer_path: str = "/",
                 chunk_size_mb: int = 8, collection: str = "",
                 replication: str = "", master_url: str = ""):
        self.filer_url = filer_url
        self.root = filer_path.rstrip("/") or ""
        self.chunk_size = chunk_size_mb * 1024 * 1024
        self.collection = collection
        self.replication = replication
        info = http_json("GET", f"http://{filer_url}/api/info", timeout=30.0)
        self.client = WeedClient(master_url or info["master"])
        self.inodes = InodeToPath()
        self.meta = MetaCache(filer_url).start()
        self._handles: dict[int, FileHandle] = {}
        self._hlock = threading.Lock()

    def close(self) -> None:
        for fh in list(self._handles.values()):
            try:
                self.flush(fh.fh)
            except Exception:
                pass
        self.meta.stop()
        self.client.close()

    # --- path plumbing ----------------------------------------------------
    def _abs(self, path: str) -> str:
        """Mount-relative -> filer-absolute."""
        if not path.startswith("/"):
            path = "/" + path
        return (self.root + path).rstrip("/") or "/"

    def _quote(self, path: str) -> str:
        return urllib.parse.quote(self._abs(path))

    # --- entry fetch (weedfs.go maybeLoadEntry) ---------------------------
    def get_entry(self, path: str) -> Entry:
        apath = self._abs(path)
        cached = self.meta.get(apath)
        if cached is not None:
            return cached
        status, body, _ = http_bytes(
            "GET", f"http://{self.filer_url}/api/stat"
            + urllib.parse.quote(apath), timeout=60.0)
        if status == 404:
            raise FuseError(errno.ENOENT, path)
        if status != 200:
            raise FuseError(errno.EIO, f"stat {path}: {status}")
        import json

        entry = Entry.from_dict(json.loads(body))
        self.meta.put(entry)
        return entry

    # --- ops --------------------------------------------------------------
    def lookup(self, path: str) -> tuple[int, Entry]:
        entry = self.get_entry(path)
        ino = self.inodes.lookup(self._abs(path), entry.is_directory)
        return ino, entry

    def getattr(self, path: str) -> dict:
        # open handles know sizes the filer doesn't yet (dirty pages)
        entry = self.get_entry(path)
        size = entry.file_size
        with self._hlock:
            for h in self._handles.values():
                if h.path == path:
                    size = max(size, h.writer.file_size_hint)
        mode = entry.attr.mode
        return {
            "st_mode": (0o040000 | (mode & 0o7777)) if entry.is_directory
            else (0o100000 | (mode & 0o7777)),
            "st_size": size,
            "st_mtime": entry.attr.mtime,
            "st_ctime": entry.attr.crtime,
            "st_uid": entry.attr.uid,
            "st_gid": entry.attr.gid,
            "st_nlink": 2 if entry.is_directory else 1,
        }

    def readdir(self, path: str) -> list[Entry]:
        apath = self._abs(path)
        if self.meta.is_listed(apath):
            return self.meta.list_cached(apath)
        entries: list[Entry] = []
        last = ""
        while True:
            # full=true returns complete entry dicts in the listing: one
            # request per page instead of one /api/stat per child
            q = (f"?limit=1000&full=true"
                 f"&lastFileName={urllib.parse.quote(last)}")
            status, body, _ = http_bytes(
                "GET", f"http://{self.filer_url}"
                + urllib.parse.quote(apath or "/") + q, timeout=60.0)
            if status != 200:
                raise FuseError(errno.ENOENT, path)
            import json

            d = json.loads(body)
            if "Entries" not in d:
                raise FuseError(errno.ENOTDIR, path)
            for item in d["Entries"]:
                e = Entry.from_dict(item)
                self.meta.put(e)
                entries.append(e)
            if not d.get("ShouldDisplayLoadMore") or not d.get("LastFileName"):
                break
            last = d["LastFileName"]
        self.meta.mark_listed(apath)
        return entries

    def mkdir(self, path: str, mode: int = 0o755) -> None:
        http_json("POST", f"http://{self.filer_url}/api/mkdir",
                  {"path": self._abs(path)}, timeout=30.0)

    def _put_entry(self, entry: Entry) -> None:
        status, body, _ = http_bytes(
            "POST", f"http://{self.filer_url}/api/entry",
            __import__("json").dumps(entry.to_dict()).encode(),
            headers={"Content-Type": "application/json"}, timeout=60.0)
        if status not in (200, 201):
            raise FuseError(errno.EIO, body.decode(errors="replace"))
        self.meta.put(entry)

    def create(self, path: str, mode: int = 0o644) -> FileHandle:
        apath = self._abs(path)
        entry = Entry(full_path=apath,
                      attr=Attr(mode=mode & 0o7777, mtime=time.time(),
                                crtime=time.time(),
                                collection=self.collection,
                                replication=self.replication))
        self._put_entry(entry)
        self.inodes.lookup(apath, False)
        return self._new_handle(path, entry)

    def open(self, path: str) -> FileHandle:
        entry = self.get_entry(path)
        if entry.is_directory:
            raise FuseError(errno.EISDIR, path)
        return self._new_handle(path, entry)

    def _new_handle(self, path: str, entry: Entry) -> FileHandle:
        h = FileHandle(self, path, entry)
        h.writer.file_size_hint = entry.file_size
        with self._hlock:
            self._handles[h.fh] = h
        return h

    def handle(self, fh: int) -> FileHandle:
        with self._hlock:
            h = self._handles.get(fh)
        if h is None:
            raise FuseError(errno.EBADF, str(fh))
        return h

    def write(self, fh: int, offset: int, data: bytes) -> int:
        h = self.handle(fh)
        with h.lock:
            return h.writer.write(offset, data)

    def read(self, fh: int, offset: int, size: int) -> bytes:
        h = self.handle(fh)
        dirty = h.writer.read_dirty(offset, size)
        if dirty is not None:
            return dirty
        if h.writer.has_dirty:
            # partial overlap with dirty state: flush for correctness
            self.flush(fh)
        status, body, _ = http_bytes(
            "GET", f"http://{self.filer_url}" + self._quote(h.path),
            headers={"Range": f"bytes={offset}-{offset + size - 1}"},
                timeout=60.0)
        if status in (200, 206):
            return body
        if status == 416:
            return b""
        raise FuseError(errno.EIO, f"read {h.path}: {status}")

    def flush(self, fh: int) -> None:
        """Combine uploaded dirty chunks into the entry
        (weedfs_file_sync.go doFlush)."""
        h = self.handle(fh)
        with h.lock:
            new_chunks = h.writer.flush()
            if not new_chunks:
                return
            entry = self.get_entry(h.path)
            chunks = entry.chunks + [FileChunk.from_dict(c)
                                     for c in new_chunks]
            entry = Entry(full_path=entry.full_path, attr=entry.attr,
                          chunks=chunks, extended=entry.extended)
            entry.attr.mtime = time.time()
            self._put_entry(entry)
            h.entry = entry

    def release(self, fh: int) -> None:
        try:
            self.flush(fh)
        finally:
            # the kernel never retries release: a flush failure must not
            # leak the handle (and its dirty pages) forever
            with self._hlock:
                self._handles.pop(fh, None)

    def unlink(self, path: str) -> None:
        status, body, _ = http_bytes(
            "DELETE", f"http://{self.filer_url}" + self._quote(path),
                timeout=60.0)
        if status == 404:
            raise FuseError(errno.ENOENT, path)
        if status not in (200, 204):
            raise FuseError(errno.EIO, body.decode(errors="replace"))
        self.meta.delete(self._abs(path))
        self.inodes.remove_path(self._abs(path))

    def rmdir(self, path: str) -> None:
        entry = self.get_entry(path)
        if not entry.is_directory:
            raise FuseError(errno.ENOTDIR, path)
        if self.readdir(path):
            raise FuseError(errno.ENOTEMPTY, path)
        self.unlink(path)

    def link(self, target: str, link: str) -> None:
        """Hardlink (mount/weedfs_link.go): filer-side content sharing."""
        status, body, _ = http_bytes(
            "POST", f"http://{self.filer_url}/api/link",
            __import__("json").dumps(
                {"target": self._abs(target),
                 "link": self._abs(link)}).encode(),
            headers={"Content-Type": "application/json"}, timeout=60.0)
        if status != 200:
            raise FuseError(errno.EIO, body.decode(errors="replace"))
        self.meta.delete(self._abs(target))
        self.meta.delete(self._abs(link))

    def rename(self, old: str, new: str) -> None:
        status, body, _ = http_bytes(
            "POST", f"http://{self.filer_url}/api/rename",
            __import__("json").dumps(
                {"from": self._abs(old), "to": self._abs(new)}).encode(),
            headers={"Content-Type": "application/json"}, timeout=60.0)
        if status != 200:
            raise FuseError(errno.EIO, body.decode(errors="replace"))
        self.meta.delete(self._abs(old))
        self.meta.delete(self._abs(new))
        self.inodes.move_path(self._abs(old), self._abs(new))
        # retarget open handles (like the reference's inode-based handles):
        # flush/release after rename-while-open must hit the new path, or the
        # dirty pages are silently dropped against a 404
        old_prefix = old.rstrip("/") + "/"
        with self._hlock:
            for h in self._handles.values():
                if h.path == old:
                    h.path = new
                elif h.path.startswith(old_prefix):
                    h.path = new.rstrip("/") + "/" + h.path[len(old_prefix):]
                else:
                    continue
                if h.entry is not None:
                    h.entry.full_path = self._abs(h.path)

    def truncate(self, path: str, size: int) -> None:
        """weedfs_attr.go setattr size change: trim/drop chunks."""
        entry = self.get_entry(path)
        if size == 0:
            chunks: list[FileChunk] = []
        else:
            chunks = []
            for c in entry.chunks:
                if c.offset >= size:
                    continue
                if c.offset + c.size > size:
                    c = FileChunk(c.file_id, c.offset, size - c.offset,
                                  c.modified_ts_ns, c.etag)
                chunks.append(c)
        new_entry = Entry(full_path=entry.full_path, attr=entry.attr,
                          chunks=chunks, extended=entry.extended)
        new_entry.attr.mtime = time.time()
        self._put_entry(new_entry)
        with self._hlock:
            for h in list(self._handles.values()):
                if h.path == path:
                    # dirty pages past the truncate point must die with it
                    # or they resurface on flush (write-then-ftruncate)
                    h.writer.truncate(size)
                    h.entry = new_entry

    def setattr(self, path: str, mode: Optional[int] = None,
                uid: Optional[int] = None, gid: Optional[int] = None,
                mtime: Optional[float] = None) -> None:
        entry = self.get_entry(path)
        attr = Attr(**{**entry.attr.__dict__})
        if mode is not None:
            dir_bit = entry.attr.mode & DIRECTORY_MODE_BIT
            attr.mode = (mode & 0o7777) | dir_bit
        if uid is not None:
            attr.uid = uid
        if gid is not None:
            attr.gid = gid
        if mtime is not None:
            attr.mtime = mtime
        self._put_entry(Entry(full_path=entry.full_path, attr=attr,
                              chunks=entry.chunks, extended=entry.extended))

    def statfs(self) -> dict:
        return {"f_bsize": 4096, "f_blocks": 1 << 30, "f_bfree": 1 << 29,
                "f_bavail": 1 << 29, "f_files": 1 << 20, "f_ffree": 1 << 19,
                "f_namemax": 255}
