"""FUSE mount over the filer.

Equivalent of weed/mount/ (weedfs.go + per-op files, inode_to_path.go,
meta_cache/, page_writer/).  The op layer (WFS) is kernel-independent
and fully testable in-process; the libfuse2 ctypes bridge
(fuse_bridge.py) wires it to the kernel when /dev/fuse is usable.
"""

from .inode_to_path import InodeToPath
from .meta_cache import MetaCache
from .page_writer import PageWriter
from .weedfs import WFS

__all__ = ["InodeToPath", "MetaCache", "PageWriter", "WFS"]
