"""ctypes bridge: WFS -> libfuse2 high-level API -> kernel.

Equivalent of the kernel boundary the reference crosses via
github.com/hanwen/go-fuse (weed/mount/weedfs.go raw bridge).  The
environment ships libfuse.so.2 (2.9, FUSE_USE_VERSION 26) but no
Python binding, so this binds the high-level path-based API directly:
a fuse_operations struct of ctypes callbacks delegating to a WFS.

Gated: import succeeds everywhere; mount() raises RuntimeError when
libfuse or /dev/fuse is unusable.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
from typing import Optional

from .weedfs import WFS, FuseError

c_off_t = ctypes.c_int64
c_mode_t = ctypes.c_uint32


class Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


class Stat(ctypes.Structure):
    """struct stat, x86-64 linux layout."""
    _fields_ = [
        ("st_dev", ctypes.c_uint64),
        ("st_ino", ctypes.c_uint64),
        ("st_nlink", ctypes.c_uint64),
        ("st_mode", ctypes.c_uint32),
        ("st_uid", ctypes.c_uint32),
        ("st_gid", ctypes.c_uint32),
        ("_pad0", ctypes.c_int),
        ("st_rdev", ctypes.c_uint64),
        ("st_size", ctypes.c_int64),
        ("st_blksize", ctypes.c_int64),
        ("st_blocks", ctypes.c_int64),
        ("st_atim", Timespec),
        ("st_mtim", Timespec),
        ("st_ctim", Timespec),
        ("_reserved", ctypes.c_int64 * 3),
    ]


class StatVfs(ctypes.Structure):
    _fields_ = [
        ("f_bsize", ctypes.c_ulong),
        ("f_frsize", ctypes.c_ulong),
        ("f_blocks", ctypes.c_uint64),
        ("f_bfree", ctypes.c_uint64),
        ("f_bavail", ctypes.c_uint64),
        ("f_files", ctypes.c_uint64),
        ("f_ffree", ctypes.c_uint64),
        ("f_favail", ctypes.c_uint64),
        ("f_fsid", ctypes.c_ulong),
        ("f_flag", ctypes.c_ulong),
        ("f_namemax", ctypes.c_ulong),
        ("_spare", ctypes.c_int * 6),
    ]


class FuseFileInfo(ctypes.Structure):
    """struct fuse_file_info (libfuse 2.9)."""
    _fields_ = [
        ("flags", ctypes.c_int),
        ("fh_old", ctypes.c_ulong),
        ("writepage", ctypes.c_int),
        ("flags_bits", ctypes.c_uint),
        ("fh", ctypes.c_uint64),
        ("lock_owner", ctypes.c_uint64),
    ]


VOIDP = ctypes.c_void_p
CHARP = ctypes.c_char_p

FILL_DIR_T = ctypes.CFUNCTYPE(ctypes.c_int, VOIDP, CHARP,
                              ctypes.POINTER(Stat), c_off_t)

_OP_GETATTR = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, ctypes.POINTER(Stat))
_OP_READLINK = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, CHARP, ctypes.c_size_t)
_OP_MKNOD = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, c_mode_t, ctypes.c_uint64)
_OP_MKDIR = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, c_mode_t)
_OP_PATH = ctypes.CFUNCTYPE(ctypes.c_int, CHARP)
_OP_PATH2 = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, CHARP)
_OP_CHMOD = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, c_mode_t)
_OP_CHOWN = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, ctypes.c_uint32,
                             ctypes.c_uint32)
_OP_TRUNCATE = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, c_off_t)
_OP_UTIME = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, VOIDP)
_OP_OPEN = ctypes.CFUNCTYPE(ctypes.c_int, CHARP,
                            ctypes.POINTER(FuseFileInfo))
# data buffers MUST be void* — declaring them c_char_p makes ctypes hand
# the callback an immutable bytes copy, so memmove would corrupt the heap
_OP_READ = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, VOIDP, ctypes.c_size_t,
                            c_off_t, ctypes.POINTER(FuseFileInfo))
_OP_WRITE = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, VOIDP, ctypes.c_size_t,
                             c_off_t, ctypes.POINTER(FuseFileInfo))
_OP_STATFS = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, ctypes.POINTER(StatVfs))
_OP_FSYNC = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, ctypes.c_int,
                             ctypes.POINTER(FuseFileInfo))
_OP_READDIR = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, VOIDP, FILL_DIR_T,
                               c_off_t, ctypes.POINTER(FuseFileInfo))
_OP_INIT = ctypes.CFUNCTYPE(VOIDP, VOIDP)
_OP_DESTROY = ctypes.CFUNCTYPE(None, VOIDP)
_OP_ACCESS = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, ctypes.c_int)
_OP_CREATE = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, c_mode_t,
                              ctypes.POINTER(FuseFileInfo))
_OP_FTRUNCATE = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, c_off_t,
                                 ctypes.POINTER(FuseFileInfo))
_OP_FGETATTR = ctypes.CFUNCTYPE(ctypes.c_int, CHARP, ctypes.POINTER(Stat),
                                ctypes.POINTER(FuseFileInfo))
_OP_UTIMENS = ctypes.CFUNCTYPE(ctypes.c_int, CHARP,
                               ctypes.POINTER(Timespec * 2))


class FuseOperations(ctypes.Structure):
    """struct fuse_operations, libfuse 2.9 (FUSE_USE_VERSION 26)."""
    _fields_ = [
        ("getattr", _OP_GETATTR),
        ("readlink", _OP_READLINK),
        ("getdir", VOIDP),  # deprecated
        ("mknod", _OP_MKNOD),
        ("mkdir", _OP_MKDIR),
        ("unlink", _OP_PATH),
        ("rmdir", _OP_PATH),
        ("symlink", _OP_PATH2),
        ("rename", _OP_PATH2),
        ("link", _OP_PATH2),
        ("chmod", _OP_CHMOD),
        ("chown", _OP_CHOWN),
        ("truncate", _OP_TRUNCATE),
        ("utime", _OP_UTIME),
        ("open", _OP_OPEN),
        ("read", _OP_READ),
        ("write", _OP_WRITE),
        ("statfs", _OP_STATFS),
        ("flush", _OP_OPEN),
        ("release", _OP_OPEN),
        ("fsync", _OP_FSYNC),
        ("setxattr", VOIDP),
        ("getxattr", VOIDP),
        ("listxattr", VOIDP),
        ("removexattr", VOIDP),
        ("opendir", _OP_OPEN),
        ("readdir", _OP_READDIR),
        ("releasedir", _OP_OPEN),
        ("fsyncdir", _OP_FSYNC),
        ("init", _OP_INIT),
        ("destroy", _OP_DESTROY),
        ("access", _OP_ACCESS),
        ("create", _OP_CREATE),
        ("ftruncate", _OP_FTRUNCATE),
        ("fgetattr", _OP_FGETATTR),
        ("lock", VOIDP),
        ("utimens", _OP_UTIMENS),
        ("bmap", VOIDP),
        ("flags", ctypes.c_uint),  # nullpath_ok etc. bitfields
        ("ioctl", VOIDP),
        ("poll", VOIDP),
        ("write_buf", VOIDP),
        ("read_buf", VOIDP),
        ("flock", VOIDP),
        ("fallocate", VOIDP),
    ]


def _load_libfuse():
    name = ctypes.util.find_library("fuse") or "libfuse.so.2"
    try:
        return ctypes.CDLL(name)
    except OSError as e:
        raise RuntimeError(f"libfuse not available: {e}") from None


class FuseMount:
    """Run a WFS under a kernel mountpoint (weed mount)."""

    def __init__(self, wfs: WFS, mountpoint: str):
        self.wfs = wfs
        self.mountpoint = mountpoint
        self._keepalive: list = []  # callback refs must outlive fuse_main

    # --- op wrappers ------------------------------------------------------
    def _guard(self, fn):
        def wrapper(*args):
            try:
                return fn(*args) or 0
            except FuseError as e:
                return -e.errno
            except Exception:
                return -errno.EIO

        return wrapper

    def _fill_stat(self, st, d: dict) -> None:
        ctypes.memset(ctypes.byref(st), 0, ctypes.sizeof(st))
        st.st_mode = d["st_mode"]
        st.st_size = d["st_size"]
        st.st_nlink = d["st_nlink"]
        st.st_uid = d["st_uid"]
        st.st_gid = d["st_gid"]
        st.st_mtim.tv_sec = int(d["st_mtime"])
        st.st_ctim.tv_sec = int(d["st_ctime"])
        st.st_atim.tv_sec = int(d["st_mtime"])
        st.st_blksize = 4096
        st.st_blocks = (d["st_size"] + 511) // 512

    def _build_ops(self) -> FuseOperations:
        wfs = self.wfs
        ops = FuseOperations()

        @self._guard
        def op_getattr(path, stp):
            self._fill_stat(stp.contents, wfs.getattr(path.decode()))

        @self._guard
        def op_readdir(path, buf, fill, off, fi):
            fill(buf, b".", None, 0)
            fill(buf, b"..", None, 0)
            for e in wfs.readdir(path.decode()):
                name = e.name.encode()
                if name:  # an empty dirent name EIOs the whole listing
                    fill(buf, name, None, 0)

        @self._guard
        def op_mkdir(path, mode):
            wfs.mkdir(path.decode(), mode)

        @self._guard
        def op_unlink(path):
            wfs.unlink(path.decode())

        @self._guard
        def op_rmdir(path):
            wfs.rmdir(path.decode())

        @self._guard
        def op_rename(old, new):
            wfs.rename(old.decode(), new.decode())

        @self._guard
        def op_link(target, link):
            wfs.link(target.decode(), link.decode())

        @self._guard
        def op_chmod(path, mode):
            wfs.setattr(path.decode(), mode=mode)

        @self._guard
        def op_chown(path, uid, gid):
            wfs.setattr(path.decode(), uid=uid, gid=gid)

        @self._guard
        def op_truncate(path, size):
            wfs.truncate(path.decode(), size)

        @self._guard
        def op_ftruncate(path, size, fi):
            wfs.flush(fi.contents.fh)
            wfs.truncate(path.decode(), size)

        @self._guard
        def op_utimens(path, times):
            mtime = None
            if times:
                ts = times.contents[1]
                mtime = ts.tv_sec + ts.tv_nsec / 1e9
            wfs.setattr(path.decode(), mtime=mtime)

        @self._guard
        def op_open(path, fi):
            fi.contents.fh = wfs.open(path.decode()).fh

        @self._guard
        def op_create(path, mode, fi):
            fi.contents.fh = wfs.create(path.decode(), mode).fh

        @self._guard
        def op_read(path, buf, size, off, fi):
            data = wfs.read(fi.contents.fh, off, size)
            ctypes.memmove(buf, data, len(data))
            return len(data)

        @self._guard
        def op_write(path, buf, size, off, fi):
            data = ctypes.string_at(buf, size)
            return wfs.write(fi.contents.fh, off, data)

        @self._guard
        def op_flush(path, fi):
            wfs.flush(fi.contents.fh)

        @self._guard
        def op_release(path, fi):
            wfs.release(fi.contents.fh)

        @self._guard
        def op_fsync(path, datasync, fi):
            wfs.flush(fi.contents.fh)

        @self._guard
        def op_statfs(path, sv):
            d = wfs.statfs()
            ctypes.memset(ctypes.byref(sv.contents), 0,
                          ctypes.sizeof(sv.contents))
            for k, v in d.items():
                if hasattr(sv.contents, k):
                    setattr(sv.contents, k, v)
            sv.contents.f_frsize = d["f_bsize"]

        @self._guard
        def op_access(path, mask):
            wfs.getattr(path.decode())

        @self._guard
        def op_opendir(path, fi):
            pass

        @self._guard
        def op_releasedir(path, fi):
            pass

        assigns = [
            ("getattr", _OP_GETATTR(op_getattr)),
            ("readdir", _OP_READDIR(op_readdir)),
            ("mkdir", _OP_MKDIR(op_mkdir)),
            ("unlink", _OP_PATH(op_unlink)),
            ("rmdir", _OP_PATH(op_rmdir)),
            ("rename", _OP_PATH2(op_rename)),
            ("link", _OP_PATH2(op_link)),
            ("chmod", _OP_CHMOD(op_chmod)),
            ("chown", _OP_CHOWN(op_chown)),
            ("truncate", _OP_TRUNCATE(op_truncate)),
            ("ftruncate", _OP_FTRUNCATE(op_ftruncate)),
            ("utimens", _OP_UTIMENS(op_utimens)),
            ("open", _OP_OPEN(op_open)),
            ("create", _OP_CREATE(op_create)),
            ("read", _OP_READ(op_read)),
            ("write", _OP_WRITE(op_write)),
            ("flush", _OP_OPEN(op_flush)),
            ("release", _OP_OPEN(op_release)),
            ("fsync", _OP_FSYNC(op_fsync)),
            ("statfs", _OP_STATFS(op_statfs)),
            ("access", _OP_ACCESS(op_access)),
            ("opendir", _OP_OPEN(op_opendir)),
            ("releasedir", _OP_OPEN(op_releasedir)),
        ]
        for name, cb in assigns:
            setattr(ops, name, cb)
            self._keepalive.append(cb)
        return ops

    def run(self, foreground: bool = True, allow_other: bool = False,
            debug: bool = False) -> int:
        """Blocks in fuse_main until unmounted (fusermount -u)."""
        lib = _load_libfuse()
        ops = self._build_ops()
        args = [b"weed-mount", self.mountpoint.encode(), b"-s"]
        if foreground:
            args.append(b"-f")
        if debug:
            args.append(b"-d")
        opts = [b"big_writes", b"default_permissions"]
        if allow_other:
            opts.append(b"allow_other")
        args += [b"-o", b",".join(opts)]
        argv = (ctypes.c_char_p * len(args))(*args)
        return lib.fuse_main_real(len(args), argv, ctypes.byref(ops),
                                  ctypes.sizeof(ops), None)


def mount(filer_url: str, mountpoint: str, filer_path: str = "/",
          collection: str = "", replication: str = "",
          chunk_size_mb: int = 8, allow_other: bool = False,
          debug: bool = False) -> int:
    wfs = WFS(filer_url, filer_path, chunk_size_mb=chunk_size_mb,
              collection=collection, replication=replication)
    try:
        return FuseMount(wfs, mountpoint).run(
            foreground=True, allow_other=allow_other, debug=debug)
    finally:
        wfs.close()
