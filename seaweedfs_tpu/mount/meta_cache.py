"""Local metadata cache for the mount, invalidated by the filer's meta
subscription.

Equivalent of weed/mount/meta_cache/ (meta_cache.go + subscription
invalidation): directory listings and entry stats are cached locally;
a background tailer of /api/meta/log (the reference's SubscribeMetadata
stream) applies remote mutations so other clients' changes become
visible without re-statting.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..filer.entry import Entry
from ..utils.bounded_tree import BoundedTree
from ..utils.httpd import http_json


class MetaCache:
    def __init__(self, filer_url: str, poll_interval: float = 0.5):
        self.filer_url = filer_url
        self.poll_interval = poll_interval
        self._lock = threading.Lock()
        self._entries: dict[str, Entry] = {}
        # bounded: least-recently-listed dirs are forgotten and re-list
        self._listed_dirs = BoundedTree(limit=100_000)
        self._since_ns = time.time_ns()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.invalidation_fn: Optional[Callable[[str], None]] = None

    # --- cache ops --------------------------------------------------------
    def get(self, path: str) -> Optional[Entry]:
        with self._lock:
            return self._entries.get(path)

    def put(self, entry: Entry) -> None:
        with self._lock:
            self._entries[entry.full_path] = entry

    def delete(self, path: str) -> None:
        with self._lock:
            self._entries.pop(path, None)
        self._listed_dirs.ensure_invalidated(path)

    def mark_listed(self, dir_path: str) -> None:
        self._listed_dirs.mark_visited(dir_path)

    def is_listed(self, dir_path: str) -> bool:
        return self._listed_dirs.has_visited(dir_path)

    def list_cached(self, dir_path: str) -> list[Entry]:
        prefix = dir_path.rstrip("/") + "/"
        with self._lock:
            # p != dir_path: "/" starts with its own prefix "/", so a
            # cached ROOT entry would list itself as a nameless child —
            # an empty dirent name makes the kernel fail getdents with
            # EIO (found by the kernel-boundary mount test)
            return sorted(
                (e for p, e in self._entries.items()
                 if p != dir_path and p.startswith(prefix)
                 and "/" not in p[len(prefix):]),
                key=lambda e: e.full_path)

    # --- subscription (meta_cache_subscribe.go) ---------------------------
    def apply_event(self, event: dict) -> None:
        old, new = event.get("old_entry"), event.get("new_entry")
        with self._lock:
            if old:
                self._entries.pop(old["full_path"], None)
            if new:
                e = Entry.from_dict(new)
                # only refresh paths we already track, or children of
                # dirs we have fully listed (others fault in on lookup)
                parent = e.parent
                if e.full_path in self._entries \
                        or self._listed_dirs.has_visited(parent):
                    self._entries[e.full_path] = e
        for ent in (old, new):
            if ent and self.invalidation_fn:
                try:
                    self.invalidation_fn(ent["full_path"])
                except Exception:
                    pass

    def _tail_loop(self) -> None:
        # the tail cursor lives on this thread's stack after start():
        # no other thread needs it, so there is no shared field to race
        since_ns = self._since_ns
        while not self._stop.is_set():
            try:
                r = http_json(
                    "GET", f"http://{self.filer_url}/api/meta/log?"
                    f"since_ns={since_ns}", timeout=30.0)
                for ev in r["events"]:
                    self.apply_event(ev)
                since_ns = r["next_ns"]
            except Exception:
                pass
            self._stop.wait(self.poll_interval)

    def start(self) -> "MetaCache":
        self._thread = threading.Thread(target=self._tail_loop, daemon=True,
                                        name="mount-meta-cache")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
