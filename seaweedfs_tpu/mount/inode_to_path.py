"""Inode <-> path bimap for the FUSE low-level protocol.

Equivalent of weed/mount/inode_to_path.go: paths get stable inode
numbers (root=1); renames move the path under the same inode; forget
drops entries when the kernel's lookup count reaches zero.
"""

from __future__ import annotations

import threading

ROOT_INODE = 1


class InodeEntry:
    __slots__ = ("paths", "nlookup", "is_directory")

    def __init__(self, path: str, is_directory: bool):
        self.paths = [path]
        self.nlookup = 1
        self.is_directory = is_directory


class InodeToPath:
    def __init__(self, root: str = "/"):
        self._lock = threading.Lock()
        self._path2inode: dict[str, int] = {root: ROOT_INODE}
        self._inode2entry: dict[int, InodeEntry] = {
            ROOT_INODE: InodeEntry(root, True)}
        self._next = ROOT_INODE + 1

    def lookup(self, path: str, is_directory: bool = False) -> int:
        """Assign (or bump) the inode for a path (inode_to_path.go Lookup)."""
        with self._lock:
            ino = self._path2inode.get(path)
            if ino is not None:
                self._inode2entry[ino].nlookup += 1
                return ino
            ino = self._next
            self._next += 1
            self._path2inode[path] = ino
            self._inode2entry[ino] = InodeEntry(path, is_directory)
            return ino

    def get_path(self, inode: int) -> str:
        with self._lock:
            entry = self._inode2entry.get(inode)
            if entry is None or not entry.paths:
                raise KeyError(f"inode {inode} not found")
            return entry.paths[0]

    def get_inode(self, path: str) -> int:
        with self._lock:
            ino = self._path2inode.get(path)
            if ino is None:
                raise KeyError(f"path {path} has no inode")
            return ino

    def has_path(self, path: str) -> bool:
        with self._lock:
            return path in self._path2inode

    def move_path(self, old: str, new: str) -> None:
        """Rename keeps the inode stable (inode_to_path.go MovePath)."""
        with self._lock:
            ino = self._path2inode.pop(old, None)
            if ino is None:
                return
            # target may already have an inode (overwrite): drop it
            displaced = self._path2inode.pop(new, None)
            if displaced is not None and displaced != ino:
                self._inode2entry.pop(displaced, None)
            self._path2inode[new] = ino
            entry = self._inode2entry[ino]
            entry.paths = [new if p == old else p for p in entry.paths]

    def remove_path(self, path: str) -> None:
        with self._lock:
            ino = self._path2inode.pop(path, None)
            if ino is not None:
                self._inode2entry.pop(ino, None)

    def forget(self, inode: int, nlookup: int) -> None:
        """Kernel forget: drop when the lookup count hits zero."""
        with self._lock:
            entry = self._inode2entry.get(inode)
            if entry is None:
                return
            entry.nlookup -= nlookup
            if entry.nlookup <= 0 and inode != ROOT_INODE:
                self._inode2entry.pop(inode, None)
                for p in entry.paths:
                    if self._path2inode.get(p) == inode:
                        self._path2inode.pop(p, None)
