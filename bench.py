#!/usr/bin/env python
"""Headline benchmark: RS(10,4) ec.encode throughput per chip.

Prints ONE JSON line:
  value       = sustained TPU encode throughput with data resident in HBM
                (MB of volume data encoded per second; the chip-side number a
                production pipeline with overlapped IO converges to)
  vs_baseline = value / CPU-SIMD engine throughput on this host (the
                equivalent of the reference's klauspost/reedsolomon AVX2
                path, which SeaweedFS publishes no EC numbers for —
                BASELINE.json.published = {})

detail carries every sub-measurement, including the honest end-to-end
number through this environment's host<->chip tunnel (device_get here runs
at ~13 MB/s, which bounds any tunneled e2e figure; on directly-attached
TPU hosts the PCIe path is 3 orders of magnitude faster).

Methodology: the TPU kernel is timed as one jitted fori_loop of N
data-dependent encodes (each iteration XOR-perturbs the input and the
parity folds into a scalar), so per-dispatch tunnel latency and lazy
dispatch cannot distort the figure.
"""

from __future__ import annotations

import json
import time

import numpy as np


def time_cpu(engine, data, reps=3):
    from seaweedfs_tpu.ec.codec import ReedSolomon

    rs = ReedSolomon(10, 4, engine=engine)
    rs.encode(data[:, :1024])  # warm tables
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        rs.encode(data)
        best = min(best, time.perf_counter() - t0)
    return data.nbytes / best / 1e6


def main():
    import jax
    import jax.numpy as jnp

    from seaweedfs_tpu.ec.codec import CpuEngine, ReedSolomon, best_cpu_engine
    from seaweedfs_tpu.ec.gf256 import parity_rows
    from seaweedfs_tpu.ops.gf_matmul import (
        TpuEngine,
        expand_matrix_bitplanes,
        gf_matmul_pallas,
        gf_matmul_xla,
    )

    rng = np.random.default_rng(0xBE)
    detail: dict = {"device": str(jax.devices()[0]), "backend": jax.default_backend()}

    # --- CPU baselines ----------------------------------------------------
    cpu_data = rng.integers(0, 256, (10, 1 << 24), dtype=np.uint8)  # 160MB
    simd = best_cpu_engine()
    detail["cpu_engine"] = simd.name
    cpu_simd_mbps = time_cpu(simd, cpu_data)
    detail["cpu_simd_mbps"] = round(cpu_simd_mbps, 1)
    detail["cpu_numpy_mbps"] = round(time_cpu(CpuEngine(), cpu_data, reps=1), 1)

    # --- TPU in-HBM sustained --------------------------------------------
    # The Pallas kernel never materializes the 8x bit expansion in HBM, so
    # the sustained loop runs on a full 640MB-resident encode; the XLA-fused
    # variant (which does materialize bits) is measured at a smaller size.
    a_planes = jnp.asarray(expand_matrix_bitplanes(parity_rows(10, 4)))

    # block_until_ready is not reliably synchronous through the remote-chip
    # tunnel, so completion is forced by device_get of a scalar that depends
    # on every parity byte, and the fixed tunnel latency cancels by
    # differencing two iteration counts (slope = time per iteration).
    def make_loop(encode, n):
        @jax.jit
        def bench_loop(a, d):
            def body(i, acc):
                di = d ^ i.astype(jnp.uint8)
                p = encode(a, di)
                return acc + p.astype(jnp.uint32).sum()

            return jax.lax.fori_loop(0, n, body, jnp.uint32(0))

        return bench_loop

    def run_loop(encode, b, n_lo=10, n_hi=40, planes=None):
        planes = a_planes if planes is None else planes
        data = jax.device_put(rng.integers(0, 256, (10, b), dtype=np.uint8))
        data.block_until_ready()
        times = {}
        for n in (n_lo, n_hi):
            loop = make_loop(encode, n)
            jax.device_get(loop(planes, data))  # compile + warm
            t0 = time.perf_counter()
            jax.device_get(loop(planes, data))
            times[n] = time.perf_counter() - t0
        per_iter = (times[n_hi] - times[n_lo]) / (n_hi - n_lo)
        return data.nbytes / per_iter / 1e6

    tpu_hbm_mbps = run_loop(gf_matmul_pallas, 1 << 26)  # 640MB resident
    detail["tpu_inhbm_pallas_mbps"] = round(tpu_hbm_mbps, 1)
    detail["tpu_inhbm_xla_mbps"] = round(run_loop(gf_matmul_xla, 1 << 23), 1)

    # single-shard rebuild latency, 1GB volume: shards are 100MB, decode of
    # the missing one is a [8,80]x[80,100M] matmul over the 10 survivors
    shard_b = 100 * (1 << 20)
    dec_planes = jnp.asarray(expand_matrix_bitplanes(parity_rows(10, 1)))
    dec_mbps = run_loop(gf_matmul_pallas, shard_b, n_lo=4, n_hi=12,
                        planes=dec_planes)
    detail["rebuild_1gb_inhbm_ms"] = round(10 * shard_b / (dec_mbps * 1e6) * 1e3, 2)

    # --- parity check + tunneled e2e -------------------------------------
    sample = rng.integers(0, 256, (10, 1 << 22), dtype=np.uint8)  # 40MB
    want = ReedSolomon(10, 4, engine=simd).encode(sample)
    rs_xla = ReedSolomon(10, 4, engine=TpuEngine(mode="xla"))
    rs_xla.encode(sample)  # untimed warm-up: jit compile happens here
    t0 = time.perf_counter()
    got_xla = rs_xla.encode(sample)
    e2e_dt = time.perf_counter() - t0
    got_pallas = ReedSolomon(10, 4, engine=TpuEngine(mode="pallas")).encode(sample)
    parity_match = bool(np.array_equal(want, got_xla) and np.array_equal(want, got_pallas))
    detail["parity_match_cpu_xla_pallas"] = parity_match
    detail["tpu_e2e_tunneled_mbps"] = round(sample.nbytes / e2e_dt / 1e6, 1)
    detail["note"] = ("value is in-HBM sustained; e2e here is bounded by the "
                      "dev-tunnel's ~13MB/s device_get readback")

    value = round(tpu_hbm_mbps, 1)
    print(json.dumps({
        "metric": "ec.encode MB/s/chip (RS(10,4), in-HBM sustained)",
        "value": value,
        "unit": "MB/s",
        "vs_baseline": round(value / cpu_simd_mbps, 2),
        "detail": detail,
    }))


if __name__ == "__main__":
    main()
